// Host-side buffer utilities — the in-tree native component.
//
// Reference anchor: the reference's only in-tree native code is its NCCL
// Cython binding plus the pinned-host/device staging buffers of
// REF:chainermn/communicators/_memory_utility.py (pack_params/unpack_params:
// gather every parameter into one contiguous buffer, scatter back).  On TPU,
// XLA owns device memory and the collectives, so the native seam moves to
// the host side of the pipeline, where Python is the bottleneck:
//
//   * gatherv/scatterv — pack N (possibly ragged) host buffers into one
//     contiguous buffer and back with a thread pool (the pack_params idea
//     applied where it still matters: batch assembly in
//     datasets.toy.batch_iterator and checkpoint payload packing in
//     extensions.checkpoint are memcpy-bound, and numpy copies are
//     single-threaded under the GIL; ctypes releases the GIL for the whole
//     call).
//   * crc32c — checkpoint shard integrity (written at save, verified at
//     load, extensions/checkpoint.py) and the collective-order debug mode
//     (SURVEY §5.2, utils/debug.py).
//   * a ring queue — bounded MPMC byte-buffer queue; stages the checkpoint
//     payload chunks between the packing thread and the file-writer thread
//     (the host-staging analogue of HostPinnedMemory's double buffering).
//
// Built with: g++ -O3 -shared -fPIC -std=c++17 -o libhostbuf.so hostbuf.cpp -lpthread
// (matches utils/native.py's build line; the SSE4.2 crc path uses a
// per-function target attribute, so no -march flag is needed)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// crc32c (Castagnoli).  Two implementations behind one entry point:
//   * hardware: SSE4.2 CRC32 instruction (8 bytes/op), compiled with a
//     per-function target attribute so the library itself needs no
//     -march flags, selected by a __builtin_cpu_supports("sse4.2")
//     runtime check (x86 only);
//   * software: slicing-by-8 table walk — the portable fallback, and the
//     same table construction utils/native.py's pure-Python fallback
//     mirrors bit-for-bit.
// hostbuf_crc32c_impl() reports which path is active so benchmarks and
// docs can say what was actually measured.
// ---------------------------------------------------------------------------
static uint32_t crc32c_tables[8][256];
static std::atomic<bool> crc_table_ready{false};
static std::mutex crc_table_mu;

static void crc32c_init_table() {
  std::lock_guard<std::mutex> lock(crc_table_mu);
  if (crc_table_ready.load()) return;
  const uint32_t poly = 0x82f63b78u;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++)
      crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    crc32c_tables[0][i] = crc;
  }
  for (int k = 1; k < 8; k++)
    for (uint32_t i = 0; i < 256; i++)
      crc32c_tables[k][i] = (crc32c_tables[k - 1][i] >> 8) ^
                            crc32c_tables[0][crc32c_tables[k - 1][i] & 0xff];
  crc_table_ready.store(true);
}

static uint32_t crc32c_sw(const uint8_t* data, uint64_t len, uint32_t crc) {
  const uint32_t (*t)[256] = crc32c_tables;
  uint64_t i = 0;
  for (; i + 8 <= len; i += 8) {
    crc ^= (uint32_t)data[i] | ((uint32_t)data[i + 1] << 8) |
           ((uint32_t)data[i + 2] << 16) | ((uint32_t)data[i + 3] << 24);
    crc = t[7][crc & 0xff] ^ t[6][(crc >> 8) & 0xff] ^
          t[5][(crc >> 16) & 0xff] ^ t[4][(crc >> 24) & 0xff] ^
          t[3][data[i + 4]] ^ t[2][data[i + 5]] ^
          t[1][data[i + 6]] ^ t[0][data[i + 7]];
  }
  for (; i < len; i++)
    crc = (crc >> 8) ^ t[0][(crc ^ data[i]) & 0xff];
  return crc;
}

#if defined(__x86_64__) || defined(__i386__)
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(const uint8_t* data, uint64_t len, uint32_t crc) {
  uint64_t i = 0;
#if defined(__x86_64__)
  uint64_t crc64 = crc;
  for (; i + 8 <= len; i += 8) {
    uint64_t word;
    std::memcpy(&word, data + i, 8);
    crc64 = __builtin_ia32_crc32di(crc64, word);
  }
  crc = (uint32_t)crc64;
#endif
  for (; i < len; i++)
    crc = __builtin_ia32_crc32qi(crc, data[i]);
  return crc;
}

static bool crc32c_have_hw() {
  static const bool have = __builtin_cpu_supports("sse4.2");
  return have;
}
#else
static bool crc32c_have_hw() { return false; }
static uint32_t crc32c_hw(const uint8_t* d, uint64_t l, uint32_t c) {
  return crc32c_sw(d, l, c);
}
#endif

// 1 = hardware CRC32 instruction, 0 = software slicing-by-8.
int hostbuf_crc32c_impl() { return crc32c_have_hw() ? 1 : 0; }

uint32_t hostbuf_crc32c(const uint8_t* data, uint64_t len, uint32_t seed) {
  uint32_t crc = ~seed;
  if (crc32c_have_hw()) {
    crc = crc32c_hw(data, len, crc);
  } else {
    if (!crc_table_ready.load()) crc32c_init_table();
    crc = crc32c_sw(data, len, crc);
  }
  return ~crc;
}

// ---------------------------------------------------------------------------
// gatherv / scatterv: pack N variable-size source buffers into a contiguous
// destination at caller-computed offsets, and the inverse.  The pack_params/
// unpack_params idea (REF:chainermn/communicators/_memory_utility.py) applied
// where it still pays on a TPU host: batch assembly (equal sizes) and
// checkpoint payload packing (ragged leaf sizes) are memcpy-bound, and numpy
// copies run single-threaded under the GIL while ctypes releases it for the
// whole call.
// ---------------------------------------------------------------------------
static void run_copies(uint64_t n_items, int n_threads,
                       const std::function<void(uint64_t)>& copy_one,
                       uint64_t total_bytes) {
  // Threading only pays past ~1 MiB of copies; below that, pool start-up
  // dominates.
  if (n_threads <= 1 || n_items < 2 || total_bytes < (1u << 20)) {
    for (uint64_t i = 0; i < n_items; i++) copy_one(i);
    return;
  }
  std::vector<std::thread> pool;
  std::atomic<uint64_t> next{0};
  for (int t = 0; t < n_threads; t++) {
    pool.emplace_back([&]() {
      for (;;) {
        uint64_t i = next.fetch_add(1);
        if (i >= n_items) return;
        copy_one(i);
      }
    });
  }
  for (auto& th : pool) th.join();
}

void hostbuf_gatherv(uint8_t* dst, const uint8_t** srcs,
                     const uint64_t* sizes, const uint64_t* offsets,
                     uint64_t n_items, int n_threads) {
  uint64_t total = 0;
  for (uint64_t i = 0; i < n_items; i++) total += sizes[i];
  run_copies(
      n_items, n_threads,
      [&](uint64_t i) { std::memcpy(dst + offsets[i], srcs[i], sizes[i]); },
      total);
}

void hostbuf_scatterv(const uint8_t* src, uint8_t** dsts,
                      const uint64_t* sizes, const uint64_t* offsets,
                      uint64_t n_items, int n_threads) {
  uint64_t total = 0;
  for (uint64_t i = 0; i < n_items; i++) total += sizes[i];
  run_copies(
      n_items, n_threads,
      [&](uint64_t i) { std::memcpy(dsts[i], src + offsets[i], sizes[i]); },
      total);
}

// ---------------------------------------------------------------------------
// Bounded MPMC ring queue of byte buffers (prefetch pipeline)
// ---------------------------------------------------------------------------
struct RingQueue {
  std::queue<std::vector<uint8_t>> q;
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  size_t capacity;
  bool closed = false;
};

void* hostbuf_queue_new(uint64_t capacity) {
  auto* rq = new RingQueue();
  rq->capacity = capacity ? capacity : 1;
  return rq;
}

// Returns 0 on success, -1 if the queue is closed.
int hostbuf_queue_push(void* handle, const uint8_t* data, uint64_t len) {
  auto* rq = static_cast<RingQueue*>(handle);
  std::unique_lock<std::mutex> lock(rq->mu);
  rq->not_full.wait(lock,
                    [&] { return rq->q.size() < rq->capacity || rq->closed; });
  if (rq->closed) return -1;
  rq->q.emplace(data, data + len);
  rq->not_empty.notify_one();
  return 0;
}

// Returns the popped size, 0 if closed-and-empty. Caller provides dst with
// max_len capacity; oversized payloads are truncated (caller sizes buffers).
uint64_t hostbuf_queue_pop(void* handle, uint8_t* dst, uint64_t max_len) {
  auto* rq = static_cast<RingQueue*>(handle);
  std::unique_lock<std::mutex> lock(rq->mu);
  rq->not_empty.wait(lock, [&] { return !rq->q.empty() || rq->closed; });
  if (rq->q.empty()) return 0;
  auto& front = rq->q.front();
  uint64_t n = front.size() < max_len ? front.size() : max_len;
  std::memcpy(dst, front.data(), n);
  rq->q.pop();
  rq->not_full.notify_one();
  return n;
}

uint64_t hostbuf_queue_size(void* handle) {
  auto* rq = static_cast<RingQueue*>(handle);
  std::lock_guard<std::mutex> lock(rq->mu);
  return rq->q.size();
}

void hostbuf_queue_close(void* handle) {
  auto* rq = static_cast<RingQueue*>(handle);
  std::lock_guard<std::mutex> lock(rq->mu);
  rq->closed = true;
  rq->not_empty.notify_all();
  rq->not_full.notify_all();
}

void hostbuf_queue_free(void* handle) {
  delete static_cast<RingQueue*>(handle);
}

}  // extern "C"
