#!/usr/bin/env python
"""Long-context causal language-model training — the net-new capability the
reference never had (SURVEY §5.7: sequence parallelism ABSENT upstream).

Composable long-context stack, selectable per flag:

* ``--sp none``  + flash attention: one chip holds the whole sequence; the
  Pallas flash kernel (ops.flash_attention) streams KV blocks through VMEM
  with online softmax — O(S) memory, ~18x faster than materialized-logits
  attention at S=8192/bf16 on a v5e-class chip.
* ``--sp ring``: the sequence dimension is sharded over the mesh's
  ``intra`` axis; K/V blocks rotate between chips via ``lax.ppermute``
  (parallel.ring_attention) with the same online-softmax accumulation —
  context length scales with the number of chips.
* ``--sp ulysses``: all-to-all swaps the sharded dimension seq<->heads
  around a local full attention (parallel.ulysses).

Mesh layout: ``inter`` = data parallel, ``intra`` = sequence parallel.
Each batch element's tokens are split into ``intra`` contiguous shards;
``position_offset`` keeps rotary/sinusoidal positions globally correct.

Training signal: synthetic successor sequences (next token = current + 1
mod vocab, random start), so the LM's loss collapses quickly — a
correctness canary, not a benchmark.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.models.transformer import TransformerLM
from chainermn_tpu.ops import make_flash_attention_fn
from chainermn_tpu.parallel.ring_attention import make_ring_attention_fn
from chainermn_tpu.parallel.ulysses import make_ulysses_attention_fn
from chainermn_tpu.utils.profiling import sync


def successor_batch(rng, batch, seq_len, vocab):
    start = rng.randint(0, vocab, size=(batch, 1))
    seq = (start + np.arange(seq_len)[None, :]) % vocab
    return seq.astype(np.int32)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--batchsize", type=int, default=8, help="global batch")
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--kv-heads", type=int, default=None,
                   help="GQA/MQA: K/V head count (divides --n-heads; "
                        "1 = MQA; default = MHA).  The flash kernel and "
                        "all --sp modes consume the reduced heads "
                        "natively — ring/zigzag rotate only the reduced "
                        "KV blocks")
    p.add_argument("--d-ff", type=int, default=1024)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--steps-per-epoch", type=int, default=20)
    p.add_argument("--sp", choices=["none", "ring", "zigzag", "ulysses"],
                   default="none",
                   help="sequence parallelism over the 'intra' mesh axis "
                   "(zigzag = load-balanced causal ring, half ring's FLOPs)")
    p.add_argument("--no-flash", action="store_true",
                   help="disable the Pallas flash kernel (sp=none only)")
    p.add_argument("--window", type=int, default=None,
                   help="sliding-window (local) attention size.  --sp "
                        "none: the flash kernel skips whole tiles "
                        "outside the band (O(S*window) compute); ring: "
                        "the global-position block masks carry the band "
                        "across shard boundaries; ulysses: full "
                        "sequence per chip after the head all-to-all.  "
                        "zigzag rejects it (its schedule derives from "
                        "full causality)")
    p.add_argument("--dtype", choices=["float32", "bfloat16"],
                   default="bfloat16")
    p.add_argument("--dp", type=int, default=None,
                   help="data-parallel ways (inter axis); rest is sequence")
    p.add_argument("--vocab-tp", action="store_true",
                   help="vocab-parallel (Megatron-style) embedding + "
                        "cross-entropy over the sequence axis: the table "
                        "and the LM-head logits stay sharded V/n per "
                        "device (parallel.sharding.vocab_parallel_*); "
                        "needs --sp != none and vocab %% sp ways == 0")
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable fault tolerance: save/auto-resume via the "
                   "multi-node checkpointer (maybe_load on relaunch)")
    p.add_argument("--checkpoint-every", type=int, default=10,
                   help="save a generation every N steps")
    p.add_argument("--checkpoint-name", default="long_context")
    p.add_argument("--packed", action="store_true",
                   help="packed-sequence training: two documents per row, "
                   "segment ids keep attention inside document boundaries "
                   "through EVERY backend (flash kernel masks, rotating "
                   "ring/zigzag KV ids, ulysses all-gathered ids)")
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator("xla_ici", inter_size=args.dp)
    dp, sp_ways = comm.inter_size, comm.intra_size
    S, B, vocab = args.seq_len, args.batchsize, args.vocab
    dtype = jnp.dtype(args.dtype)

    if args.packed and args.sp == "none" and args.no_flash:
        raise SystemExit(
            "--packed with --sp none needs the flash kernel's segment "
            "masks: drop --no-flash"
        )

    # Packed-sequence training: two documents per row at the S/2
    # boundary.  Row-uniform (S,) segment ids (every row shares the
    # boundary) thread through EVERY attention backend — the flash
    # kernel's segment masks (sp=none), rotating KV ids (ring/zigzag),
    # or the all-gathered ids around the local kernel (ulysses).
    seg_row = (
        jnp.asarray((np.arange(S) >= S // 2).astype(np.int32))
        if args.packed else None
    )

    if args.window is not None and (
        args.sp == "zigzag" or (args.sp == "none" and args.no_flash)
    ):
        raise SystemExit("--window: supported with --sp none (flash "
                         "kernel band), ring (global-position band), or "
                         "ulysses (full sequence after the head "
                         "all-to-all); zigzag's chunk schedule is "
                         "derived from FULL causality and would need "
                         "its own banded block selection")
    if args.sp == "none":
        if args.packed:
            attention_fn = make_flash_attention_fn(
                q_segment_ids=seg_row, window=args.window
            )
        else:
            attention_fn = (
                None if args.no_flash
                else make_flash_attention_fn(window=args.window)
            )
        sp_ways_eff = 1
    elif args.sp == "ring":
        attention_fn = make_ring_attention_fn(
            "intra", segment_ids=seg_row, window=args.window
        )
        sp_ways_eff = sp_ways
    elif args.sp == "zigzag":
        from chainermn_tpu.parallel.ring_attention import (
            make_zigzag_ring_attention_fn,
            zigzag_indices as _zz,
        )

        zz_seg = (
            seg_row[np.asarray(_zz(S, sp_ways))]
            if args.packed else None
        )
        attention_fn = make_zigzag_ring_attention_fn(
            "intra", segment_ids=zz_seg
        )
        sp_ways_eff = sp_ways
    else:
        attention_fn = make_ulysses_attention_fn(
            "intra", segment_ids=seg_row, window=args.window
        )
        sp_ways_eff = sp_ways
    if args.sp != "none" and sp_ways == 1:
        raise SystemExit(
            "sequence parallelism needs intra_size > 1; pass --dp to leave "
            "devices on the intra axis (e.g. --dp 1)"
        )
    if args.vocab_tp:
        if args.sp == "none":
            raise SystemExit("--vocab-tp shards over the sequence axis; "
                             "pick an --sp mode")
        if vocab % sp_ways:
            raise SystemExit(f"--vocab-tp needs vocab ({vocab}) divisible "
                             f"by sp ways ({sp_ways})")
        if args.checkpoint_dir:
            raise SystemExit("--vocab-tp + --checkpoint-dir is not wired "
                             "up in this example yet")
    if S % max(sp_ways_eff, 1):
        raise SystemExit(f"--seq-len {S} must divide by sp ways {sp_ways_eff}")
    if args.sp == "zigzag" and S % (2 * sp_ways):
        raise SystemExit(
            f"--sp zigzag needs --seq-len divisible by 2*sp ways "
            f"({2 * sp_ways}); got {S}"
        )
    if args.sp == "ulysses" and args.n_heads % sp_ways:
        # Only ulysses reshapes heads across the axis; ring/zigzag shard
        # the sequence and accept any head count.
        raise SystemExit("--sp ulysses needs n_heads % sp ways == 0")
    if args.kv_heads is not None:
        if args.n_heads % args.kv_heads:
            raise SystemExit("--kv-heads must divide --n-heads")
        if args.sp == "ulysses" and args.kv_heads % sp_ways:
            raise SystemExit("--sp ulysses needs kv_heads % sp ways == 0")

    model = TransformerLM(
        vocab=vocab, d_model=args.d_model, n_heads=args.n_heads,
        d_ff=args.d_ff, n_layers=args.layers, max_len=S, dtype=dtype,
        attention_fn=attention_fn, n_kv_heads=args.kv_heads,
    )
    S_local = S // max(sp_ways_eff, 1)
    tok0 = jnp.zeros((1, S_local), jnp.int32)
    # Init with a dense twin: parameters don't depend on attention_fn, and
    # the ring/ulysses fns need their mesh axis bound (shard_map) to trace.
    init_model = TransformerLM(
        vocab=vocab, d_model=args.d_model, n_heads=args.n_heads,
        d_ff=args.d_ff, n_layers=args.layers, max_len=S, dtype=dtype,
        attention_fn=None, n_kv_heads=args.kv_heads,
    )
    params = init_model.init(jax.random.PRNGKey(0), tok0)
    params = jax.tree.map(lambda x: x.astype(jnp.float32), params)

    opt = optax.adamw(args.lr, weight_decay=0.01)
    opt_state = opt.init(params)
    if comm.rank == 0:
        n_params = sum(l.size for l in jax.tree.leaves(params))
        print(f"mesh: data={dp} x seq={sp_ways}; sp={args.sp} "
              f"flash={args.sp == 'none' and not args.no_flash} "
              f"params={n_params/1e6:.1f}M seq_len={S}")

    # Predicted positions: each packed document loses its final token.
    denom = B * (S - 2) if args.packed else B * (S - 1)
    # THE per-document position rule, shared by every path: positions
    # restart at the packing boundary (plain global order otherwise).
    base_pos_np = (
        np.concatenate([np.arange(S // 2)] * 2).astype(np.int32)
        if args.packed else np.arange(S, dtype=np.int32)
    )
    packed_pos = jnp.asarray(base_pos_np) if args.packed else None

    if args.sp == "none":
        # Pure DP path through the reference-shaped optimizer wrapper.
        mn_opt = chainermn_tpu.create_multi_node_optimizer(opt, comm)

        def loss_fn(params, batch):
            tok, tgt, wt = batch
            logits = model.apply(params, tok, position_offset=packed_pos)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, tgt)
            # Local mean over this device's (equal-size) share of the
            # predicted positions; the wrapper pmeans across devices.
            return jnp.sum(ce * wt) / (denom / comm.device_size)

        dp_step = mn_opt.make_train_step(loss_fn, donate=False)

        def step(carry, batch):
            params, st = carry
            params, st, loss = dp_step(params, st, batch)
            return (params, st), loss

        carry = (params, mn_opt.init(params))
    else:
        def body(params, opt_state, tok_l, tgt_l, wt_l, pos_l):
            def loss_fn(params):
                # Explicit global positions: contiguous arange for
                # ring/ulysses, the zigzag permutation for zigzag — the
                # model indexes its positional table with them, so
                # non-contiguous shard layouts stay correct.
                logits = model.apply(params, tok_l, position_offset=pos_l)
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, tgt_l
                )
                # Sum here, global mean via psum: shards hold different
                # numbers of unmasked positions (the last shard masks the
                # final token), so a plain pmean-of-means would be biased.
                return jnp.sum(ce * wt_l) / denom

            loss, grads = jax.value_and_grad(loss_fn)(params)
            loss = lax.psum(loss, comm.axes)
            grads = jax.tree.map(lambda g: lax.psum(g, comm.axes), grads)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        batch_spec = P("inter", "intra")
        mapped = comm.shard_map(
            body,
            in_specs=(P(), P(), batch_spec, batch_spec, batch_spec,
                      P("intra")),
            out_specs=(P(), P(), P()),
        )
        jitted = jax.jit(mapped)

        if args.sp == "zigzag":
            from chainermn_tpu.parallel.ring_attention import zigzag_indices

            seq_perm = zigzag_indices(S, sp_ways)
        else:
            seq_perm = np.arange(S)
        # Positions index the model's positional table: the shared
        # base_pos_np rule carried through the shard layout permutation.
        positions = jnp.asarray(base_pos_np[seq_perm], jnp.int32)

        if args.vocab_tp:
            # Megatron-style vocab parallelism over the SAME devices as
            # the sequence axis: the embedding table and the LM-head
            # logits live sharded V/n per device; the transformer body
            # stays sequence-parallel.  The head follows Megatron's
            # SP+TP composition: all-gather the final hidden states over
            # the axis, then the vocab-sharded CE merges softmax
            # statistics with pmax/psum — logits never materialize
            # beyond a (chunk, V/n) tile per device.
            from chainermn_tpu.parallel.sharding import (
                gather_seq_for_replicated_head,
                vocab_parallel_cross_entropy,
                vocab_parallel_embed,
            )

            S_loc = S // sp_ways
            emb0 = params["params"]["embed"]["embedding"]
            params_rest = {"params": {
                k: v for k, v in params["params"].items() if k != "embed"
            }}
            st_rest0 = opt.init(params_rest)
            st_emb0 = opt.init(emb0)
            emb_spec = P("intra")
            # Optimizer moments are table-shaped: shard them alongside.
            st_emb_spec = jax.tree.map(
                lambda x: emb_spec if getattr(x, "ndim", 0) == 2 else P(),
                st_emb0,
            )

            def body_vtp(pr, emb, st_r, st_e, tok_f, tgt_f, wt_f, pos_f):
                my = lax.axis_index("intra")

                def loss_fn(pr, emb):
                    # grad_reduce=True: the transformer consumes only
                    # this device's sequence slice, so table cotangents
                    # arrive device-varying and the embed backward must
                    # psum across the axis.
                    x_f = vocab_parallel_embed(
                        tok_f, emb, "intra", True
                    )
                    x_l = lax.dynamic_slice_in_dim(
                        x_f, my * S_loc, S_loc, 1
                    )
                    tok_l = lax.dynamic_slice_in_dim(
                        tok_f, my * S_loc, S_loc, 1
                    )
                    pos_l = lax.dynamic_slice_in_dim(
                        pos_f, my * S_loc, S_loc, 0
                    )
                    h_l = model.apply(
                        pr, tok_l, position_offset=pos_l,
                        return_hidden=True, inputs_embeds=x_l,
                    )
                    # NOT plain lax.all_gather: the CE's gradient is
                    # replicated over intra, so all_gather's reduce-
                    # scatter transpose would inflate every transformer
                    # gradient by sp_ways.  The head-gather's backward
                    # slices instead (see sharding.py).
                    h_f = gather_seq_for_replicated_head(h_l, "intra", 1)
                    labels = jnp.where(wt_f > 0, tgt_f, -1)
                    return vocab_parallel_cross_entropy(
                        h_f, emb, labels, "intra"
                    )

                loss, (g_r, g_e) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1)
                )(pr, emb)
                # Transformer grads: intra devices hold their sequence
                # shard's partials, inter rows per-row grads — psum
                # completes both sums; /dp turns the inter sum into the
                # DP mean (the loss is already a per-row mean).
                g_r = jax.tree.map(
                    lambda g: lax.psum(g, comm.axes) / dp, g_r
                )
                # Embed-shard grads are intra-complete (both custom vjps
                # reduce internally); only the DP mean remains.
                g_e = lax.psum(g_e, "inter") / dp
                up_r, st_r = opt.update(g_r, st_r, pr)
                pr = optax.apply_updates(pr, up_r)
                up_e, st_e = opt.update(g_e, st_e, emb)
                emb = optax.apply_updates(emb, up_e)
                return pr, emb, st_r, st_e, lax.pmean(loss, "inter")

            jitted_vtp = jax.jit(comm.shard_map(
                body_vtp,
                in_specs=(P(), emb_spec, P(), st_emb_spec,
                          P("inter"), P("inter"), P("inter"), P()),
                out_specs=(P(), emb_spec, P(), st_emb_spec, P()),
            ))

            def step(carry, batch):
                pr, emb, st_r, st_e = carry
                pr, emb, st_r, st_e, loss = jitted_vtp(
                    pr, emb, st_r, st_e, *batch, positions
                )
                return (pr, emb, st_r, st_e), loss

            carry = (params_rest, emb0, st_rest0, st_emb0)
        else:
            def step(carry, batch):
                params, opt_state = carry
                params, opt_state, loss = jitted(params, opt_state, *batch,
                                                 positions)
                return (params, opt_state), loss

            carry = (params, opt_state)

    rng = np.random.RandomState(0)
    wt_np = np.ones((B, S), np.float32)
    wt_np[:, -1] = 0.0  # final position has no successor
    if args.packed:
        wt_np[:, S // 2 - 1] = 0.0  # first document's final position
    # Zigzag layout: batches are permuted into shard order on the host;
    # targets/weights ride the same permutation (the loss is a positionwise
    # sum, so it is permutation-invariant as long as all three agree).
    perm = seq_perm if args.sp == "zigzag" else np.arange(S)
    wt = jnp.asarray(wt_np[:, perm])

    # Fault tolerance: relaunching the same command line resumes from the
    # newest consistent generation.  The data stream is an rng sequence,
    # so resume replays (draws and discards) the consumed batches — the
    # restored run sees byte-identical remaining data.
    ckpt = None
    resume_step = gstep = 0
    if args.checkpoint_dir:
        from chainermn_tpu.extensions import create_multi_node_checkpointer
        from chainermn_tpu.global_except_hook import add_hook

        add_hook()
        ckpt = create_multi_node_checkpointer(
            args.checkpoint_name, comm, path=args.checkpoint_dir
        )
        loaded, it = ckpt.maybe_load({"carry": carry})
        if it is not None:
            carry = loaded["carry"]
            resume_step = gstep = it
            if comm.rank == 0:
                print(f"resumed from step {it}")

    last = float("nan")
    for epoch in range(args.epochs):
        t0, n_tok = time.perf_counter(), 0
        for i in range(args.steps_per_epoch):
            # Draw FIRST (the rng stream position is what resume replays),
            # assemble targets only for steps that actually train.
            if args.packed:
                halves = [
                    successor_batch(rng, B, S // 2, vocab) for _ in range(2)
                ]
            else:
                tok_np = successor_batch(rng, B, S, vocab)
            if epoch * args.steps_per_epoch + i < resume_step:
                continue  # replayed rng draw; already trained pre-crash
            if args.packed:
                # Two independent documents per row; targets roll WITHIN
                # each document (the boundary position is weight-zeroed).
                tok_np = np.concatenate(halves, axis=1)
                tgt_np = np.concatenate(
                    [np.roll(h, -1, axis=1) for h in halves], axis=1
                )
            else:
                tgt_np = np.roll(tok_np, -1, axis=1)
            tok = jnp.asarray(tok_np[:, perm])
            tgt = jnp.asarray(tgt_np[:, perm])
            carry, last = step(carry, (tok, tgt, wt))
            n_tok += B * S
            gstep += 1
            if ckpt is not None and gstep % args.checkpoint_every == 0:
                ckpt.save({"carry": carry}, gstep, block=False)
        if n_tok:
            sync(last)  # host readback: honest timing on all backends
        dt = time.perf_counter() - t0
        if comm.rank == 0 and n_tok:
            print(
                f"epoch {epoch}: loss {float(last):.4f} "
                f"({n_tok / dt:,.0f} tok/s)"
            )
    if ckpt is not None:
        ckpt.wait()
        from chainermn_tpu.utils.native import tree_digest

        if comm.rank == 0:
            print(
                f"final step {gstep} params_digest "
                f"{tree_digest(carry[0]):08x}"
            )
    return float(last)


if __name__ == "__main__":
    main()
