#!/usr/bin/env python
"""Data-parallel MNIST MLP — the minimum end-to-end slice.

Reference: REF:examples/mnist/train_mnist.py — the canonical ChainerMN
usage pattern: ``create_communicator`` → ``scatter_dataset`` →
``create_multi_node_optimizer`` → trainer + ``create_multi_node_evaluator``,
with a flag-selectable communicator (CPU-capable with ``naive``).

TPU-native differences: there is one process per *host* (not per chip);
the per-step batch is a global array whose leading axis the jitted step
shards over the device mesh, and the gradient allreduce is traced into the
step by the multi-node optimizer.

Run (single host, any backend):
    python examples/mnist/train_mnist.py --communicator xla_ici
CPU-mesh smoke run (8 virtual devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/mnist/train_mnist.py --communicator naive --epochs 2
"""

import argparse
import contextlib
import json
import time

import jax
import jax.numpy as jnp
import optax

import chainermn_tpu
from chainermn_tpu.utils.profiling import sync
from chainermn_tpu.datasets.toy import SyntheticImageDataset, batch_iterator
from chainermn_tpu.extensions import Evaluator
from chainermn_tpu.models import MLP


def main():
    p = argparse.ArgumentParser(description="chainermn_tpu MNIST example")
    p.add_argument("--communicator", default="xla_ici")
    p.add_argument("--bucket-bytes", type=int, default=None,
                   help="gradient-allreduce bucket cap in bytes "
                        "(0 disables bucketing; default: 4 MiB / "
                        "CHAINERMN_TPU_BUCKET_BYTES — docs/performance.md)")
    p.add_argument("--batchsize", type=int, default=256, help="global batch size")
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--unit", type=int, default=1000)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--double-buffering", action="store_true")
    p.add_argument("--zero-stage", type=int, default=0, choices=(0, 1, 2, 3),
                   help="ZeRO sharding stage (composes with "
                        "--double-buffering)")
    p.add_argument("--train-size", type=int, default=8192)
    p.add_argument("--val-size", type=int, default=1024)
    p.add_argument("--step-log", default=None, metavar="PATH",
                   help="write a JSONL step-event log (per-step loss, "
                        "timing, compile events, one hlo_audit row); "
                        "summarize with `python -m chainermn_tpu.tools.obs "
                        "summarize PATH`.  Multi-process runs should "
                        "point each rank at its own file.")
    args = p.parse_args()

    comm = chainermn_tpu.create_communicator(
        args.communicator, bucket_bytes=args.bucket_bytes
    )
    if comm.rank == 0:  # reference pattern: only rank 0 logs
        print(f"communicator: {comm!r}")
        print(f"global batch {args.batchsize} over {comm.device_size} devices")

    train = SyntheticImageDataset(n=args.train_size, seed=0)
    val = SyntheticImageDataset(n=args.val_size, seed=1)
    train = chainermn_tpu.scatter_dataset(train, comm, shuffle=True, seed=42)
    val = chainermn_tpu.scatter_dataset(val, comm)

    model = MLP(n_units=args.unit, n_out=10)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply(params, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    def metric_fn(params, batch):
        x, y = batch
        logits = model.apply(params, x)
        return {
            "val/loss": optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean(),
            "val/accuracy": (logits.argmax(-1) == y).mean(),
        }

    opt = chainermn_tpu.create_multi_node_optimizer(
        optax.adam(args.lr), comm, double_buffering=args.double_buffering,
        zero_stage=args.zero_stage,
    )
    state = opt.init(params)
    if args.zero_stage == 3:
        # Stage 3: the step trades in the flat sharded master buffer.
        params = opt.shard_params(params)
    step = opt.make_train_step(loss_fn)
    evaluator = Evaluator(metric_fn, comm)

    # --step-log: install a Reporter + StepRecorder for the whole run.
    # The instrumented step and the evaluator publish into them; the
    # per-step float(loss) readback below is the example's choice of
    # fidelity over async dispatch.
    telemetry = contextlib.ExitStack()
    reporter = recorder = None
    if args.step_log:
        from chainermn_tpu import observability as obs

        reporter = obs.Reporter()
        telemetry.enter_context(obs.scope(reporter))
        recorder = telemetry.enter_context(
            obs.StepRecorder(args.step_log, rank=comm.rank)
        )

    global_step = 0
    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        n_seen = 0
        last_loss = float("nan")
        for batch in batch_iterator(train, args.batchsize, seed=epoch):
            if recorder is not None and global_step == 0:
                # Audit the unwrapped jitted step once: the collective
                # census of the program the whole run executes.
                a = obs.audit_fn(getattr(step, "__wrapped__", step),
                                 params, state, batch)
                recorder.record("hlo_audit", counts=a.counts,
                                bytes_per_axis=a.bytes_per_axis)
            params, state, loss = step(params, state, batch)
            n_seen += batch[0].shape[0]
            last_loss = loss
            if recorder is not None:
                recorder.step(step=global_step, items=batch[0].shape[0],
                              loss=float(loss), epoch=epoch)
            global_step += 1
        sync(last_loss)  # host readback: honest timing on all backends
        dt = time.perf_counter() - t0

        eval_params = (
            opt.materialize(params) if args.zero_stage == 3 else params
        )
        metrics = evaluator.evaluate(
            eval_params, batch_iterator(val, args.batchsize, shuffle=False)
        )
        if comm.rank == 0:
            ips = n_seen / dt
            print(
                f"epoch {epoch}: train/loss {float(last_loss):.4f}  "
                + "  ".join(f"{k} {v:.4f}" for k, v in metrics.items())
                + f"  ({ips:,.0f} img/s)"
            )
    if reporter is not None:
        agg = reporter.aggregate(comm)
        if comm.rank == 0:
            print("telemetry: " + json.dumps(agg))
    telemetry.close()
    return params, metrics


if __name__ == "__main__":
    main()
