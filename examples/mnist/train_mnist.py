#!/usr/bin/env python
"""Data-parallel MNIST MLP — the minimum end-to-end slice.

Reference: REF:examples/mnist/train_mnist.py — the canonical ChainerMN
usage pattern: ``create_communicator`` → ``scatter_dataset`` →
``create_multi_node_optimizer`` → trainer + ``create_multi_node_evaluator``,
with a flag-selectable communicator (CPU-capable with ``naive``).

TPU-native differences: there is one process per *host* (not per chip);
the per-step batch is a global array whose leading axis the jitted step
shards over the device mesh, and the gradient allreduce is traced into the
step by the multi-node optimizer.

Run (single host, any backend):
    python examples/mnist/train_mnist.py --communicator xla_ici
CPU-mesh smoke run (8 virtual devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/mnist/train_mnist.py --communicator naive --epochs 2

Elastic run under the supervisor (docs/fault_tolerance.md):
    python -m chainermn_tpu.tools.elastic --nproc 2 -- \
        python examples/mnist/train_mnist.py --communicator naive \
        --elastic --checkpoint-dir ckpt --checkpoint-every 1
"""

import argparse
import contextlib
import json
import time

import jax
import jax.numpy as jnp
import optax

import chainermn_tpu
from chainermn_tpu.utils.profiling import sync
from chainermn_tpu.datasets.toy import SyntheticImageDataset, batch_iterator
from chainermn_tpu.extensions import Evaluator
from chainermn_tpu.models import MLP


def main(argv=None):
    p = argparse.ArgumentParser(description="chainermn_tpu MNIST example")
    p.add_argument("--communicator", default="xla_ici")
    p.add_argument("--bucket-bytes", type=int, default=None,
                   help="gradient-allreduce bucket cap in bytes "
                        "(0 disables bucketing; default: 4 MiB / "
                        "CHAINERMN_TPU_BUCKET_BYTES — docs/performance.md)")
    p.add_argument("--batchsize", type=int, default=256, help="global batch size")
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--unit", type=int, default=1000)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--double-buffering", action="store_true")
    p.add_argument("--zero-stage", type=int, default=0, choices=(0, 1, 2, 3),
                   help="ZeRO sharding stage (composes with "
                        "--double-buffering)")
    p.add_argument("--train-size", type=int, default=8192)
    p.add_argument("--val-size", type=int, default=1024)
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable fault tolerance: multi-node checkpointer "
                   "saves here and auto-resumes from the newest consistent "
                   "generation on relaunch")
    p.add_argument("--checkpoint-every", type=int, default=10,
                   help="save a generation every N global steps")
    p.add_argument("--checkpoint-name", default="mnist",
                   help="checkpoint set name under --checkpoint-dir")
    p.add_argument("--elastic", action="store_true",
                   help="join the elastic supervisor's world "
                   "(CHAINERMN_TPU_ELASTIC_* env): heartbeats, chaos "
                   "faults, SIGTERM-as-preemption, and plan-driven "
                   "resharding on rescale.  A no-op outside a "
                   "supervised run.")
    p.add_argument("--step-log", default=None, metavar="PATH",
                   help="write a JSONL step-event log (per-step loss, "
                        "timing, compile events, one hlo_audit row); "
                        "summarize with `python -m chainermn_tpu.tools.obs "
                        "summarize PATH`.  Multi-process runs should "
                        "point each rank at its own file.")
    args = p.parse_args(argv)

    ctx = None
    if args.elastic:
        from chainermn_tpu import elastic

        # Joins jax.distributed BEFORE the backend initializes below;
        # returns None when not running under the supervisor.
        ctx = elastic.init_from_env()

    comm = chainermn_tpu.create_communicator(
        args.communicator, bucket_bytes=args.bucket_bytes
    )
    if comm.rank == 0:  # reference pattern: only rank 0 logs
        print(f"communicator: {comm!r}")
        print(f"global batch {args.batchsize} over {comm.device_size} devices")

    train = SyntheticImageDataset(n=args.train_size, seed=0)
    val = SyntheticImageDataset(n=args.val_size, seed=1)
    train = chainermn_tpu.scatter_dataset(train, comm, shuffle=True, seed=42)
    val = chainermn_tpu.scatter_dataset(val, comm)

    model = MLP(n_units=args.unit, n_out=10)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply(params, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    def metric_fn(params, batch):
        x, y = batch
        logits = model.apply(params, x)
        return {
            "val/loss": optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean(),
            "val/accuracy": (logits.argmax(-1) == y).mean(),
        }

    opt = chainermn_tpu.create_multi_node_optimizer(
        optax.adam(args.lr), comm, double_buffering=args.double_buffering,
        zero_stage=args.zero_stage,
    )
    state = opt.init(params)
    if args.zero_stage == 3:
        # Stage 3: the step trades in the flat sharded master buffer.
        params = opt.shard_params(params)
    step = opt.make_train_step(loss_fn)
    evaluator = Evaluator(metric_fn, comm)

    # --step-log: install a Reporter + StepRecorder for the whole run.
    # The instrumented step and the evaluator publish into them; the
    # per-step float(loss) readback below is the example's choice of
    # fidelity over async dispatch.
    telemetry = contextlib.ExitStack()
    reporter = recorder = None
    if args.step_log:
        from chainermn_tpu import observability as obs

        reporter = obs.Reporter()
        telemetry.enter_context(obs.scope(reporter))
        recorder = telemetry.enter_context(
            obs.StepRecorder(args.step_log, rank=comm.rank)
        )

    # Fault tolerance: a crashed/killed/preempted run relaunched with
    # the same command line resumes from the newest consistent
    # generation — mid-epoch, at the exact step.
    ckpt = None
    start_epoch = start_step = gstep = 0
    if args.checkpoint_dir:
        from chainermn_tpu.extensions import create_multi_node_checkpointer
        from chainermn_tpu.global_except_hook import add_hook

        add_hook()
        ckpt = create_multi_node_checkpointer(
            args.checkpoint_name, comm, path=args.checkpoint_dir
        )
        if ctx is not None:
            ctx.attach_checkpointer(ckpt)  # arm ckpt_* chaos faults
        template = {"params": params, "state": state, "epoch": 0, "step": 0}
        loaded, it = ckpt.maybe_load(template)
        if it is not None:
            params, state = loaded["params"], loaded["state"]
            start_epoch, start_step = int(loaded["epoch"]), int(loaded["step"])
            gstep = it
            if comm.rank == 0:
                print(
                    f"resumed from iteration {it} "
                    f"(epoch {start_epoch}, step {start_step})"
                )
            if ctx is not None and args.zero_stage == 0:
                # Rescale-ready restore: re-place params and moments for
                # the CURRENT mesh through the sharding-plan registry —
                # an N→M restart is plan.resolve on a different mesh.
                params, state, plan_report = ctx.reshard(
                    params, state, comm, plan="dp"
                )
                if comm.rank == 0:
                    print(
                        f"elastic_reshard plan=dp ok={plan_report.ok} "
                        f"leaves={plan_report.n_leaves} world={comm.size}"
                    )

    # Multi-process deployment: each process draws a LOCAL slice of the
    # global batch from its scattered shard and comm.global_batch
    # assembles the device-global arrays (single-process runs keep the
    # exact original arithmetic: local slice == global batch).
    if args.batchsize % comm.size:
        raise SystemExit(
            f"--batchsize {args.batchsize} must divide by the process "
            f"count {comm.size}"
        )
    local_bs = args.batchsize // comm.size

    metrics = {}
    for epoch in range(start_epoch, args.epochs):
        t0 = time.perf_counter()
        n_seen = 0
        n_steps = 0
        last_loss = float("nan")
        # Resuming into this epoch: replay the iterator (same epoch seed
        # → same permutation) and drop the batches already trained on.
        skip = start_step if epoch == start_epoch else 0
        start_step = 0
        for batch in batch_iterator(train, local_bs, seed=epoch):
            if skip > 0:
                skip -= 1
                n_steps += 1
                if ctx is not None:
                    ctx.beat(gstep)  # liveness during replay
                continue
            if ctx is not None:
                ctx.beat(gstep)  # chaos faults fire here, deterministically
                if ckpt is not None and ctx.check_preemption(comm):
                    # Grace-window synchronous checkpoint: every rank
                    # arrives here at the same step, saves, and exits
                    # with the preemption code (not a crash).
                    ckpt.save(
                        {"params": params, "state": state,
                         "epoch": epoch, "step": n_steps},
                        gstep, block=True,
                    )
                    if comm.rank == 0:
                        print(f"preempted: checkpoint saved at "
                              f"iteration {gstep}")
                    ctx.exit_preempted()
            gb = (batch[0], batch[1])
            if comm.size > 1:
                gb = comm.global_batch(gb)
            if recorder is not None and gstep == 0:
                from chainermn_tpu import observability as obs

                # Audit the unwrapped jitted step once: the collective
                # census of the program the whole run executes.
                a = obs.audit_fn(getattr(step, "__wrapped__", step),
                                 params, state, gb)
                recorder.record("hlo_audit", counts=a.counts,
                                bytes_per_axis=a.bytes_per_axis)
            params, state, loss = step(params, state, gb)
            n_seen += gb[0].shape[0]
            n_steps += 1
            gstep += 1
            last_loss = loss
            if recorder is not None:
                recorder.step(step=gstep - 1, items=gb[0].shape[0],
                              loss=float(loss), epoch=epoch)
            if ckpt is not None and gstep % args.checkpoint_every == 0:
                ckpt.save(
                    {"params": params, "state": state,
                     "epoch": epoch, "step": n_steps},
                    gstep, block=False,
                )
        sync(last_loss)  # host readback: honest timing on all backends
        dt = time.perf_counter() - t0

        eval_params = (
            opt.materialize(params) if args.zero_stage == 3 else params
        )
        metrics = evaluator.evaluate(
            eval_params, batch_iterator(val, local_bs, shuffle=False)
        )
        if comm.rank == 0:
            ips = n_seen / dt
            print(
                f"epoch {epoch}: train/loss {float(last_loss):.4f}  "
                + "  ".join(f"{k} {v:.4f}" for k, v in metrics.items())
                + f"  ({ips:,.0f} img/s)"
            )
    if ckpt is not None:
        ckpt.wait()
        from chainermn_tpu.utils.native import tree_digest

        digest_params = (
            opt.materialize(params) if args.zero_stage == 3 else params
        )
        if comm.rank == 0:
            print(
                f"final gstep {gstep} "
                f"params_digest {tree_digest(digest_params):08x}"
            )
    if reporter is not None:
        agg = reporter.aggregate(comm)
        if comm.rank == 0:
            print("telemetry: " + json.dumps(agg))
    telemetry.close()
    return params, metrics


if __name__ == "__main__":
    main()
