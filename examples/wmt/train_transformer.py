#!/usr/bin/env python
"""Transformer enc-dec (WMT-shape) with hierarchical 2D allreduce —
BASELINE config #4.

The configuration the reference ran on multi-node GPU pods with its
``two_dimensional`` communicator (intra-node reduce-scatter → inter-node
allreduce → intra-node all-gather, REF:chainermn/communicators/
two_dimensional_communicator.py): here the same collective pattern rides
the ICI (``intra``) and DCN (``inter``) mesh axes, traced into the jitted
step by the multi-node optimizer.

Data: zero-egress → synthetic reversal "translation" corpus of WMT-like
shape; point --data-npz at {src,tgt} int32 arrays for real text.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import optax

import chainermn_tpu
from chainermn_tpu.utils.profiling import sync
from chainermn_tpu.datasets.toy import SyntheticSeqDataset, batch_iterator
from chainermn_tpu.models.transformer import Transformer


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--communicator", default="two_dimensional")
    p.add_argument("--batchsize", type=int, default=128, help="global batch")
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--d-ff", type=int, default=1024)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--train-size", type=int, default=4096)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--comm-dtype", default="bfloat16",
                   help="allreduce_grad dtype (the fp16-comm analogue)")
    p.add_argument("--steps", type=int, default=None)
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator(
        args.communicator,
        allreduce_grad_dtype=args.comm_dtype if args.comm_dtype != "none" else None,
    )
    if comm.rank == 0:
        print(f"communicator: {comm!r} comm-dtype={args.comm_dtype}")

    train = SyntheticSeqDataset(
        n=args.train_size, src_len=args.seq_len, tgt_len=args.seq_len,
        vocab=args.vocab,
    )
    train = chainermn_tpu.scatter_dataset(train, comm, shuffle=True, seed=0)

    model = Transformer(
        vocab=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        d_ff=args.d_ff, n_enc_layers=args.layers, n_dec_layers=args.layers,
        max_len=args.seq_len,
    )
    src0 = jnp.zeros((2, args.seq_len), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), src0, src0)

    def loss_fn(params, batch):
        src, tgt = batch
        tgt_in = jnp.concatenate(
            [jnp.ones((tgt.shape[0], 1), tgt.dtype), tgt[:, :-1]], axis=1
        )
        logits = model.apply(params, src, tgt_in)
        mask = (tgt != 0).astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, tgt)
        return (ce * mask).sum() / mask.sum()

    sched = optax.warmup_cosine_decay_schedule(
        0.0, args.lr, 50, max(200, args.epochs * len(train) // args.batchsize)
    )
    opt = chainermn_tpu.create_multi_node_optimizer(
        optax.adamw(sched, weight_decay=0.01), comm
    )
    state = opt.init(params)
    step = opt.make_train_step(loss_fn)

    n_steps = 0
    for epoch in range(args.epochs):
        t0, n_tok, last = time.perf_counter(), 0, float("nan")
        for batch in batch_iterator(train, args.batchsize, seed=epoch):
            params, state, last = step(params, state, batch)
            n_tok += batch[0].size + batch[1].size
            n_steps += 1
            if args.steps and n_steps >= args.steps:
                break
        sync(last)  # host readback: honest timing on all backends
        dt = time.perf_counter() - t0
        if comm.rank == 0:
            print(
                f"epoch {epoch}: loss {float(last):.4f} "
                f"({n_tok/dt:,.0f} tok/s over {comm.device_size} devices)"
            )
    return float(last)


if __name__ == "__main__":
    main()
