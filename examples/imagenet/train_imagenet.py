#!/usr/bin/env python
"""Data-parallel ImageNet ResNet — the throughput configuration.

Reference: REF:examples/imagenet/train_imagenet.py — per-rank
MultiprocessIterator feeding a ResNet-50, hierarchical/pure_nccl
communicators, linear LR scaling with warmup.  This is BASELINE config #2
and the source of the ``images/sec/chip`` headline metric.

TPU-native shape: bf16 NHWC ResNet, global-batch arrays sharded over the
mesh by the jitted step, BatchNorm statistics pmean-synced across replicas,
SGD+momentum with the linear-scaling warmup schedule of the large-minibatch
papers the reference stack pioneered (arXiv:1711.04325).

Data: zero-egress environment → synthetic ImageNet-shaped dataset by
default; pass ``--data-npz`` with ``images``/``labels`` arrays for real
data.
"""

import argparse
import contextlib
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import chainermn_tpu
from chainermn_tpu.utils.profiling import sync
from chainermn_tpu.datasets.toy import SyntheticImageDataset, batch_iterator
from chainermn_tpu.extensions import Evaluator
from chainermn_tpu.models.convnets import AlexNet, GoogLeNet, NiN
from chainermn_tpu.models.resnet import ResNet18, ResNet50


def main(argv=None):
    p = argparse.ArgumentParser(description="chainermn_tpu ImageNet example")
    p.add_argument("--communicator", default="xla_ici")
    p.add_argument("--bucket-bytes", type=int, default=None,
                   help="gradient-allreduce bucket cap in bytes "
                        "(0 disables bucketing; default: 4 MiB / "
                        "CHAINERMN_TPU_BUCKET_BYTES — docs/performance.md)")
    p.add_argument("--arch", "--model", dest="arch", default="resnet50",
                   choices=["resnet50", "resnet18", "alex", "nin", "googlenet"],
                   help="model architecture (reference: train_imagenet.py --arch)")
    p.add_argument("--batchsize", type=int, default=256, help="global batch")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--optimizer", choices=["sgd", "lars"], default="sgd",
                   help="lars = layer-wise adaptive rates for very large "
                   "global batches")
    p.add_argument("--warmup-steps", type=int, default=100)
    p.add_argument("--train-size", type=int, default=4096)
    p.add_argument("--val-size", type=int, default=512)
    p.add_argument("--steps", type=int, default=None, help="cap steps/epoch")
    p.add_argument("--data-npz", default=None)
    p.add_argument("--prefetch", type=int, default=2,
                   help="device-prefetch queue depth (0 disables) — the "
                   "reference's MultiprocessIterator overlap")
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable fault tolerance: multi-node checkpointer "
                   "saves here and auto-resumes from the newest consistent "
                   "generation on relaunch (reference: "
                   "create_multi_node_checkpointer + maybe_load)")
    p.add_argument("--checkpoint-every", type=int, default=50,
                   help="save a generation every N global steps")
    p.add_argument("--checkpoint-name", default="imagenet",
                   help="checkpoint set name under --checkpoint-dir")
    p.add_argument("--elastic", action="store_true",
                   help="join the elastic supervisor's world "
                   "(CHAINERMN_TPU_ELASTIC_* env): heartbeats, chaos "
                   "faults, SIGTERM-as-preemption, and plan-driven "
                   "resharding on rescale.  A no-op outside a "
                   "supervised run.")
    p.add_argument("--step-log", default=None, metavar="PATH",
                   help="write a JSONL step-event log (per-step timing, "
                        "loss, compile events, device memory, one "
                        "hlo_audit row); summarize with `python -m "
                        "chainermn_tpu.tools.obs summarize PATH`")
    args = p.parse_args(argv)

    ctx = None
    if args.elastic:
        from chainermn_tpu import elastic

        # Joins jax.distributed BEFORE the backend initializes below;
        # returns None when not running under the supervisor.
        ctx = elastic.init_from_env()

    comm = chainermn_tpu.create_communicator(
        args.communicator, bucket_bytes=args.bucket_bytes
    )
    if comm.rank == 0:
        print(f"communicator: {comm!r}")

    shape = (args.image_size, args.image_size, 3)
    if args.data_npz:
        raw = np.load(args.data_npz)
        images, labels = raw["images"], raw["labels"]
        train = list(zip(images, labels))
        val = train[: args.val_size]
    else:
        train = SyntheticImageDataset(
            n=args.train_size, shape=shape, n_classes=args.num_classes, seed=0
        )
        val = SyntheticImageDataset(
            n=args.val_size, shape=shape, n_classes=args.num_classes, seed=1
        )
    train = chainermn_tpu.scatter_dataset(train, comm, shuffle=True, seed=42)
    val = chainermn_tpu.scatter_dataset(val, comm)

    archs = {
        "resnet50": ResNet50, "resnet18": ResNet18,
        "alex": AlexNet, "nin": NiN, "googlenet": GoogLeNet,
    }
    model = archs[args.arch](num_classes=args.num_classes)
    has_bn = args.arch.startswith("resnet")
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, *shape), jnp.float32), train=False
    )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})

    # Linear-scaling rule with warmup (the reference stack's large-batch
    # recipe): lr = base * (global_batch / 256), warmed up from 0.
    # --optimizer lars is the layer-wise adaptive-rate variant the
    # extreme-batch ResNet results (arXiv:1711.04325-era) relied on.
    scaled_lr = args.lr * args.batchsize / 256.0
    sched = optax.linear_schedule(0.0, scaled_lr, args.warmup_steps)
    if args.optimizer == "lars":
        inner = optax.lars(sched, momentum=0.9, weight_decay=1e-4)
    else:
        inner = optax.sgd(sched, momentum=0.9, nesterov=False)
    opt = chainermn_tpu.create_multi_node_optimizer(inner, comm)
    state = opt.init(params)

    if has_bn:
        def loss_fn(params, batch_stats, batch):
            x, y = batch
            logits, updates = model.apply(
                {"params": params, "batch_stats": batch_stats},
                x,
                train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
            return loss, updates["batch_stats"]

        step = opt.make_train_step_with_state(loss_fn)
    else:
        # Dropout architectures: rng threaded per (step, device) by the
        # optimizer wrapper.
        def rng_loss_fn(params, batch, rng):
            x, y = batch
            logits = model.apply(
                {"params": params}, x, train=True, rngs={"dropout": rng}
            )
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        plain_step = opt.make_train_step(rng_loss_fn, rng=jax.random.PRNGKey(7))

        def step(params, state, batch_stats, batch):
            params, state, loss = plain_step(params, state, batch)
            return params, state, batch_stats, loss

    def metric_fn(params_and_stats, batch):
        params, batch_stats = params_and_stats
        x, y = batch
        variables = {"params": params}
        if has_bn:
            variables["batch_stats"] = batch_stats
        logits = model.apply(variables, x, train=False)
        return {
            "val/loss": optax.softmax_cross_entropy_with_integer_labels(logits, y).mean(),
            "val/accuracy": (logits.argmax(-1) == y).mean(),
        }

    evaluator = Evaluator(metric_fn, comm)

    # --step-log: opt-in telemetry for the whole run.  Note the per-step
    # float(loss) readback below serializes host and device — leave the
    # flag off when chasing headline img/s.
    telemetry = contextlib.ExitStack()
    reporter = recorder = None
    if args.step_log:
        from chainermn_tpu import observability as obs

        reporter = obs.Reporter()
        telemetry.enter_context(obs.scope(reporter))
        recorder = telemetry.enter_context(
            obs.StepRecorder(args.step_log, rank=comm.rank)
        )

    # Fault tolerance (reference: REF:examples' checkpointer usage +
    # REF:chainermn/extensions/checkpoint.py): a crashed/killed run
    # relaunched with the same command line resumes from the newest
    # consistent generation — mid-epoch, at the exact step — and the
    # global except hook turns any rank's uncaught error into a whole-job
    # abort instead of a hang.
    ckpt = None
    start_epoch = start_step = gstep = 0
    if args.checkpoint_dir:
        from chainermn_tpu.extensions import create_multi_node_checkpointer
        from chainermn_tpu.global_except_hook import add_hook

        add_hook()
        ckpt = create_multi_node_checkpointer(
            args.checkpoint_name, comm, path=args.checkpoint_dir
        )
        if ctx is not None:
            ctx.attach_checkpointer(ckpt)  # arm ckpt_* chaos faults
        template = {
            "params": params, "state": state, "batch_stats": batch_stats,
            "epoch": 0, "step": 0,
        }
        loaded, it = ckpt.maybe_load(template)
        if it is not None:
            params, state = loaded["params"], loaded["state"]
            batch_stats = loaded["batch_stats"]
            start_epoch, start_step = int(loaded["epoch"]), int(loaded["step"])
            gstep = it
            if comm.rank == 0:
                print(
                    f"resumed from iteration {it} "
                    f"(epoch {start_epoch}, step {start_step})"
                )
            if ctx is not None:
                # Rescale-ready restore: re-place params and moments for
                # the CURRENT mesh through the sharding-plan registry —
                # an N→M restart is plan.resolve on a different mesh.
                params, state, plan_report = ctx.reshard(
                    params, state, comm, plan="dp"
                )
                if comm.rank == 0:
                    print(
                        f"elastic_reshard plan=dp ok={plan_report.ok} "
                        f"leaves={plan_report.n_leaves} world={comm.size}"
                    )

    # Multi-process deployment (the reference's mpiexec shape): each
    # process draws a LOCAL slice of the global batch from its scattered
    # shard and comm.global_batch assembles the device-global arrays.
    if args.batchsize % comm.size:
        raise SystemExit(
            f"--batchsize {args.batchsize} must divide by the process "
            f"count {comm.size}"
        )
    local_bs = args.batchsize // comm.size

    def host_batches(epoch):
        # Host-side work (cast/augment) runs here — inside the prefetch
        # thread when enabled, overlapped with device compute.
        for batch in batch_iterator(train, local_bs, seed=epoch):
            yield (batch[0].astype(np.float32), batch[1])

    for epoch in range(start_epoch, args.epochs):
        t0, n_seen, last_loss, n_steps = time.perf_counter(), 0, float("nan"), 0
        # Resuming into this epoch: replay the iterator (same epoch seed →
        # same permutation) and drop the batches already trained on.
        skip = start_step if epoch == start_epoch else 0
        start_step = 0
        batches = host_batches(epoch)
        if args.prefetch > 0:
            batches = chainermn_tpu.create_prefetch_iterator(
                batches, size=args.prefetch
            )
        for batch in batches:
            if skip > 0:
                skip -= 1
                n_steps += 1
                if ctx is not None:
                    ctx.beat(gstep)  # liveness during replay
                if args.steps and n_steps >= args.steps:
                    break  # the cap counts replayed steps too
                continue
            if ctx is not None:
                ctx.beat(gstep)  # chaos faults fire here, deterministically
                if ckpt is not None and ctx.check_preemption(comm):
                    # Grace-window synchronous checkpoint: every rank
                    # arrives here at the same step, saves, and exits
                    # with the preemption code (not a crash).
                    ckpt.save(
                        {"params": params, "state": state,
                         "batch_stats": batch_stats,
                         "epoch": epoch, "step": n_steps},
                        gstep, block=True,
                    )
                    if comm.rank == 0:
                        print(f"preempted: checkpoint saved at "
                              f"iteration {gstep}")
                    ctx.exit_preempted()
            gb = (batch[0], batch[1])
            if comm.size > 1:
                gb = comm.global_batch(gb)
            if recorder is not None and gstep == 0:
                from chainermn_tpu import observability as obs

                a = obs.audit_fn(getattr(step, "__wrapped__", step),
                                 params, state, batch_stats, gb)
                recorder.record("hlo_audit", counts=a.counts,
                                bytes_per_axis=a.bytes_per_axis)
            params, state, batch_stats, loss = step(
                params, state, batch_stats, gb
            )
            n_seen += gb[0].shape[0]
            n_steps += 1
            gstep += 1
            last_loss = loss
            if recorder is not None:
                recorder.step(step=gstep - 1, items=gb[0].shape[0],
                              loss=float(loss), epoch=epoch)
            if ckpt is not None and gstep % args.checkpoint_every == 0:
                ckpt.save(
                    {"params": params, "state": state,
                     "batch_stats": batch_stats,
                     "epoch": epoch, "step": n_steps},
                    gstep, block=False,
                )
            if args.steps and n_steps >= args.steps:
                break
        sync(last_loss)  # host readback: honest timing on all backends
        dt = time.perf_counter() - t0

        metrics = evaluator.evaluate(
            (params, batch_stats),
            batch_iterator(val, local_bs, shuffle=False),
        )
        if comm.rank == 0:
            ips = n_seen / dt
            per_chip = ips / comm.device_size
            print(
                f"epoch {epoch}: loss {float(last_loss):.4f}  "
                + "  ".join(f"{k} {v:.4f}" for k, v in metrics.items())
                + f"  {ips:,.1f} img/s ({per_chip:,.1f}/chip)"
            )
    if ckpt is not None:
        ckpt.wait()
        from chainermn_tpu.utils.native import tree_digest

        if comm.rank == 0:
            print(
                f"final gstep {gstep} params_digest {tree_digest(params):08x}"
            )
    if reporter is not None:
        agg = reporter.aggregate(comm)
        if comm.rank == 0:
            print("telemetry: " + json.dumps(agg))
    telemetry.close()
    return params, batch_stats


if __name__ == "__main__":
    main()
