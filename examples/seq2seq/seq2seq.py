#!/usr/bin/env python
"""Model-parallel seq2seq — encoder and decoder on different device ranks.

Reference: REF:examples/seq2seq/seq2seq.py — the ChainerMN model-parallel
showcase: encoder on rank 0, decoder on rank 1, wired with
``MultiNodeChainList`` ``send``/``recv`` (BASELINE config #3).

TPU-native: both stages live in ONE traced SPMD program; the encoder's
hidden state crosses ranks as a single ``lax.ppermute`` and gradients ride
its transpose back.  Trained here on the synthetic reversal task (target =
reversed source) so convergence is a real acceptance signal.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.utils.profiling import sync
from chainermn_tpu.datasets.toy import SyntheticSeqDataset, batch_iterator
from chainermn_tpu.links import MultiNodeChainList
from chainermn_tpu.models.seq2seq import Decoder, Encoder, shift_right


def main(argv=None):
    p = argparse.ArgumentParser(description="chainermn_tpu seq2seq example")
    p.add_argument("--communicator", default="xla_ici")
    p.add_argument("--batchsize", type=int, default=64)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--unit", type=int, default=128)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--seq-len", type=int, default=12)
    p.add_argument("--train-size", type=int, default=2048)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--sharded-params", action="store_true",
                   help="stage-sharded parameter storage: each device "
                        "holds only its own component (encoder XOR "
                        "decoder), not the whole model")
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator(args.communicator)
    n = comm.device_size
    enc_rank, dec_rank = 0, n - 1
    if comm.rank == 0:
        print(f"communicator: {comm!r}; encoder on rank {enc_rank}, "
              f"decoder on rank {dec_rank}")

    train = SyntheticSeqDataset(
        n=args.train_size, src_len=args.seq_len, tgt_len=args.seq_len,
        vocab=args.vocab,
    )
    train = chainermn_tpu.scatter_dataset(train, comm, shuffle=True, seed=0)

    encoder = Encoder(args.vocab, args.unit)
    decoder = Decoder(args.vocab, args.unit)
    src0 = jnp.zeros((2, args.seq_len), jnp.int32)
    tgt0 = jnp.zeros((2, args.seq_len), jnp.int32)
    enc_params = encoder.init(jax.random.PRNGKey(0), src0)
    dec_params = decoder.init(
        jax.random.PRNGKey(1), encoder.apply(enc_params, src0), tgt0
    )

    # The split model: encoder owned by rank 0, decoder by the last rank,
    # hidden state transferred between them.
    chain = MultiNodeChainList(comm)
    chain.add_link(
        lambda p, batch: encoder.apply(p, batch[0]),
        rank=enc_rank, rank_in=None, rank_out=dec_rank,
    )
    chain.add_link(
        lambda p, inp: decoder.apply(p, inp[0], shift_right(inp[1][1])),
        rank=dec_rank, rank_in=enc_rank, rank_out=None, needs_input=True,
    )

    def ce_loss(logits, batch):
        tgt = batch[1]
        mask = (tgt != 0).astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, tgt)
        return (ce * mask).sum() / mask.sum()

    def loss_fn(params_list, batch):
        return ce_loss(chain.apply(params_list, batch), batch)

    opt = optax.adam(args.lr)
    params = (enc_params, dec_params)

    if args.sharded_params:
        # Stage-sharded tier: each device persistently holds only its own
        # component's parameters (encoder XOR decoder), as one flat row of
        # the sharded buffer — the per-process memory profile the
        # reference's one-rank-one-submodel processes had.
        flat = chain.shard_params(params)
        opt_state = chain.init_sharded_opt_state(opt, flat)
        train_step = chain.make_sharded_train_step(opt, ce_loss)
        params = flat
    else:
        opt_state = opt.init(params)

        def train_step_fn(params, opt_state, batch):
            def mapped(params, batch):
                loss, grads = jax.value_and_grad(loss_fn)(params, batch)
                # Model-parallel ranks hold the full (replicated) params;
                # grads are summed so every rank applies identical updates.
                grads = jax.tree.map(
                    lambda g: jax.lax.psum(g, comm.axes), grads
                )
                return loss, grads

            loss, grads = comm.shard_map(
                mapped, in_specs=(P(), P()), out_specs=(P(), P())
            )(params, batch)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        train_step = jax.jit(train_step_fn)

    for epoch in range(args.epochs):
        t0, last = time.perf_counter(), float("nan")
        for batch in batch_iterator(train, args.batchsize, seed=epoch):
            params, opt_state, last = train_step(params, opt_state, batch)
        sync(last)  # host readback: honest timing on all backends
        if comm.rank == 0:
            print(
                f"epoch {epoch}: loss {float(last):.4f} "
                f"({time.perf_counter() - t0:.1f}s)"
            )
    if args.sharded_params:
        params = chain.materialize_params(params)

    # Evaluation on a fresh batch: teacher-forced token accuracy AND
    # greedy-decode BLEU (the reference's seq2seq reported BLEU).
    test = SyntheticSeqDataset(n=256, src_len=args.seq_len, vocab=args.vocab, seed=9)
    src = jnp.asarray(test.src)
    tgt = jnp.asarray(test.tgt)
    fwd = chain.make_forward(batch_spec=P())
    logits = fwd(params, (src, tgt))
    acc = float((logits.argmax(-1) == tgt).mean())

    # Autoregressive greedy decode (params are replicated, so this runs
    # identically on every rank; static unroll over the short target).
    from chainermn_tpu.models.seq2seq import BOS
    from chainermn_tpu.utils.metrics import corpus_bleu, strip_special

    @jax.jit
    def greedy(params, src):
        enc_p, dec_p = params
        h = encoder.apply(enc_p, src)
        toks = jnp.full((src.shape[0], 1), BOS, jnp.int32)
        for _ in range(args.seq_len):
            step_logits = decoder.apply(dec_p, h, toks)
            nxt = step_logits[:, -1].argmax(-1).astype(jnp.int32)
            toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
        return toks[:, 1:]

    hyp = np.asarray(greedy(params, src))
    refs = [strip_special(r) for r in np.asarray(tgt)]
    hyps = [strip_special(h) for h in hyp]
    bleu = corpus_bleu(refs, hyps)
    if comm.rank == 0:
        print(f"token accuracy (teacher-forced): {acc:.4f}  "
              f"BLEU (greedy): {bleu * 100:.2f}")
    return acc


if __name__ == "__main__":
    main()
