#!/usr/bin/env python
"""Channel-parallel convolution — the reference's proto-tensor-parallelism.

Reference: REF:examples/parallel_convolution/ — each rank computes a
1/size shard of every conv layer's output channels and the ranks
``allgather`` activations between layers (differentiable allgather from
REF:chainermn/functions/collective_communication.py).

TPU-native: the same algorithm inside one ``shard_map`` — each device owns
``C/n`` output channels of each conv, activations are re-assembled with
``chainermn_tpu.functions.allgather`` (backward = reduce-scatter, inserted
by AD), and the data-parallel gradient mean runs over the same mesh.  This
is the explicit-collective spelling of what GSPMD does from sharding
annotations (chainermn_tpu.parallel.sharding); both styles are supported on
purpose, as in the reference where this example existed alongside the
communicator-driven DP stack.
"""

import argparse
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

import chainermn_tpu
from chainermn_tpu import functions as F
from chainermn_tpu.datasets.toy import SyntheticImageDataset, batch_iterator


class ShardedConvNet(nn.Module):
    """A CNN whose conv layers will be instantiated with C/n channels on
    each device; activations are allgathered between layers."""

    channels: int  # per-device channels (global // n)
    n_classes: int = 10

    @nn.compact
    def __call__(self, x, comm=None):
        for i, stride in enumerate([1, 2, 2]):
            x = nn.Conv(
                self.channels, (3, 3), strides=(stride, stride), name=f"conv_{i}"
            )(x)
            x = nn.relu(x)
            if comm is not None:
                # Reassemble the full channel dimension from all devices —
                # the reference's differentiable allgather, riding ICI.
                x = F.allgather(comm, x, axis=0, tiled=False)
                # (n, B, H, W, C/n) → (B, H, W, C)
                x = jnp.concatenate([x[j] for j in range(x.shape[0])], axis=-1)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.n_classes, name="head")(x)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--communicator", default="xla_ici")
    p.add_argument("--batchsize", type=int, default=128)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--channels", type=int, default=64, help="global channels")
    p.add_argument("--train-size", type=int, default=1024)
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator(args.communicator)
    n = comm.device_size
    if args.channels % n:
        raise SystemExit(f"--channels must be divisible by {n} devices")
    model = ShardedConvNet(channels=args.channels // n)

    train = SyntheticImageDataset(n=args.train_size, shape=(16, 16, 3), seed=0)
    train = chainermn_tpu.scatter_dataset(train, comm, shuffle=True, seed=1)

    x0 = jnp.zeros((2, 16, 16, 3))

    # Each device holds the SAME parameter structure (its channel shard);
    # different init per device comes from folding the device rank into
    # the rng inside the mapped init.  Init runs inside shard_map with the
    # communicator so the traced allgathers give every layer its true
    # (gathered) input channel count.
    def device_init():
        def body():
            seed = chainermn_tpu.communicators.mesh_utils.flat_rank(comm.axes)
            params = model.init(
                jax.random.fold_in(jax.random.PRNGKey(0), seed), x0, comm=comm
            )
            return jax.tree.map(lambda x: x[None], params)

        return jax.jit(
            comm.shard_map(body, in_specs=(), out_specs=comm._world_spec)
        )()

    stacked_params = device_init()  # leading axis = device (each a real shard)

    opt = optax.adam(1e-3)
    opt_state = opt.init(stacked_params)

    def loss_fn(params, batch):
        x, y = batch
        logits = model.apply(params, x, comm=comm)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    def step(stacked_params, opt_state, batch):
        def body(params, batch):
            params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            # Channel-parallel ranks must see the SAME batch (the invariant
            # the reference's create_multi_node_iterator protects), so the
            # batch is replicated and each device's channel-shard params
            # get their own exact gradients — no averaging needed.
            return jax.tree.map(lambda g: g[None], grads), loss[None]

        batch_spec = P()  # replicated: model-parallel ranks share the batch
        grads, loss = jax.jit(
            comm.shard_map(
                body,
                in_specs=(comm._world_spec, batch_spec),
                out_specs=(comm._world_spec, comm._world_spec),
            )
        )(stacked_params, batch)
        updates, opt_state = opt.update(grads, opt_state, stacked_params)
        stacked_params = optax.apply_updates(stacked_params, updates)
        return stacked_params, opt_state, float(loss[0])

    for epoch in range(args.epochs):
        t0, last = time.perf_counter(), float("nan")
        for batch in batch_iterator(train, args.batchsize, seed=epoch):
            stacked_params, opt_state, last = step(
                stacked_params, opt_state, (batch[0], batch[1])
            )
        if comm.rank == 0:
            print(f"epoch {epoch}: loss {last:.4f} ({time.perf_counter()-t0:.1f}s)")
    return last


if __name__ == "__main__":
    main()
