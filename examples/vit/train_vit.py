#!/usr/bin/env python
"""ViT-B/16-style training with mixed data+pipeline parallelism and
double-buffered allreduce — BASELINE config #5.

Layout: the mesh's ``inter`` axis is DATA parallel, the ``intra`` axis is
the PIPELINE.  Patchify runs replicated (cheap), the transformer blocks run
through ``parallel.pipeline.spmd_pipeline`` with each pipeline rank holding
only ITS stages' parameters (genuinely sharded — the memory win the
reference's MultiNodeChainList never had), and the classifier head runs on
the pipeline output.  Gradients are combined per-role:

* stage params   → mean over the DATA axis only (each pipeline rank owns
  different weights — averaging across ``intra`` would mix stages);
* patchify/head  → summed over the pipeline axis (only one pipeline rank
  produces nonzero grads) then averaged over data — exercised via a
  ``comm.split(('inter',))`` sub-communicator, the reference's
  sub-communicator pattern for hybrid parallelism (SURVEY §2.5).

Double buffering applies the PREVIOUS step's averaged gradients
(one-step-stale, first step reduce-only) — the semantics of the
reference's _DoubleBufferingOptimizer, letting XLA overlap the DP
allreduce across the step boundary.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

import chainermn_tpu
from chainermn_tpu.utils.profiling import sync
from chainermn_tpu.datasets.toy import SyntheticImageDataset, batch_iterator
from chainermn_tpu.models.transformer import EncoderLayer
from chainermn_tpu.parallel.pipeline import (
    pipeline_1f1b_loss_and_grads,
    spmd_pipeline,
)

import flax.linen as nn


class Patchify(nn.Module):
    d_model: int
    patch: int

    @nn.compact
    def __call__(self, x):
        B = x.shape[0]
        x = nn.Conv(
            self.d_model, (self.patch, self.patch),
            strides=(self.patch, self.patch), name="proj",
        )(x)
        x = x.reshape(B, -1, self.d_model)
        pos = self.param(
            "pos", nn.initializers.normal(0.02), (1, x.shape[1], self.d_model)
        )
        return x + pos


class Blocks(nn.Module):
    """The per-pipeline-rank stage: `layers_per_stage` encoder blocks."""

    d_model: int
    n_heads: int
    d_ff: int
    layers_per_stage: int

    @nn.compact
    def __call__(self, x):
        for i in range(self.layers_per_stage):
            x = EncoderLayer(
                self.d_model, self.n_heads, self.d_ff, jnp.float32,
                name=f"block_{i}",
            )(x)
        return x


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--batchsize", type=int, default=64, help="global batch")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--patch", type=int, default=8)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--d-ff", type=int, default=256)
    p.add_argument("--layers-per-stage", type=int, default=1)
    p.add_argument("--n-classes", type=int, default=10)
    p.add_argument("--microbatches", type=int, default=2)
    p.add_argument("--train-size", type=int, default=1024)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--no-double-buffering", action="store_true")
    p.add_argument("--schedule", choices=["gpipe", "1f1b"], default="gpipe",
                   help="pipeline schedule: GPipe (AD backward) or the "
                   "memory-bounded 1F1B (explicit backward)")
    p.add_argument("--virtual-stages", type=int, default=1,
                   help="model chunks PER pipeline device (interleaved "
                   "1F1B; total depth = pp * v, bubble cut ~(v+1)/2v of "
                   "the non-interleaved schedule's; requires --schedule "
                   "1f1b and microbatches divisible by the pipeline size)")
    p.add_argument("--dp", type=int, default=None,
                   help="data-parallel ways (inter axis); rest is pipeline")
    args = p.parse_args(argv)

    comm = chainermn_tpu.create_communicator("xla_ici", inter_size=args.dp)
    dp = comm.inter_size
    pp = comm.intra_size
    dp_comm = comm.split(("inter",))  # data-parallel sub-communicator
    # Arbitrary-subgroup split (MPI_Comm_split(color, key) shape): one
    # data-parallel subgroup PER PIPELINE STAGE — the devices at intra
    # position s across all inter rows.  Stage s's grads could be
    # averaged on stage_dp[s] alone; here they sanity-check the topology.
    stage_dp = comm.split_devices([r % pp for r in range(comm.device_size)])
    # A color whose devices all live on other processes maps to None
    # (MPI_COMM_NULL) — skip those rather than AttributeError on None.
    assert all(
        sub is None or sub.device_size == dp for sub in stage_dp.values()
    )
    if comm.rank == 0:
        print(f"mesh: data={dp} x pipeline={pp} "
              f"(+{len(stage_dp)} per-stage DP subgroups); "
              f"double_buffering={not args.no_double_buffering}")

    shape = (args.image_size, args.image_size, 3)
    train = SyntheticImageDataset(
        n=args.train_size, shape=shape, n_classes=args.n_classes, seed=0
    )
    train = chainermn_tpu.scatter_dataset(train, comm, shuffle=True, seed=1)

    patchify = Patchify(args.d_model, args.patch)
    stage = Blocks(args.d_model, args.n_heads, args.d_ff, args.layers_per_stage)
    head = nn.Dense(args.n_classes)

    v = args.virtual_stages
    if v < 1:
        raise SystemExit("--virtual-stages must be >= 1")
    if v > 1 and args.schedule != "1f1b":
        raise SystemExit("--virtual-stages > 1 requires --schedule 1f1b")

    x0 = jnp.zeros((2, *shape))
    embed_params = patchify.init(jax.random.PRNGKey(0), x0)
    tok0 = patchify.apply(embed_params, x0)
    if v == 1:
        # One stage per pipeline rank, stacked on a leading axis sharded
        # over 'intra' — each device holds only its own stage's weights.
        stage_params = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[stage.init(jax.random.PRNGKey(10 + i), tok0) for i in range(pp)],
        )
    else:
        # Interleaved assignment: device d's chunk l is GLOBAL stage
        # l*pp + d; stacked (pp, v, ...), still sharded over 'intra'.
        inits = [
            stage.init(jax.random.PRNGKey(10 + i), tok0)
            for i in range(pp * v)
        ]
        stage_params = jax.tree.map(
            lambda *xs: jnp.stack(xs).reshape(v, pp, *xs[0].shape)
            .swapaxes(0, 1),
            *inits,
        )
    head_params = head.init(jax.random.PRNGKey(1), tok0.mean(axis=1))

    opt = optax.adamw(args.lr, weight_decay=0.01)
    params = {"embed": embed_params, "stages": stage_params, "head": head_params}
    opt_state = opt.init(params)
    double_buffering = not args.no_double_buffering

    def head_loss(hp, out, tgt):
        # Shared by both schedules — edit the head/loss here only.
        logits = head.apply(hp, out.mean(axis=1))
        return optax.softmax_cross_entropy_with_integer_labels(logits, tgt).mean()

    def forward_loss(params, batch):
        x, y = batch
        tokens = patchify.apply(params["embed"], x)
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), params["stages"])
        out = spmd_pipeline(
            stage.apply, mine, tokens, "intra", args.microbatches
        )
        # Pipeline output is valid on the last pipeline rank; broadcast it
        # along 'intra' so the (replicated) head computes the loss everywhere.
        out = jax.lax.psum(out, "intra")
        return head_loss(params["head"], out, y)

    def reduce_grads(grads):
        # Stage grads: DP-mean only. Embed/head grads: collect over the
        # pipeline axis (one owner each) then DP-mean.
        stages = dp_comm.allreduce_grad(grads["stages"])
        embed = jax.tree.map(lambda g: jax.lax.psum(g, "intra"), grads["embed"])
        head_g = jax.tree.map(lambda g: jax.lax.psum(g, "intra"), grads["head"])
        embed = dp_comm.allreduce_grad(embed)
        head_g = dp_comm.allreduce_grad(head_g)
        return {"embed": embed, "stages": stages, "head": head_g}

    def forward_loss_1f1b(params, batch):
        # 1F1B: the head rides inside the schedule (loss_params), the
        # patchify embedding hangs off it via jax.vjp on the input
        # cotangents — each microbatch's backward starts the tick its
        # forward ends, bounding live activations to O(pipeline depth).
        x, y = batch
        tokens, embed_vjp = jax.vjp(
            lambda ep: patchify.apply(ep, x), params["embed"]
        )
        mine = jax.tree.map(lambda p: jnp.squeeze(p, 0), params["stages"])
        if v > 1:
            from chainermn_tpu.parallel.pipeline import (
                pipeline_interleaved_1f1b_loss_and_grads,
            )

            loss, sg, hg, gtok = pipeline_interleaved_1f1b_loss_and_grads(
                stage.apply, head_loss, mine, tokens, y, "intra",
                args.microbatches, v, loss_params=params["head"],
                with_input_grads=True,
            )
        else:
            loss, sg, hg, gtok = pipeline_1f1b_loss_and_grads(
                stage.apply, head_loss, mine, tokens, y, "intra",
                args.microbatches, loss_params=params["head"],
                with_input_grads=True,
            )
        gtok = jax.lax.psum(gtok, "intra")   # stage-0 owner
        hg = jax.lax.psum(hg, "intra")       # last-stage owner
        (eg,) = embed_vjp(gtok)
        sg = jax.tree.map(lambda a: jnp.expand_dims(a, 0), sg)
        return loss, {"embed": eg, "stages": sg, "head": hg}

    def step(params, opt_state, prev_grads, step_idx, batch):
        def body(params, prev_grads, batch):
            if args.schedule == "1f1b":
                loss, grads = forward_loss_1f1b(params, batch)
                loss = jax.lax.pmean(loss, "inter")
                # embed/head grads are already psum-collected over the
                # pipeline axis inside forward_loss_1f1b; DP-mean the rest.
                grads = {
                    "embed": dp_comm.allreduce_grad(grads["embed"]),
                    "stages": dp_comm.allreduce_grad(grads["stages"]),
                    "head": dp_comm.allreduce_grad(grads["head"]),
                }
                return loss, grads
            loss, grads = jax.value_and_grad(forward_loss)(params, batch)
            loss = jax.lax.pmean(loss, comm.axes)
            grads = reduce_grads(grads)
            return loss, grads

        spec = {"embed": P(), "stages": P("intra"), "head": P()}
        loss, grads = comm.shard_map(
            body,
            in_specs=(spec, spec, P("inter")),
            out_specs=(P(), spec),
        )(params, prev_grads, batch)

        apply_grads = grads
        if double_buffering:
            apply_grads, keep = prev_grads, grads
        else:
            keep = grads
        updates, opt_state = opt.update(apply_grads, opt_state, params)
        # Double buffering: step 0 has no previous grads — reduce only.
        scale = jnp.where(step_idx == 0, 0.0, 1.0) if double_buffering else 1.0
        updates = jax.tree.map(lambda u: u * scale, updates)
        params = optax.apply_updates(params, updates)
        return params, opt_state, keep, loss

    step = jax.jit(step, static_argnames=())

    prev_grads = jax.tree.map(jnp.zeros_like, params)
    step_idx = 0
    for epoch in range(args.epochs):
        t0, n_seen, last = time.perf_counter(), 0, float("nan")
        for batch in batch_iterator(train, args.batchsize, seed=epoch):
            params, opt_state, prev_grads, last = step(
                params, opt_state, prev_grads, step_idx, batch
            )
            step_idx += 1
            n_seen += batch[0].shape[0]
        sync(last)  # host readback: honest timing on all backends
        if comm.rank == 0:
            print(
                f"epoch {epoch}: loss {float(last):.4f} "
                f"({n_seen/(time.perf_counter()-t0):,.0f} img/s)"
            )
    return float(last)


if __name__ == "__main__":
    main()
