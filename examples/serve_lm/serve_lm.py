#!/usr/bin/env python
"""Minimal serving demo: train a toy LM briefly, then serve it.

Two halves, deliberately end-to-end:

1. **Train** a small :class:`TransformerLM` on the synthetic successor
   task (next token = current + 1 mod vocab) for a handful of steps —
   enough that greedy decoding visibly continues the pattern, so the
   served output is checkable by eye.
2. **Serve** it through the full stack: requests with different prompt
   lengths enter the :class:`ServeFrontend` queue, the continuous-
   batching scheduler interleaves their prefill and decode iterations,
   tokens stream back through callbacks as they are sampled, and the
   Reporter's gauges/counters show queue depth and KV-cache occupancy.

Runs on anything (CPU included): the decode data plane is plain jnp.

Usage::

    python examples/serve_lm/serve_lm.py                 # defaults
    python examples/serve_lm/serve_lm.py --requests 8 --new-tokens 24
"""

import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax

from chainermn_tpu.models.transformer import TransformerLM
from chainermn_tpu.observability import Reporter
from chainermn_tpu.serving import (
    ContinuousBatchingScheduler,
    EngineConfig,
    InferenceEngine,
    SamplingParams,
    ServeFrontend,
)


def train_successor_lm(model, vocab, steps, batch, seq_len, lr=1e-2):
    rng = np.random.RandomState(0)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, seq_len), jnp.int32)
    )
    opt = optax.adam(lr)
    state = opt.init(params)

    @jax.jit
    def step(params, state, tok, tgt):
        def loss_fn(p):
            logits = model.apply(p, tok)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, state = opt.update(grads, state)
        return optax.apply_updates(params, updates), state, loss

    loss = float("nan")
    for _ in range(steps):
        start = rng.randint(0, vocab, size=(batch, 1))
        tok = (start + np.arange(seq_len)[None, :]) % vocab
        tok = jnp.asarray(tok, jnp.int32)
        tgt = (tok + 1) % vocab
        params, state, loss = step(params, state, tok, tgt)
    return params, float(loss)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--d-ff", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--train-steps", type=int, default=200)
    p.add_argument("--requests", type=int, default=6)
    p.add_argument("--new-tokens", type=int, default=12)
    p.add_argument("--max-batch", type=int, default=4)
    p.add_argument("--block-size", type=int, default=8,
                   help="KV page size in tokens")
    p.add_argument("--n-blocks", type=int, default=128,
                   help="KV pages in the pool (shrink to watch "
                        "preemption-by-eviction kick in)")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; >0 samples (seeded per request)")
    args = p.parse_args(argv)

    max_len = 128
    model = TransformerLM(
        vocab=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        d_ff=args.d_ff, n_layers=args.layers, max_len=max_len,
    )
    params, loss = train_successor_lm(
        model, args.vocab, args.train_steps, batch=16, seq_len=32
    )
    print(f"trained {args.train_steps} steps, final loss {loss:.3f}")

    reporter = Reporter()
    engine = InferenceEngine(model, params, EngineConfig(
        block_size=args.block_size, n_blocks=args.n_blocks,
        max_len=max_len, max_batch=args.max_batch,
    ))
    sched = ContinuousBatchingScheduler(engine, reporter=reporter)
    frontend = ServeFrontend(sched, max_queue=args.requests + 1)

    rng = np.random.RandomState(1)
    streams = {}

    def on_token(rid, tok):
        streams.setdefault(rid, []).append(tok)

    handles = []
    for i in range(args.requests):
        start = int(rng.randint(0, args.vocab))
        plen = int(rng.randint(3, 9))
        prompt = [(start + j) % args.vocab for j in range(plen)]
        h = frontend.submit(
            prompt, args.new_tokens,
            sampling=SamplingParams(temperature=args.temperature,
                                    seed=i),
            on_token=on_token,
        )
        handles.append((prompt, h))
    frontend.run_until_idle()

    for prompt, h in handles:
        want = [(prompt[-1] + 1 + j) % args.vocab
                for j in range(len(h.tokens))]
        tag = "" if args.temperature else (
            " <- successor" if h.tokens == want else " (off-pattern)"
        )
        print(f"req {h.request_id}: prompt {prompt} -> {h.tokens}{tag}")
        assert streams[h.request_id] == h.tokens  # streaming == final

    summary = reporter.summary()
    print("engine:", json.dumps({
        k: v for k, v in engine.stats().items()
        if k in ("prefill_compiles", "decode_compiles",
                 "tokens_prefilled", "tokens_decoded")
    }))
    print("gauges:", json.dumps(
        {k: d["value"] for k, d in summary["gauges"].items()}
    ))
    print("counters:", json.dumps(summary["counters"]))


if __name__ == "__main__":
    main()
