"""Build hook for the native host-buffer library.

The reference compiled its communication binding inside setup.py (the
Cython NCCL module was part of the install, SURVEY §2.1); the TPU-native
equivalent is ``csrc/hostbuf.cpp`` — crc32c, threaded pack/unpack, the
MPMC ring queue — loaded via ctypes.  ``pip install .`` / ``pip wheel .``
compiles it into ``chainermn_tpu/_native/libhostbuf.so`` so installed
trees get the native path without a toolchain at import time; the
in-repo on-demand compile and the pure-Python fallbacks remain for
source checkouts and toolchain-less hosts (utils/native.py's chain).
"""

import os
import subprocess
import sys

from setuptools import setup
from setuptools.command.build_py import build_py


class build_py_with_native(build_py):
    def run(self):
        super().run()
        here = os.path.dirname(os.path.abspath(__file__))
        src = os.path.join(here, "csrc", "hostbuf.cpp")
        dest_dir = os.path.join(self.build_lib, "chainermn_tpu", "_native")
        os.makedirs(dest_dir, exist_ok=True)
        out = os.path.join(dest_dir, "libhostbuf.so")
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
            "-o", out, src, "-lpthread",
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=300)
        except Exception as e:  # graceful: the Python fallbacks still work
            print(
                "warning: native hostbuf build failed "
                f"({type(e).__name__}); the installed package will use "
                "the pure-Python fallbacks (utils/native.py chain)",
                file=sys.stderr,
            )


setup(cmdclass={"build_py": build_py_with_native})
