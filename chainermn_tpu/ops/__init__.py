"""Hand-written TPU kernels and memory transforms for the hot ops.

The reference had no kernel layer — its math was Chainer's and its only
"kernels" were pack/unpack copies (SURVEY §1 notes).  On TPU two ops
earn hand treatment: attention (the Pallas flash kernels — FLOPs and
O(S²) memory) and the LM loss head (the chunked fused cross-entropy —
a custom-vjp memory transform that never materializes the logits).
Everything else XLA fuses well.

The serving tier adds a third: paged single-query decode attention
(``decode_attention``) — gather-by-block-table K/V plus the page-write
scatters, the inference analogue of flash attention's training role.
"""

from chainermn_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    make_flash_attention_fn,
)
from chainermn_tpu.ops.fused_ce import (  # noqa: F401
    fused_cross_entropy,
    fused_cross_entropy_with_lse,
)
from chainermn_tpu.ops.decode_attention import (  # noqa: F401
    invalid_block,
    paged_attention_decode,
    write_prompt_pages,
    write_token_pages,
)
