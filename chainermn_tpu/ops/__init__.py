"""Hand-written TPU kernels (Pallas) for the hot ops.

The reference had no kernel layer — its math was Chainer's and its only
"kernels" were pack/unpack copies (SURVEY §1 notes).  On TPU the hot op
worth hand-scheduling is attention; everything else XLA fuses well.
"""

from chainermn_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    make_flash_attention_fn,
)
from chainermn_tpu.ops.fused_ce import (  # noqa: F401
    fused_cross_entropy,
    fused_cross_entropy_with_lse,
)
