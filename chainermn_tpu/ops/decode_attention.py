"""Paged single-query decode attention — the serving data plane's hot op.

Training attention (``ops.flash_attention``) streams a *contiguous*
(B, S, H, D) K/V; serving cannot afford contiguity: sequences in a
continuously-batched decode step have wildly different lengths, grow one
token per iteration, and are admitted/evicted mid-flight.  The
PagedAttention answer (vLLM, arXiv:2309.06180) is to store K/V in
fixed-size *pages* indexed by a per-sequence block table, so memory is
allocated in O(block_size) quanta and the attention kernel follows the
table.

This module is the functional core shared by the serving engine and the
cached-KV model path (:mod:`chainermn_tpu.models.transformer`):

* :func:`paged_attention_decode` — one-query-per-sequence attention over
  paged K/V.  The reference-quality jnp lowering (gather pages → masked
  softmax) is the **CPU-safe fallback** the tier-1 suite runs under
  ``JAX_PLATFORMS=cpu``; on TPU the gather is chunked along the context
  by a tuned ``block_ctx`` (``tuning.decode_cache_key``) to bound the
  transient gathered buffer — chunking a gather is a pure data-movement
  choice, so the numerics are bit-identical to the one-shot gather.
* :func:`write_prompt_pages` / :func:`write_token_pages` — the scatter
  writes that land prefill (whole prompt) and decode (one token per
  sequence) K/V into the pages.

Invalid-slot convention: block-table entries that do not name a real
page carry the value ``n_pages`` (one past the last page).  That is
out-of-bounds *high*, which JAX scatters **drop** and gathers **fill**
with zeros; negative sentinels would silently wrap (`a[-1]`) and corrupt
the last page.  Padding rows/positions therefore cost nothing and touch
nothing — no masks on the write side, one mask on the read side.

All reductions here are per-sequence: nothing crosses the batch
dimension and nothing is a collective, which is what keeps (a) batched
decode bit-identical to single-request decode and (b) the decode step
collective-free on the data plane (pinned by the serving lint fixture
and ``tests/golden/serving_decode_census.json``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def invalid_block(n_pages: int) -> int:
    """The sentinel block id for unallocated table slots: out-of-bounds
    HIGH (dropped by scatter, zero-filled by gather).  Never use -1 —
    negative indices wrap in JAX and would alias the last real page."""
    return int(n_pages)


def _positions_to_pages(block_tables, positions, page_size: int,
                        n_pages: int):
    """Map token positions to (page_id, slot) through the block table.

    ``block_tables``: (B, W) int32, invalid entries == ``n_pages``.
    ``positions``: (B, P) int32 token positions; positions that are
    negative or beyond the table's reach resolve to the invalid page.
    Returns ``(page_id, slot)``, both (B, P) int32.
    """
    W = block_tables.shape[1]
    valid = (positions >= 0) & (positions < W * page_size)
    safe = jnp.clip(positions, 0, W * page_size - 1)
    page = jnp.take_along_axis(block_tables, safe // page_size, axis=1)
    page = jnp.where(valid, page, invalid_block(n_pages))
    return page.astype(jnp.int32), (safe % page_size).astype(jnp.int32)


def write_prompt_pages(k_pages, new_k, block_tables, seq_lens):
    """Scatter a whole prompt's K (or V) into the pages.

    ``k_pages``: (N, page_size, Hkv, D); ``new_k``: (B, S, Hkv, D);
    ``seq_lens``: (B,) valid prompt lengths — positions ``t >= seq_lens[b]``
    (padding up to the bucket) are routed to the invalid page and dropped.
    Returns the updated pages.
    """
    N, page_size = k_pages.shape[0], k_pages.shape[1]
    B, S = new_k.shape[0], new_k.shape[1]
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    pos = jnp.where(pos < seq_lens[:, None], pos, -1)
    page, slot = _positions_to_pages(block_tables, pos, page_size, N)
    return k_pages.at[page, slot].set(
        new_k.astype(k_pages.dtype), mode="drop"
    )


def write_chunk_pages(k_pages, new_k, block_tables, start_lens):
    """Scatter a T-token chunk's K (or V) per sequence into the pages.

    ``new_k``: (B, T, Hkv, D) — token ``t`` of row ``b`` lands at position
    ``start_lens[b] + t``.  Rows with ``start_lens[b] < 0`` (padding slots
    in a chunk bucket) write nothing; positions beyond the table's reach
    route to the invalid page and are dropped, so a chunk may safely
    over-run a row's real suffix (speculative drafts, bucket padding) —
    every such slot is beyond the row's masked context and is rewritten
    by a later step before the mask can expose it.
    """
    N, page_size = k_pages.shape[0], k_pages.shape[1]
    B, T = new_k.shape[0], new_k.shape[1]
    pos = start_lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None]
    pos = jnp.where(start_lens[:, None] >= 0, pos, -1)
    page, slot = _positions_to_pages(block_tables, pos, page_size, N)
    return k_pages.at[page, slot].set(
        new_k.astype(k_pages.dtype), mode="drop"
    )


def write_token_pages(k_pages, new_k, block_tables, seq_lens):
    """Scatter one decode token's K (or V) per sequence into the pages.

    ``new_k``: (B, 1, Hkv, D) — the token at position ``seq_lens[b]``
    (the context length *before* this token).  Rows with
    ``seq_lens[b] < 0`` (padding slots in a decode bucket) write nothing.
    A T=1 chunk write is exactly this, so delegate — one lowering, one
    set of numerics.
    """
    return write_chunk_pages(k_pages, new_k, block_tables, seq_lens)


def paged_attention_decode(
    q,
    k_pages,
    v_pages,
    block_tables,
    seq_lens,
    *,
    block_ctx: Optional[int] = None,
    k_scales=None,
    v_scales=None,
):
    """Single-query attention over paged K/V.

    ``q``: (B, 1, H, D) the decode step's query; ``k_pages``/``v_pages``:
    (N, page_size, Hkv, D) with Hkv dividing H (GQA/MQA); ``block_tables``:
    (B, W) int32 page ids (invalid == N); ``seq_lens``: (B,) the number of
    valid cache positions INCLUDING the just-written current token.

    Returns (B, 1, H, D) in ``q.dtype``.  The masked-softmax numerics
    mirror the dense training path in
    :class:`~chainermn_tpu.models.transformer.MultiHeadAttention`
    bit-for-bit at fp32: masked keys get ``finfo(float32).min`` logits
    (exactly-zero weights), softmax accumulates in fp32, and every
    reduction stays inside one sequence's row.

    ``block_ctx``: gather the context in chunks of this many *pages*
    (tuned on TPU via :func:`chainermn_tpu.tuning.lookup_decode_block_ctx`)
    to bound the transient (B, ctx, Hkv, D) buffer; ``None`` gathers in
    one shot.  Chunking only the gather leaves the attention numerics
    untouched.

    ``k_scales``/``v_scales``: (N, page_size, Hkv) fp32 per-token-per-head
    scales for quantized (int8) pages — gathered through the same block
    table and multiplied back in after the gather (``kv_dtype`` in
    docs/serving.md).  ``None`` = pages are already in a compute dtype.
    """
    B, one, H, D = q.shape
    if one != 1:
        raise ValueError(
            f"paged_attention_decode consumes one query per sequence, got "
            f"a length-{one} chunk"
        )
    return paged_attention_chunk(
        q, k_pages, v_pages, block_tables, seq_lens - 1,
        block_ctx=block_ctx, k_scales=k_scales, v_scales=v_scales,
    )


def paged_attention_chunk(
    q,
    k_pages,
    v_pages,
    block_tables,
    start_lens,
    *,
    block_ctx: Optional[int] = None,
    k_scales=None,
    v_scales=None,
):
    """Multi-query causal attention over paged K/V — the verify/suffix step.

    ``q``: (B, T, H, D) — T queries per sequence at consecutive positions
    ``start_lens[b] + t``; query ``t`` attends to cache positions
    ``< start_lens[b] + t + 1`` (its own freshly-written slot included),
    which is exactly the per-query causal bound a sequential decode would
    see.  Rows with ``start_lens[b] < 0`` are padding: everything is
    masked and the output row is garbage that callers never read.

    ``paged_attention_decode`` is the T=1 special case and delegates
    here, so single-token decode and multi-token verify share one
    lowering — bit-identical numerics at T=1 by construction.

    ``k_scales``/``v_scales``: (N, page_size, Hkv) fp32 scales when the
    pages are int8 (``kv_dtype``).  They ride the SAME gather (block
    table, fill value 0) so an invalid slot dequantizes to exactly the
    zeros the unquantized path gathers; the dequantized context is in
    ``q.dtype`` before any einsum, so everything downstream of the
    gather is byte-identical program structure to the full-precision
    path.

    Returns (B, T, H, D) in ``q.dtype``.
    """
    B, T, H, D = q.shape
    N, page_size, Hkv, _ = k_pages.shape
    if H % Hkv:
        raise ValueError(f"n_kv_heads ({Hkv}) must divide n_heads ({H})")
    if (k_scales is None) != (v_scales is None):
        raise ValueError("k_scales and v_scales must be given together")
    W = block_tables.shape[1]

    def gather(pages, tables):
        g = jnp.take(pages, tables, axis=0, mode="fill", fill_value=0)
        return g.reshape(B, tables.shape[1] * page_size, Hkv, D)

    def gather_deq(pages, scales, tables):
        g = gather(pages, tables)
        if scales is None:
            return g
        from chainermn_tpu.communicators.quant import dequantize_kv

        s = jnp.take(scales, tables, axis=0, mode="fill", fill_value=0)
        s = s.reshape(B, tables.shape[1] * page_size, Hkv)
        return dequantize_kv(g, s, q.dtype)

    if block_ctx is None or block_ctx >= W:
        k = gather_deq(k_pages, k_scales, block_tables)
        v = gather_deq(v_pages, v_scales, block_tables)
    else:
        # Chunked gather: identical concatenated tensor, bounded transient.
        ks, vs = [], []
        for start in range(0, W, block_ctx):
            t = block_tables[:, start:start + block_ctx]
            ks.append(gather_deq(k_pages, k_scales, t))
            vs.append(gather_deq(v_pages, v_scales, t))
        k = jnp.concatenate(ks, axis=1)
        v = jnp.concatenate(vs, axis=1)

    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    scale = 1.0 / np.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    ctx = k.shape[1]
    bounds = start_lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None] + 1
    mask = (jnp.arange(ctx)[None, None] < bounds[:, :, None])[:, None]
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits.astype(jnp.float32)).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)
