"""Memory-efficient (chunked) softmax cross-entropy against a tied
embedding — the LM loss head.

The reference framework computed ``softmax_cross_entropy`` on fully
materialized logits (Chainer's ``F.softmax_cross_entropy`` over a
``(B*S, V)`` array — REF:chainermn examples seq2seq loss path).  That is
fine at seq2seq scale; at long-context LM scale the logits are the
single largest tensor in the step: B=8, S=4096, V=32768 is 4 GiB in
fp32 — more than the activations of the entire transformer stack — and
the autodiff residual doubles it.

TPU-native design: never materialize the full logit matrix.  Tokens are
processed in row chunks; each chunk's logits live only inside the chunk
computation (bf16 MXU matmul, fp32 accumulation), reduced immediately to
the scalar loss contribution plus a per-token log-sum-exp.  The backward
pass recomputes each chunk's logits from the saved ``lse`` (one fp32
scalar per token — the flash-attention residual trick applied to the
vocabulary axis) and accumulates the embedding gradient chunk by chunk
in a ``lax.scan`` carry.  Peak extra memory is ``chunk x V`` fp32
(default 64 MiB at V=32k) instead of ``N x V``.

The same per-chunk (max, sum-exp) reduction is the building block of the
vocab-parallel (tensor-parallel) cross-entropy in
``chainermn_tpu.parallel.sharding``: there the V axis is sharded and the
two reductions become ``psum``/``pmax`` over the model axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

#: the static default row chunk — the cache-miss / off-TPU fallback, and
#: a mandatory member of the autotuner's search space (a tuned chunk can
#: never lose to it).
DEFAULT_CHUNK = 512


def _resolve_chunk(chunk, N: int, V: int, D: int, dtype) -> int:
    """``chunk=None`` → the tuned chunk from the persistent autotune
    cache (``chainermn_tpu.tuning``; populated only by the explicit CLI /
    ``bench.py --autotune``), falling back to :data:`DEFAULT_CHUNK` on a
    miss.  Inert under pytest and off-TPU — there None always resolves
    to the static default, bit-identical to the pre-tuning behavior.
    An explicit ``chunk`` bypasses the cache."""
    if chunk is not None:
        return int(chunk)
    from chainermn_tpu.tuning.autotune import lookup_ce_chunk

    tuned = lookup_ce_chunk(N=N, V=V, D=D, dtype=dtype)
    return int(tuned) if tuned else DEFAULT_CHUNK


def _pick_chunk(n: int, chunk: int) -> int:
    """Largest divisor of ``n`` that is <= chunk (scan needs equal-size
    chunks; a ragged tail would need masking for no benefit since callers
    control N = B*S)."""
    chunk = min(chunk, n)
    while n % chunk:
        chunk -= 1
    return chunk


def _chunk_logits(h_c, emb):
    """(C, D) x (V, D) -> (C, V) fp32 logits: bf16 operands on the MXU,
    fp32 accumulation."""
    return jax.lax.dot_general(
        h_c.astype(jnp.bfloat16),
        emb.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


class LocalVocabStrategy:
    """Reduction strategy for a FULL vocabulary on one device: every
    merge is the identity and every label row is locally resolvable.

    The vocab-parallel cross-entropy
    (``parallel.sharding.vocab_parallel_cross_entropy``) swaps in a
    strategy whose merges are ``pmax``/``psum`` over the model axis and
    whose label resolution is ownership-masked — same math, one
    implementation of the chunked scan to maintain."""

    def merge_max(self, m):
        return m

    def merge_sum(self, s):
        return s

    def merge_pick(self, p):
        return p

    def reduce_dh(self, dh):
        return dh

    def label_local(self, labels):
        """(local row index, ownership mask).  Locally every valid label
        is owned; invalid (< 0) labels are owned nowhere."""
        return jnp.maximum(labels, 0), labels >= 0


def ce_scan_fwd(hidden, embedding, labels, chunk, strat):
    """Chunked CE forward: sum over valid tokens of ``lse - picked`` plus
    the valid count and per-token lse, never holding more than one
    ``(chunk, V_local)`` logit tile.  ``strat`` supplies the cross-shard
    merges (identity for the local case)."""
    N = hidden.shape[0]
    C = _pick_chunk(N, chunk)
    h_chunks = hidden.reshape(N // C, C, hidden.shape[1])
    l_chunks = labels.reshape(N // C, C)

    def body(carry, hc_lc):
        loss_sum, n_valid = carry
        h_c, l_c = hc_lc
        logits = _chunk_logits(h_c, embedding)  # (C, V_local) fp32
        m = strat.merge_max(jnp.max(logits, axis=-1))
        se = strat.merge_sum(
            jnp.sum(jnp.exp(logits - m[:, None]), axis=-1)
        )
        lse_c = m + jnp.log(se)
        valid = l_c >= 0
        idx, owner = strat.label_local(l_c)
        picked_s = jnp.take_along_axis(logits, idx[:, None], axis=-1)[:, 0]
        picked = strat.merge_pick(jnp.where(owner, picked_s, 0.0))
        tok_loss = jnp.where(valid, lse_c - picked, 0.0)
        return (
            (loss_sum + tok_loss.sum(),
             n_valid + valid.sum().astype(jnp.float32)),
            lse_c,
        )

    (loss_sum, n_valid), lse = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)), (h_chunks, l_chunks)
    )
    return loss_sum, n_valid, lse.reshape(N)


def ce_scan_bwd(hidden, embedding, labels, lse, g_loss, g_lse, chunk,
                strat):
    """Chunked CE backward: recompute each chunk's logits from the saved
    lse (remat), assemble ``dlogits = g*(p - onehot) + g_lse*p``, and
    accumulate ``d embedding`` in the scan carry.  Returns (dh, d_emb) in
    the input dtypes."""
    N, D = hidden.shape
    C = _pick_chunk(N, chunk)
    h_chunks = hidden.reshape(N // C, C, D)
    l_chunks = labels.reshape(N // C, C)
    lse_chunks = lse.reshape(N // C, C)
    g_lse_chunks = g_lse.reshape(N // C, C)

    def body(d_emb, args):
        h_c, l_c, lse_c, g_lse_c = args
        logits = _chunk_logits(h_c, embedding)  # recompute (remat)
        p = jnp.exp(logits - lse_c[:, None])    # softmax (local shard)
        valid = (l_c >= 0)[:, None]
        idx, owner = strat.label_local(l_c)
        onehot = jax.nn.one_hot(
            idx, logits.shape[1], dtype=p.dtype
        ) * owner[:, None]
        # d loss_sum / d logits = (p - onehot) per valid token;
        # d lse / d logits = p (lse is an output in its own right).
        dlogits = jnp.where(
            valid, g_loss * (p - onehot), 0.0
        ) + g_lse_c[:, None] * p
        dh_c = strat.reduce_dh(jnp.dot(
            dlogits.astype(jnp.bfloat16), embedding.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        ))
        d_emb = d_emb + jax.lax.dot_general(
            dlogits.astype(jnp.bfloat16), h_c.astype(jnp.bfloat16),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return d_emb, dh_c

    d_emb, dh = jax.lax.scan(
        body,
        jnp.zeros(embedding.shape, jnp.float32),
        (h_chunks, l_chunks, lse_chunks, g_lse_chunks),
    )
    return (
        dh.reshape(N, D).astype(hidden.dtype),
        d_emb.astype(embedding.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_ce_sum(hidden, embedding, labels, chunk):
    """Sum over valid tokens of ``lse(logits_i) - logits_i[label_i]`` and
    the valid-token count.  ``labels < 0`` are ignored (0 loss, 0 grad).

    hidden: (N, D); embedding: (V, D); labels: (N,) int32.
    Returns (loss_sum fp32, n_valid fp32, lse (N,) fp32).
    """
    return ce_scan_fwd(hidden, embedding, labels, chunk,
                       LocalVocabStrategy())


def _fused_ce_vjp_fwd(hidden, embedding, labels, chunk):
    out = ce_scan_fwd(hidden, embedding, labels, chunk,
                      LocalVocabStrategy())
    return out, (hidden, embedding, labels, out[2])


def _fused_ce_vjp_bwd(chunk, res, cots):
    hidden, embedding, labels, lse = res
    g_loss, _g_nvalid, g_lse = cots
    dh, d_emb = ce_scan_bwd(
        hidden, embedding, labels, lse, g_loss, g_lse, chunk,
        LocalVocabStrategy(),
    )
    return dh, d_emb, None


_fused_ce_sum.defvjp(_fused_ce_vjp_fwd, _fused_ce_vjp_bwd)


def fused_cross_entropy(hidden, embedding, labels, *, chunk=None):
    """Mean softmax cross-entropy of ``hidden @ embedding.T`` against
    ``labels``, computed without materializing the ``(N, V)`` logit
    matrix (peak extra memory ``chunk x V`` fp32).

    * ``hidden`` — ``(..., D)`` final hidden states (any float dtype; the
      logit matmuls run bf16 on the MXU with fp32 accumulation).
    * ``embedding`` — ``(V, D)`` tied output embedding (``nn.Embed``'s
      ``embedding`` table — the ``embed.attend`` weight).
    * ``labels`` — ``(...,)`` int32; negative labels are ignored
      (0 loss, 0 grad) — the packed/padded-sequence convention shared
      with the flash kernels' segment masks.

    Returns the scalar mean over valid tokens (0.0 when none are valid).
    Differentiable in ``hidden`` and ``embedding``; the backward pass
    recomputes each chunk's logits from a saved per-token log-sum-exp
    (4 bytes/token) instead of storing them.

    ``chunk`` — rows per scan tile.  The default (None) resolves to the
    autotuned chunk for this (device kind, dtype, N, V, D) when the
    persistent tune cache has one (see docs/tuning.md), else the static
    :data:`DEFAULT_CHUNK` — always the static default off-TPU and under
    pytest.  Passing an int pins it.
    """
    h2, l2 = _validate_and_flatten(hidden, embedding, labels, chunk)
    chunk = _resolve_chunk(
        chunk, h2.shape[0], embedding.shape[0], h2.shape[1], hidden.dtype
    )
    loss_sum, n_valid, _lse = _fused_ce_sum(h2, embedding, l2, chunk)
    return loss_sum / jnp.maximum(n_valid, 1.0)


def fused_cross_entropy_with_lse(hidden, embedding, labels, *, chunk=None):
    """:func:`fused_cross_entropy` variant also returning the per-token
    log-sum-exp ``(N,)`` — the z-loss / logit-scale diagnostic, and the
    merge quantity for vocab-sharded composition."""
    h2, l2 = _validate_and_flatten(hidden, embedding, labels, chunk)
    chunk = _resolve_chunk(
        chunk, h2.shape[0], embedding.shape[0], h2.shape[1], hidden.dtype
    )
    loss_sum, n_valid, lse = _fused_ce_sum(h2, embedding, l2, chunk)
    return loss_sum / jnp.maximum(n_valid, 1.0), lse


def _validate_and_flatten(hidden, embedding, labels, chunk):
    if chunk is not None and int(chunk) < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    D = hidden.shape[-1]
    h2 = hidden.reshape(-1, D)
    l2 = labels.reshape(-1)
    if h2.shape[0] != l2.shape[0]:
        raise ValueError(
            f"hidden rows {h2.shape[0]} != labels {l2.shape[0]}"
        )
    if embedding.shape[-1] != D:
        raise ValueError(
            f"embedding dim {embedding.shape[-1]} != hidden dim {D}"
        )
    return h2, l2


def naive_cross_entropy(hidden, embedding, labels):
    """Materialized-logits oracle (tests only): same math, full ``(N, V)``
    fp32 logits."""
    logits = _chunk_logits(hidden.reshape(-1, hidden.shape[-1]), embedding)
    l2 = labels.reshape(-1)
    valid = l2 >= 0
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, jnp.maximum(l2, 0)[:, None], axis=-1
    )[:, 0]
    tok = jnp.where(valid, lse - picked, 0.0)
    return tok.sum() / jnp.maximum(valid.sum().astype(jnp.float32), 1.0)
