"""Flash attention as a Pallas TPU kernel.

Blockwise attention with online softmax: Q blocks stream over KV blocks
held in VMEM, accumulating unnormalized outputs with running max/denominator
— O(S) memory instead of O(S²), fp32 accumulation, MXU matmuls via
``jnp.dot(..., preferred_element_type=float32)``.  The same math as
``parallel.ring_attention`` — there the blocks live on *different chips*
and rotate over ICI; here they live in *HBM* and stream through VMEM.  A
sequence-parallel model composes both: ring outside, this kernel inside
each block pair.

Causal skipping: grid programs whose whole K block is in the future of the
whole Q block write nothing and skip the matmuls (``pl.when``), so the
causal kernel does ~half the FLOPs, like the CUDA flash-attention kernels.

Differentiable: a ``custom_vjp`` with explicit FlashAttention-2-style
backward kernels — the forward saves one fp32 log-sum-exp per row, and the
dQ / dK+dV kernels recompute probabilities blockwise from it, so neither
pass ever materializes the S×S matrix.  Measured on a v5e-class chip at
S=8192/bf16/D=128 (slope-timed; see docs/performance.md "Measuring"):
forward ~67 TFLOP/s (4.5-4.9x XLA's materialized-logits attention),
forward+backward 4.4x, backward alone ~81 TFLOP/s — at the chip's own
sustained matmul roofline — with O(S) memory in both passes.

Optional segment-id masks support packed-sequence training: tokens attend
only within their own segment, and padding rows produce zero output and
zero gradients in both passes.

Falls back to interpreter mode off-TPU (tests run the same kernel code on
the CPU mesh) and to plain XLA attention for shapes the kernel does not
cover (head_dim > 256 or unaligned sequence lengths).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

_NEG_INF = -1e30


def _block_mask(shape, causal, q_start, k_start, qs_ref, ks_ref,
                window=None):
    """Combined (block_q, block_k) boolean mask for one grid tile — the
    causal triangle, the sliding-window band (query attends only its
    ``window`` most recent positions, itself included — Mistral-style
    local attention), AND segment-id equality (packed sequences attend
    only within their own segment).  None when nothing masks."""
    m = None
    if causal or window is not None:
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        m = q_pos >= k_pos
        if window is not None:
            m = m & (q_pos - k_pos < window)
    if qs_ref is not None:
        seg = qs_ref[0] == ks_ref[0].reshape(1, -1)   # (bq,1) == (1,bk)
        m = seg if m is None else (m & seg)
    return m


def _band_live(causal, window, q_start, block_q, k_start, block_k):
    """Whole-block skip condition: does this (q block, k block) tile
    intersect the attention band at all?  Causal bound above (no k after
    the last query), window bound below (no k more than ``window - 1``
    positions before the first live query of the block)."""
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1
    if window is not None:
        run = jnp.logical_and(
            run, k_start + block_k - 1 >= q_start - (window - 1)
        )
    return run


def _attn_kernel(
    *refs,
    scale: float, causal: bool, segmented: bool, block_q: int, block_k: int,
    window=None,
):
    if segmented:
        (q_ref, k_ref, v_ref, qs_ref, ks_ref,
         o_ref, lse_ref, acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
        qs_ref = ks_ref = None
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = iq * block_q
    k_start = ik * block_k

    # Whole-block skip: K block past the causal bound OR entirely before
    # the sliding window's reach.
    run = _band_live(causal, window, q_start, block_q, k_start, block_k)

    @pl.when(run)
    def _():
        # MXU-native matmuls: operands stay in their input dtype (bf16 on
        # the training path — one MXU pass) with fp32 accumulation via
        # preferred_element_type; only the softmax runs in fp32.
        q = q_ref[0]                              # (block_q, D)
        k = k_ref[0]                              # (block_k, D)
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale

        mask = _block_mask(s.shape, causal, q_start, k_start, qs_ref,
                           ks_ref, window)
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, 0]
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new[:, None])
        if segmented or window is not None:
            # A row fully masked in this block has m_new == _NEG_INF ==
            # its masked scores, making exp(s - m_new) = 1 — zero those
            # entries so padding rows accumulate nothing.  (Causal-only
            # running blocks always have >= 1 valid entry per row; a
            # low-k windowed block is admitted because the q block's
            # EARLY rows still reach it, while its LATE rows — whose
            # window starts later — can be fully masked on this, their
            # first visited block, so the window path needs this too.)
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)

        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_ref[:, 0] = m_new

    @pl.when(ik == n_k - 1)
    def _():
        denom = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[:] / denom[:, None]).astype(o_ref.dtype)
        # Row log-sum-exp — the single per-row statistic the backward needs
        # to recompute exact probabilities blockwise.
        lse_ref[0] = (m_ref[:, 0] + jnp.log(denom))[:, None]


def _kv_group(BHq: int, BHk: int) -> int:
    """Query-heads-per-KV-head group size, derived purely from the leading
    (batch*heads) dims — GQA/MQA need no extra static arguments.

    Layout contract: the (B, S, H, D) -> (B*H, S, D) flattening is
    batch-major with query head ``h = hk * G + g`` (the natural
    ``transpose(0,2,1,3).reshape`` order), so q row ``b``'s KV row is
    exactly ``b // G``."""
    if BHq % BHk:
        raise ValueError(
            f"query head rows {BHq} not a multiple of kv head rows {BHk}"
        )
    return BHq // BHk


def _flash_bh_fwd(q, k, v, *, scale, causal, block_q, block_k, interpret,
                  q_seg=None, kv_seg=None, window=None):
    """(BH, S, D) flash attention forward; returns (o, lse).

    ``k``/``v`` may carry FEWER head rows than ``q`` (GQA/MQA): with
    ``G = BHq // BHk``, q row ``b`` attends to kv row ``b // G`` — pure
    index-map arithmetic, the shared KV block is streamed once per query
    head with no materialized repeat.

    ``q_seg``/``kv_seg``: optional (BH, S, 1) int32 segment ids for packed
    sequences — attention is masked to segment-id equality."""
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    G = _kv_group(BH, k.shape[0])
    grid = (BH, Sq // block_q, Sk // block_k)
    segmented = q_seg is not None

    kernel = functools.partial(
        _attn_kernel, scale=scale, causal=causal, segmented=segmented,
        block_q=block_q, block_k=block_k, window=window,
    )
    scratch = [
        pltpu.VMEM((block_q, D), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
    ]
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b // G, j, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b // G, j, 0)),
    ]
    args = [q, k, v]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, i, j: (b // G, j, 0)),
        ]
        args += [q_seg, kv_seg]
    return pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Sq, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)


def _dq_kernel(
    *refs,
    scale: float, causal: bool, segmented: bool, block_q: int, block_k: int,
    window=None,
):
    if segmented:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
        qs_ref = ks_ref = None
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = iq * block_q
    k_start = ik * block_k
    run = _band_live(causal, window, q_start, block_q, k_start, block_k)

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        mask = _block_mask(s.shape, causal, q_start, k_start, qs_ref,
                           ks_ref, window)
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, :, :])             # exact probabilities
        if segmented or window is not None:
            # A FULLY-masked row (padding) has lse ~ _NEG_INF, making
            # exp(s - lse) = 1 at masked entries; zero them explicitly.
            p = jnp.where(mask, p, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0, :, :]) * scale).astype(k.dtype)
        dq_acc[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ik == n_k - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(
    *refs,
    scale: float, causal: bool, segmented: bool, block_q: int, block_k: int,
    n_q: int, window=None,
):
    if segmented:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qs_ref, ks_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        qs_ref = ks_ref = None
    ik = pl.program_id(1)   # grid: (BHk, n_k, G*n_q) — (head, q) innermost
    # The innermost axis enumerates (g, iq) pairs: for GQA every query
    # head of the group contributes to this KV row's dk/dv, so the
    # accumulator runs over all G * n_q steps and flushes once.
    i = pl.program_id(2)
    iq = i % n_q
    n_i = pl.num_programs(2)

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = iq * block_q
    k_start = ik * block_k
    # Skip when the whole Q block precedes the whole K block (causal) or
    # lies entirely beyond the K block's window reach.
    run = _band_live(causal, window, q_start, block_q, k_start, block_k)

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        mask = _block_mask(s.shape, causal, q_start, k_start, qs_ref,
                           ks_ref, window)
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, :, :])
        if segmented or window is not None:
            p = jnp.where(mask, p, 0.0)  # see _dq_kernel
        pt = p.astype(do.dtype).T
        dv_acc[:] += jnp.dot(pt, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0, :, :]) * scale).astype(q.dtype)
        dk_acc[:] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(i == n_i - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bh_bwd(q, k, v, o, lse, do, *, scale, causal, block_q, block_k,
                  interpret, dlse=None, q_seg=None, kv_seg=None,
                  window=None):
    """(BH, S, D) flash attention backward: (dq, dk, dv).

    ``dlse``: optional cotangent of the row log-sum-exp output (used when
    the LSE itself feeds downstream math, e.g. cross-block merging in ring
    attention).  Since ∂lse_i/∂s_ij = p_ij, the whole contribution folds
    into the per-row residual: ds = p·(dp − (δ − dlse)).
    """
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    BHk = k.shape[0]
    G = _kv_group(BH, BHk)
    segmented = q_seg is not None
    # delta_i = rowsum(dO ∘ O) — cheap elementwise, XLA handles it.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )[..., None]                                   # (BH, Sq, 1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)[..., None]

    q_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b // G, j, 0))
    r_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    dq_in = [q_spec, k_spec, k_spec, q_spec, r_spec, r_spec]
    dq_args = [q, k, v, do, lse, delta]
    if segmented:
        dq_in += [
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, i, j: (b // G, j, 0)),
        ]
        dq_args += [q_seg, kv_seg]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, segmented=segmented,
            block_q=block_q, block_k=block_k, window=window,
        ),
        out_shape=jax.ShapeDtypeStruct((BH, Sq, D), q.dtype),
        grid=(BH, Sq // block_q, Sk // block_k),
        in_specs=dq_in,
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(*dq_args)

    # dkv grid walks (BHk, n_k, G*n_q): one program chain per KV row with
    # every query head of its group innermost — the group's contributions
    # accumulate in the scratch and flush once, so GQA's dk/dv reduction
    # needs no extra pass.  Query-side rows for (kv row b, inner step i)
    # live at q row b*G + i // n_q, q block i % n_q.
    n_q = Sq // block_q
    qT_spec = pl.BlockSpec(
        (1, block_q, D), lambda b, j, i: (b * G + i // n_q, i % n_q, 0)
    )
    kT_spec = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    rT_spec = pl.BlockSpec(
        (1, block_q, 1), lambda b, j, i: (b * G + i // n_q, i % n_q, 0)
    )
    dkv_in = [qT_spec, kT_spec, kT_spec, qT_spec, rT_spec, rT_spec]
    dkv_args = [q, k, v, do, lse, delta]
    if segmented:
        dkv_in += [
            pl.BlockSpec(
                (1, block_q, 1),
                lambda b, j, i: (b * G + i // n_q, i % n_q, 0),
            ),
            pl.BlockSpec((1, block_k, 1), lambda b, j, i: (b, j, 0)),
        ]
        dkv_args += [q_seg, kv_seg]
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, segmented=segmented,
            block_q=block_q, block_k=block_k, n_q=n_q, window=window,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((BHk, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((BHk, Sk, D), v.dtype),
        ],
        grid=(BHk, Sk // block_k, G * n_q),
        in_specs=dkv_in,
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_args)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _flash_bh(q, k, v, scale, causal, block_q, block_k, interpret,
              window=None, block_q_bwd=None, block_k_bwd=None):
    """(BH, S, D) flash attention, differentiable (FlashAttention-2-style
    explicit backward: recompute probabilities blockwise from the saved row
    LSE, never materializing the S×S matrix in either pass).

    ``block_q_bwd``/``block_k_bwd``: optional separate geometry for the
    backward kernels (their tile economics differ — two extra streamed
    operands, two kernels); None means reuse the forward blocks."""
    o, _ = _flash_bh_fwd(
        q, k, v, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        window=window,
    )
    return o


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                   window=None, block_q_bwd=None, block_k_bwd=None):
    o, lse = _flash_bh_fwd(
        q, k, v, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        window=window,
    )
    return o, (q, k, v, o, lse)

def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, window,
                   block_q_bwd, block_k_bwd, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bh_bwd(
        q, k, v, o, lse, do, scale=scale, causal=causal,
        block_q=block_q_bwd or block_q, block_k=block_k_bwd or block_k,
        interpret=interpret, window=window,
    )
    return dq, dk, dv


_flash_bh.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _float0_like(x):
    """Cotangent for integer primal inputs (jax's float0 convention)."""
    return np.zeros(x.shape, dtype=jax.dtypes.float0)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(5, 6, 7, 8, 9, 10, 11, 12))
def _flash_bh_seg(q, k, v, q_seg, kv_seg, scale, causal, block_q, block_k,
                  interpret, window=None, block_q_bwd=None,
                  block_k_bwd=None):
    """Segment-masked (BH, S, D) flash attention (packed sequences):
    tokens attend only within their own segment id.  Same explicit
    FlashAttention-2 backward (with its own optional block geometry, see
    :func:`_flash_bh`); fully-masked (padding) rows produce zero output
    and zero gradients."""
    o, _ = _flash_bh_fwd(
        q, k, v, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        q_seg=q_seg, kv_seg=kv_seg, window=window,
    )
    return o


def _flash_seg_vjp_fwd(q, k, v, q_seg, kv_seg, scale, causal, block_q,
                       block_k, interpret, window=None, block_q_bwd=None,
                       block_k_bwd=None):
    o, lse = _flash_bh_fwd(
        q, k, v, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        q_seg=q_seg, kv_seg=kv_seg, window=window,
    )
    return o, (q, k, v, o, lse, q_seg, kv_seg)


def _flash_seg_vjp_bwd(scale, causal, block_q, block_k, interpret, window,
                       block_q_bwd, block_k_bwd, res, do):
    q, k, v, o, lse, q_seg, kv_seg = res
    dq, dk, dv = _flash_bh_bwd(
        q, k, v, o, lse, do, scale=scale, causal=causal,
        block_q=block_q_bwd or block_q, block_k=block_k_bwd or block_k,
        interpret=interpret, q_seg=q_seg, kv_seg=kv_seg, window=window,
    )
    return dq, dk, dv, _float0_like(q_seg), _float0_like(kv_seg)


_flash_bh_seg.defvjp(_flash_seg_vjp_fwd, _flash_seg_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_with_lse(q, k, v, scale, causal, block_q, block_k,
                             interpret):
    """(BH, S, D) flash attention returning ``(o, lse)`` — both
    differentiable.  For composition layers (ring/zigzag) that merge
    blocks via the row log-sum-exp: the LSE cotangent folds into the
    backward kernels' residual (see :func:`_flash_bh_bwd`)."""
    return _flash_bh_fwd(
        q, k, v, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )


def _flash_lse_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    o, lse = _flash_bh_fwd(
        q, k, v, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return (o, lse), (q, k, v, o, lse)


def _flash_lse_vjp_bwd(scale, causal, block_q, block_k, interpret, res, cots):
    q, k, v, o, lse = res
    do, dlse = cots
    # lse output is (BH, S, 1) from the kernel; normalize cotangent shape.
    dlse2 = dlse[..., 0] if dlse.ndim == 3 else dlse
    dq, dk, dv = _flash_bh_bwd(
        q, k, v, o, lse, do, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret, dlse=dlse2,
    )
    return dq, dk, dv


flash_attention_with_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def segment_mask(q_segment_ids, kv_segment_ids):
    """(B, Sq) × (B, Sk) int ids → (B, Sq, Sk) boolean equality mask —
    THE packed-sequence mask rule, shared by the XLA fallback, ring, and
    zigzag paths (one definition to evolve, e.g. a future 'padding id
    matches nothing' convention)."""
    return q_segment_ids[:, :, None] == kv_segment_ids[:, None, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def flash_attention_with_lse_seg(q, k, v, q_seg, kv_seg, scale, causal,
                                 block_q, block_k, interpret):
    """Segment-masked :func:`flash_attention_with_lse` — ``(o, lse)``
    with both cotangents folding into the explicit backward, plus the
    packed-sequence masks.  The composition form for segmented
    ring/zigzag inners."""
    return _flash_bh_fwd(
        q, k, v, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        q_seg=q_seg, kv_seg=kv_seg,
    )


def _flash_lse_seg_vjp_fwd(q, k, v, q_seg, kv_seg, scale, causal, block_q,
                           block_k, interpret):
    o, lse = _flash_bh_fwd(
        q, k, v, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        q_seg=q_seg, kv_seg=kv_seg,
    )
    return (o, lse), (q, k, v, o, lse, q_seg, kv_seg)


def _flash_lse_seg_vjp_bwd(scale, causal, block_q, block_k, interpret, res,
                           cots):
    q, k, v, o, lse, q_seg, kv_seg = res
    do, dlse = cots
    dlse2 = dlse[..., 0] if dlse.ndim == 3 else dlse
    dq, dk, dv = _flash_bh_bwd(
        q, k, v, o, lse, do, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret, dlse=dlse2,
        q_seg=q_seg, kv_seg=kv_seg,
    )
    return dq, dk, dv, _float0_like(q_seg), _float0_like(kv_seg)


flash_attention_with_lse_seg.defvjp(
    _flash_lse_seg_vjp_fwd, _flash_lse_seg_vjp_bwd
)


def _xla_attention(q, k, v, scale, causal, q_segment_ids=None,
                   kv_segment_ids=None, window=None):
    if k.shape[2] != q.shape[2]:
        # GQA/MQA fallback: broadcast KV heads to the query head count.
        # jnp.repeat's transpose sums the group's dk/dv — exactly the
        # grouped reduction the Pallas dkv kernel does in its scratch.
        G = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, G, axis=2)
        v = jnp.repeat(v, G, axis=2)
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    Sq, Sk = q.shape[1], k.shape[1]
    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((Sq, Sk), bool))[None]
    if window is not None:
        band = (
            jnp.arange(Sq)[:, None] - jnp.arange(Sk)[None, :] < window
        )[None]
        mask = band if mask is None else (mask & band)
    if q_segment_ids is not None:
        seg = segment_mask(q_segment_ids, kv_segment_ids)
        mask = seg if mask is None else (mask & seg)
    if mask is not None:
        logits = jnp.where(mask[:, None], logits, _NEG_INF)
    w = jax.nn.softmax(logits)
    if q_segment_ids is not None:
        # Fully-masked (padding) rows: softmax of all -inf is uniform
        # garbage; zero them so output AND gradients vanish, matching the
        # Pallas kernel's behavior.
        any_valid = mask.any(axis=-1)  # (B, Sq)
        w = jnp.where(any_valid[:, None, :, None], w, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32)).astype(q.dtype)


def auto_block_size(S: int) -> int:
    """The STATIC default block edge: largest-coverage choice near S/16
    that both divides S and meets the sublane alignment (128/256/512 are
    multiples of every sublane count) — a poor auto pick must not
    silently demote a previously-compiling shape to the XLA fallback.
    This is also the fallback the tuning subsystem resolves to on a
    cache miss, and a mandatory member of its search space (a tuned pick
    can never lose to it)."""
    target = int(np.clip(S // 16, 128, 512))
    cands = [b for b in (128, 256, 512) if S % b == 0]
    if not cands:
        return min(128, S)
    return min(cands, key=lambda b: abs(b - target))


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    q_segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    window: Optional[int] = None,
    block_q_bwd: Optional[int] = None,
    block_k_bwd: Optional[int] = None,
):
    """Flash attention over (B, S, H, D) tensors (layout matches the
    transformer layers in ``chainermn_tpu.models``).

    ``window``: optional sliding-window size (Mistral-style local
    attention, causal only): query ``i`` attends keys ``[i - window + 1,
    i]``, intersected with the segment masks.  Whole tiles outside the
    band are skipped in forward AND both backward kernels, so compute
    scales O(S * window) instead of O(S²/2).

    Uses the Pallas kernel when shapes allow (D ≤ 256, S divisible by the
    block sizes after clamping); otherwise falls back to XLA attention.
    The compiled path handles any D ≤ 256 (Mosaic pads the lane dim;
    verified on a v5e-class chip against the XLA oracle at D ∈ {16..128}
    and at the wide-head points D ∈ {160, 192, 256}).

    GQA/MQA: ``k``/``v`` may carry ``H_kv`` heads with ``H_kv`` dividing
    ``H`` (``H_kv == 1`` is MQA).  Query head ``h`` attends to kv head
    ``h // (H / H_kv)``; the kernels stream the SHARED kv block via index
    maps (no materialized repeat) and reduce the group's dk/dv inside the
    backward kernel's accumulator.

    ``q_segment_ids``/``kv_segment_ids``: optional (B, S) int32 segment
    ids for PACKED sequences — tokens attend only within their own
    segment (combined with the causal mask), the packed-long-context
    training shape.  Rows whose segment matches nothing (padding, e.g.
    segment id -1 against all-nonnegative kv ids) produce zero output
    and zero gradients.

    ``block_q``/``block_k`` default to a TUNED size when the persistent
    autotune cache (``chainermn_tpu.tuning``, see docs/tuning.md) holds a
    measured-best entry for this (device kind, dtype, shape bucket,
    causal/window) — populated by ``python -m chainermn_tpu.tools
    .autotune`` or ``bench.py --autotune``, never implicitly.  On a miss,
    off-TPU, or under pytest, the static auto size applies: ``S/16``
    clamped to [128, 512] — measured optimal per length on a v5e-class
    chip (S=2048→128, 4096→256, 8192→512; at 8192/bf16/D=128 the kernel
    sustains ~67 TFLOP/s forward, 4.5-4.9x XLA's materialized-logits
    attention, slope-timed per docs/performance.md).  Pinning either
    block explicitly bypasses the cache entirely.

    ``block_q_bwd``/``block_k_bwd``: optional separate geometry for the
    backward kernels (tuned independently — the backward streams two
    extra operands and runs two kernels, so its optimum can differ);
    default to the forward blocks (tuned or static).
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    Hk = k.shape[2]
    if H % Hk or v.shape[2] != Hk:
        raise ValueError(
            f"kv heads ({Hk}, v {v.shape[2]}) must be equal and divide "
            f"the query head count ({H})"
        )
    if scale is None:
        scale = 1.0 / (D**0.5)
    if window is not None:
        if not causal:
            raise ValueError(
                "window (sliding-window attention) requires causal=True — "
                "a non-causal local band has no in-tree consumer and "
                "would silently differ from every oracle"
            )
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError(
            "q_segment_ids and kv_segment_ids must be passed together"
        )

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    segmented = q_segment_ids is not None
    if block_q is None and block_k is None and not interpret:
        # Caller pinned nothing: consult the persistent tune cache (a
        # trace-time read; inert under pytest and off-TPU, so interpret/
        # CPU behavior stays bit-identical to the static defaults).
        from chainermn_tpu.tuning.autotune import lookup_flash_blocks

        tuned = lookup_flash_blocks(
            "fwd", Sq=Sq, Sk=Sk, D=D, dtype=q.dtype, causal=causal,
            window=window, segmented=segmented,
        )
        if tuned is not None:
            block_q, block_k = tuned
        if block_q_bwd is None and block_k_bwd is None:
            tuned_bwd = lookup_flash_blocks(
                "bwd", Sq=Sq, Sk=Sk, D=D, dtype=q.dtype, causal=causal,
                window=window, segmented=segmented,
            )
            if tuned_bwd is not None:
                block_q_bwd, block_k_bwd = tuned_bwd

    if block_q is None:
        block_q = auto_block_size(Sq)
    if block_k is None:
        block_k = auto_block_size(Sk)
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    # Sublane tiling constraint on compiled TPU kernels: the block's
    # second-to-last dim must be a multiple of the dtype's sublane count.
    # The lane (last) dim need not be a multiple of 128 — Mosaic pads it —
    # so any head_dim ≤ 128 compiles.  Interpret mode has no tiling, so
    # the CPU harness can exercise smaller shapes.
    sublane = 16 if q.dtype == jnp.bfloat16 else 8
    tile_ok = interpret or (
        block_q % sublane == 0 and block_k % sublane == 0
    )
    # Wide heads: Mosaic pads the lane dim, so any D ≤ 256 compiles
    # (verified on-chip at D ∈ {160, 192, 256} against the oracle);
    # beyond 256 the VMEM block economics favor the XLA fallback.
    d_ok = D <= 256
    usable = (
        _HAS_PLTPU
        and d_ok
        and Sq % block_q == 0
        and Sk % block_k == 0
        and tile_ok
    )
    if not usable:
        return _xla_attention(
            q, k, v, scale, causal,
            q_segment_ids=q_segment_ids, kv_segment_ids=kv_segment_ids,
            window=window,
        )

    # Backward geometry rides the same gate as the forward's: an invalid
    # pair (stale cache bucket, caller typo) silently reverts to the
    # forward blocks rather than demoting the whole call to the XLA path.
    if block_q_bwd is not None or block_k_bwd is not None:
        bq_b = block_q_bwd or block_q
        bk_b = block_k_bwd or block_k
        bwd_ok = (
            Sq % bq_b == 0 and Sk % bk_b == 0
            and (interpret or (bq_b % sublane == 0 and bk_b % sublane == 0))
        )
        block_q_bwd, block_k_bwd = (bq_b, bk_b) if bwd_ok else (None, None)

    # (B, S, H, D) → (B*H, S, D); kv keep their own (possibly smaller)
    # head count — the batch-major flattening makes q row b's kv row
    # exactly b // (H // Hk) (see _kv_group).
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Sq, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * Hk, Sk, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * Hk, Sk, D)
    if q_segment_ids is not None:
        qs = seg_to_bh(q_segment_ids, H)
        ks = seg_to_bh(kv_segment_ids, Hk)
        out = _flash_bh_seg(
            qt, kt, vt, qs, ks, scale, causal, block_q, block_k, interpret,
            window, block_q_bwd, block_k_bwd,
        )
    else:
        out = _flash_bh(
            qt, kt, vt, scale, causal, block_q, block_k, interpret, window,
            block_q_bwd, block_k_bwd,
        )
    return out.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)


def flash_block_plan(S: int, D: int, dtype, interpret: bool):
    """(usable, block_size) for running the kernel over length-``S``
    chunks — the single block-policy used by composition layers
    (ring/zigzag).  Mirrors :func:`flash_attention`'s gating: pallas-TPU
    importable, D ≤ 256 compiled, blocks always DIVIDING S (a
    non-dividing block floors the grid and silently drops tail rows —
    interpret mode included), sized near the measured-optimal S/16
    clamped to [128, 512]."""
    if not _HAS_PLTPU:
        return False, 0
    if interpret:
        # Interpreter-mode block policy: a full-S block materializes the
        # S×S matrix (defeating the O(S) property), while a degenerate
        # block means (S/b)² interpreter invocations — an effective hang.
        # So: smallest aligned divisor keeping the grid ≤ 64 per axis,
        # else the largest divisor ≤ 512 under the same grid cap, else
        # refuse and let the caller fall back / raise, as the compiled
        # branch does.
        cands = [b for b in (128, 256, 512) if S % b == 0 and S <= b * 64]
        if cands:
            return True, min(cands)
        b = max(d for d in range(1, min(S, 512) + 1) if S % d == 0)
        if b * 64 < S:
            return False, 0
        return True, b
    if D > 256:
        return False, 0
    if any(S % b == 0 for b in (128, 256, 512)):
        return True, auto_block_size(S)
    sublane = 16 if dtype == jnp.bfloat16 else 8
    if S <= 512 and S % sublane == 0:
        return True, S
    return False, 0


def to_bh(x):
    """(B, S, H, D) → (B*H, S, D), the kernel layout."""
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def from_bh(x, B: int, H: int):
    """(B*H, S, D) → (B, S, H, D)."""
    _, S, D = x.shape
    return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def seg_to_bh(ids, H: int):
    """(B, S) segment ids → the kernel's (B*H, S, 1) layout (head index
    minor, matching :func:`to_bh`'s flattening)."""
    return jnp.repeat(ids.astype(jnp.int32), H, axis=0)[..., None]


def make_flash_attention_fn(causal: bool = True, q_segment_ids=None,
                            kv_segment_ids=None, window=None,
                            block_q=None, block_k=None,
                            block_q_bwd=None, block_k_bwd=None):
    """Adapter for the transformer layers' ``attention_fn`` slot (mask
    argument ignored; causality is the kernel's).

    ``block_q``/``block_k``/``block_q_bwd``/``block_k_bwd``: optional
    pinned kernel geometry (``bench.py --autotune`` binds the tuned
    blocks here); None defers to :func:`flash_attention`'s cache-then-
    static default.

    ``q_segment_ids``/``kv_segment_ids`` (optional int32) bind
    packed-sequence segment masks at CONSTRUCTION — the layers call
    ``attention_fn(q, k, v, mask)``, so per-batch metadata enters as a
    closure.  Two shapes are accepted:

    * ``(S,)`` — one row's ids, broadcast to every batch row.  This is
      the DATA-PARALLEL-SAFE form: under ``shard_map`` the closure is
      replicated while ``q`` is a local shard, so only row-uniform ids
      can be correct without knowing which global rows a device holds.
    * ``(B, S)`` — per-row ids; ``B`` must EQUAL the batch the adapter
      sees (a mismatch raises rather than silently masking shard 1+ with
      shard 0's rows)."""

    def _match(ids, batch):
        if ids.ndim == 1:
            import jax.numpy as _jnp

            return _jnp.broadcast_to(ids[None], (batch, ids.shape[0]))
        if ids.shape[0] != batch:
            raise ValueError(
                f"segment_ids batch {ids.shape[0]} != attention batch "
                f"{batch}: under data-parallel sharding the adapter "
                "cannot know which global rows this shard holds — pass "
                "row-uniform (S,) ids, or thread per-row ids through "
                "flash_attention directly inside the sharded region"
            )
        return ids

    def fn(q, k, v, mask=None):
        del mask
        qs = ks = None
        if q_segment_ids is not None:
            qs = _match(q_segment_ids, q.shape[0])
            ks = _match(
                kv_segment_ids if kv_segment_ids is not None
                else q_segment_ids,
                k.shape[0],
            )
        return flash_attention(
            q, k, v, causal=causal, q_segment_ids=qs, kv_segment_ids=ks,
            window=window, block_q=block_q, block_k=block_k,
            block_q_bwd=block_q_bwd, block_k_bwd=block_k_bwd,
        )

    return fn
