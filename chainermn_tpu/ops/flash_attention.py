"""Flash attention as a Pallas TPU kernel.

Blockwise attention with online softmax: Q blocks stream over KV blocks
held in VMEM, accumulating unnormalized outputs with running max/denominator
— O(S) memory instead of O(S²), fp32 accumulation, MXU matmuls via
``jnp.dot(..., preferred_element_type=float32)``.  The same math as
``parallel.ring_attention`` — there the blocks live on *different chips*
and rotate over ICI; here they live in *HBM* and stream through VMEM.  A
sequence-parallel model composes both: ring outside, this kernel inside
each block pair.

Causal skipping: grid programs whose whole K block is in the future of the
whole Q block write nothing and skip the matmuls (``pl.when``), so the
causal kernel does ~half the FLOPs, like the CUDA flash-attention kernels.

Falls back to interpreter mode off-TPU (tests run the same kernel code on
the CPU mesh) and to plain XLA attention for shapes the kernel does not
cover (head_dim > 128 or unaligned sequence lengths).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

_NEG_INF = -1e30


def _attn_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ik == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = iq * block_q
    k_start = ik * block_k

    # Whole-block causal skip: K block strictly in the future of Q block.
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _():
        q = q_ref[0].astype(jnp.float32)          # (block_q, D)
        k = k_ref[0].astype(jnp.float32)          # (block_k, D)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale

        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_ref[:, 0]
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)

        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_ref[:, 0] = m_new

    @pl.when(ik == n_k - 1)
    def _():
        denom = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[:] / denom[:, None]).astype(o_ref.dtype)


def _flash_bh(q, k, v, *, scale, causal, block_q, block_k, interpret):
    """(BH, S, D) flash attention."""
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    grid = (BH, Sq // block_q, Sk // block_k)

    kernel = functools.partial(
        _attn_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    scratch = [
        pltpu.VMEM((block_q, D), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
    ]
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((BH, Sq, D), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)


def _xla_attention(q, k, v, scale, causal):
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((Sq, Sk), bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    w = jax.nn.softmax(logits)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32)).astype(q.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Flash attention over (B, S, H, D) tensors (layout matches the
    transformer layers in ``chainermn_tpu.models``).

    Uses the Pallas kernel when shapes allow (D ≤ 128, S divisible by the
    block sizes after clamping); otherwise falls back to XLA attention.
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    if scale is None:
        scale = 1.0 / (D**0.5)

    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    # Sublane tiling constraint on compiled TPU kernels: the block's
    # second-to-last dim must be a multiple of the dtype's sublane count
    # and the last (lane) dim a multiple of 128.  Interpret mode has no
    # tiling, so the CPU harness can exercise smaller shapes.
    sublane = 16 if q.dtype == jnp.bfloat16 else 8
    tile_ok = interpret or (
        D % 128 == 0 and block_q % sublane == 0 and block_k % sublane == 0
    )
    usable = (
        _HAS_PLTPU
        and D <= 128
        and Sq % block_q == 0
        and Sk % block_k == 0
        and tile_ok
    )
    if not usable:
        return _xla_attention(q, k, v, scale, causal)

    # (B, S, H, D) → (B*H, S, D)
    qt = q.transpose(0, 2, 1, 3).reshape(B * H, Sq, D)
    kt = k.transpose(0, 2, 1, 3).reshape(B * H, Sk, D)
    vt = v.transpose(0, 2, 1, 3).reshape(B * H, Sk, D)
    out = _flash_bh(
        qt, kt, vt, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return out.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)


def make_flash_attention_fn(causal: bool = True):
    """Adapter for the transformer layers' ``attention_fn`` slot (mask
    argument ignored; causality is the kernel's)."""

    def fn(q, k, v, mask=None):
        del mask
        return flash_attention(q, k, v, causal=causal)

    return fn
