"""Multi-node iterators.

Reference: REF:chainermn/iterators/ — ``create_multi_node_iterator``
(rank ``root`` draws batches and broadcasts them, so model-parallel ranks
see the SAME batch, unlike data-parallel ranks) and
``create_synchronized_iterator`` (ranks draw independently but stay in
lockstep on epoch boundaries).  The reference's ImageNet example fed each
rank through Chainer's ``MultiprocessIterator`` (background workers +
pinned-memory staging); :func:`create_prefetch_iterator` is that role here
— a background thread drains the host iterator and stages batches into
device memory ahead of compute.

TPU-native shape: these operate on the host/object plane (per process).  On
a single host they are near-no-ops — all local devices already see the same
global batch array — but on multi-host model-parallel runs they keep every
process feeding identical data, which is the invariant the reference's
iterator wrappers existed to protect.
"""

from __future__ import annotations

import queue as _queue
import threading
from typing import Iterable, Iterator

import jax

from chainermn_tpu.communicators.base import CommunicatorBase

_STOP = "__chainermn_tpu_stop__"


def create_multi_node_iterator(
    actual_iterator: Iterable, communicator: CommunicatorBase, rank_master: int = 0
) -> Iterator:
    """Master draws; everyone receives the same batches (reference-parity).

    The master iterates ``actual_iterator`` and broadcasts each batch over
    the object plane; non-master ranks ignore their local iterator.  A
    sentinel broadcast ends every rank's epoch together.
    """

    def gen():
        if communicator.rank == rank_master:
            for batch in actual_iterator:
                communicator.bcast_obj(batch, root=rank_master)
                yield batch
            communicator.bcast_obj(_STOP, root=rank_master)
        else:
            while True:
                batch = communicator.bcast_obj(None, root=rank_master)
                if isinstance(batch, str) and batch == _STOP:
                    return
                yield batch

    return gen()


def create_prefetch_iterator(
    actual_iterator: Iterable,
    size: int = 2,
    sharding=None,
    close_join_timeout: float | None = 1.0,
) -> Iterator:
    """Device-prefetching wrapper: overlap host-side batch production and
    host→device transfer with device compute.

    A daemon thread iterates ``actual_iterator`` (so any Python-side work
    in it — decoding, augmentation, ``comm.global_batch`` assembly — runs
    off the training loop's critical path) and issues ``jax.device_put``
    for each batch; up to ``size`` transferred batches sit in a bounded
    queue.  By the time the train step wants batch N+1, its transfer was
    issued while step N computed — the reference ImageNet example's
    ``MultiprocessIterator`` + pinned-staging overlap, with XLA's async
    dispatch standing in for the CUDA copy stream.

    ``sharding`` (optional): a ``jax.sharding.Sharding`` — or a pytree of
    them matching the batch structure — to place batches directly in their
    jitted-step layout and skip the re-layout on dispatch.

    ``close_join_timeout``: bound on waiting for the producer thread at
    shutdown.  The default (1 s) guards against a producer blocked inside
    the user's iterator; pass ``None`` for an unbounded join when the
    source's ``next()`` is known to return in bounded time AND the caller
    will tear down resources the producer may still be reading (e.g. a
    shared-memory loader's slots) — an expired bounded join would let
    that teardown race the producer's final read.

    Exceptions in the producer thread re-raise at the consuming ``next()``.
    """
    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")
    q: _queue.Queue = _queue.Queue(maxsize=size)
    _END = object()
    stop = threading.Event()

    def put(batch):
        if sharding is None:
            return jax.device_put(batch)
        if isinstance(sharding, jax.sharding.Sharding):
            return jax.device_put(batch, sharding)
        return jax.tree.map(
            jax.device_put, batch, sharding,
            is_leaf=lambda x: x is None,
        )

    def _put_or_stop(item) -> bool:
        """Enqueue unless the consumer went away; True if enqueued."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def producer():
        try:
            for batch in actual_iterator:
                if not _put_or_stop(put(batch)):
                    return
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            _put_or_stop((_END, e))
            return
        _put_or_stop((_END, None))

    t = threading.Thread(target=producer, daemon=True)

    def gen():
        # The producer starts lazily on the first next(): an abandoned,
        # never-started generator then owns no thread and pins no device
        # buffers (the finally block below would never run for it).
        # The finally block is the shutdown path: closing or abandoning the
        # iterator mid-stream (e.g. `break` in the consuming loop) signals
        # the producer to exit and drains queued batches so their device
        # buffers are released instead of pinned for the process lifetime.
        t.start()
        try:
            while True:
                item = q.get()
                if (
                    isinstance(item, tuple)
                    and len(item) == 2
                    and item[0] is _END
                ):
                    if item[1] is not None:
                        raise item[1]
                    return
                yield item
        finally:
            stop.set()
            # Join before draining: a producer already inside its ≤0.1 s
            # q.put attempt could otherwise land one last batch AFTER the
            # drain, pinning its device buffers for the process lifetime.
            # The join is bounded by default (every put attempt re-checks
            # `stop`); the timeout only guards a producer blocked inside
            # the user's iterator itself — see ``close_join_timeout``.
            t.join(timeout=close_join_timeout)
            try:
                while True:
                    q.get_nowait()
            except _queue.Empty:
                pass

    return gen()


def create_synchronized_iterator(
    actual_iterator: Iterable, communicator: CommunicatorBase
) -> Iterator:
    """Ranks draw from their own iterators but stop together: each step all
    ranks agree (object-plane allreduce) whether every rank still has data —
    the lockstep-epoch guarantee (reference-parity)."""

    def gen():
        it = iter(actual_iterator)
        while True:
            try:
                batch = next(it)
                have = 1
            except StopIteration:
                batch, have = None, 0
            total = communicator.allreduce_obj(have)
            if total < communicator.size:
                return  # someone ran dry: everyone stops this epoch
            yield batch

    return gen()
