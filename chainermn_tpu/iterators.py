"""Multi-node iterators.

Reference: REF:chainermn/iterators/ — ``create_multi_node_iterator``
(rank ``root`` draws batches and broadcasts them, so model-parallel ranks
see the SAME batch, unlike data-parallel ranks) and
``create_synchronized_iterator`` (ranks draw independently but stay in
lockstep on epoch boundaries).

TPU-native shape: these operate on the host/object plane (per process).  On
a single host they are near-no-ops — all local devices already see the same
global batch array — but on multi-host model-parallel runs they keep every
process feeding identical data, which is the invariant the reference's
iterator wrappers existed to protect.
"""

from __future__ import annotations

from typing import Iterable, Iterator

from chainermn_tpu.communicators.base import CommunicatorBase

_STOP = "__chainermn_tpu_stop__"


def create_multi_node_iterator(
    actual_iterator: Iterable, communicator: CommunicatorBase, rank_master: int = 0
) -> Iterator:
    """Master draws; everyone receives the same batches (reference-parity).

    The master iterates ``actual_iterator`` and broadcasts each batch over
    the object plane; non-master ranks ignore their local iterator.  A
    sentinel broadcast ends every rank's epoch together.
    """

    def gen():
        if communicator.rank == rank_master:
            for batch in actual_iterator:
                communicator.bcast_obj(batch, root=rank_master)
                yield batch
            communicator.bcast_obj(_STOP, root=rank_master)
        else:
            while True:
                batch = communicator.bcast_obj(None, root=rank_master)
                if isinstance(batch, str) and batch == _STOP:
                    return
                yield batch

    return gen()


def create_synchronized_iterator(
    actual_iterator: Iterable, communicator: CommunicatorBase
) -> Iterator:
    """Ranks draw from their own iterators but stop together: each step all
    ranks agree (object-plane allreduce) whether every rank still has data —
    the lockstep-epoch guarantee (reference-parity)."""

    def gen():
        it = iter(actual_iterator)
        while True:
            try:
                batch = next(it)
                have = 1
            except StopIteration:
                batch, have = None, 0
            total = communicator.allreduce_obj(have)
            if total < communicator.size:
                return  # someone ran dry: everyone stops this epoch
            yield batch

    return gen()
