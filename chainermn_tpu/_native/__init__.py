"""Holds the packaged native library (``libhostbuf.so``), compiled by
setup.py's build hook at install/wheel time.  Empty in source checkouts —
there ``utils.native`` compiles ``csrc/hostbuf.cpp`` on demand instead."""
