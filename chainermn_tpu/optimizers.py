"""Multi-node optimizer — the data-parallel hot path.

Reference: REF:chainermn/optimizers.py — ``create_multi_node_optimizer(
actual_optimizer, communicator, double_buffering=False)`` wraps any Chainer
optimizer; on ``update()`` it (first call) broadcasts model parameters from
rank 0, then runs local backward, ``communicator.allreduce_grad(model)``,
and the inner optimizer's update.  ``_DoubleBufferingOptimizer`` overlaps
this step's allreduce with the next step's compute, applying one-step-stale
averaged gradients.

TPU-native translation (SURVEY §7 "hard part 2" — the eager-API ↔
traced-step impedance): the reference's imperative per-step
``allreduce_grad`` call becomes a collective *traced into* one jitted step
function.  ``make_train_step`` builds that step: a ``shard_map`` over the
communicator's mesh computes per-device gradients on the local batch shard,
runs the communicator's characteristic allreduce, and applies an inner
`optax` transformation on the (now replicated) mean gradients.  XLA then
owns the overlap: async collectives hide the allreduce behind surrounding
compute where data dependence allows, which is what the reference's
dedicated side stream bought it.

Double buffering keeps its reference *semantics* (apply one-step-stale
means; the first call only reduces, no update) because the staleness — not
the stream machinery — is what changes training behavior; the overlap
itself widens, since with stale application the collective's result is not
needed until the *next* step and XLA may overlap it across the entire
step boundary.

The imperative parity surface (``setup``/``update``/``target``) is a thin
stateful veneer over the functional path for users arriving from the
reference API.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators.base import CommunicatorBase


class MultiNodeOptimizerState(NamedTuple):
    inner: Any            # the wrapped optax optimizer's state
    step: jnp.ndarray     # int32 step counter
    comm_buf: Any         # double buffering: previous step's averaged grads
                          # (None-like zeros tree when double_buffering=False)


class MultiNodeOptimizer:
    """Wrap an ``optax.GradientTransformation`` with distributed gradient
    averaging — the reference's ``_MultiNodeOptimizer`` reimagined for
    traced steps."""

    def __init__(
        self,
        actual_optimizer: optax.GradientTransformation,
        communicator: CommunicatorBase,
        double_buffering: bool = False,
        zero_stage: int = 0,
    ):
        """``zero_stage=1`` shards optimizer state 1/n per device (ZeRO-1):
        gradients arrive by reduce-scatter, the inner optimizer updates only
        the local flat shard, and updated parameters are all-gathered — the
        TPU-native memory optimization the reference never had (its
        optimizer state was fully replicated per GPU)."""
        self.actual_optimizer = actual_optimizer
        self.communicator = communicator
        self.double_buffering = double_buffering
        if zero_stage not in (0, 1):
            raise ValueError("zero_stage must be 0 or 1")
        if zero_stage == 1 and double_buffering:
            raise NotImplementedError(
                "double_buffering + zero_stage=1 not supported together"
            )
        self.zero_stage = zero_stage
        # imperative-parity state (setup/update/target)
        self._params = None
        self._state = None
        self._step_fn = None

    # ------------------------------------------------------------------
    # Functional API
    # ------------------------------------------------------------------
    def init(self, params) -> MultiNodeOptimizerState:
        """Initialize optimizer state.  The analogue of the reference's
        first-``update`` ``broadcast_data``: parameters are replicated from
        process 0 so every host starts identical."""
        params = self.broadcast_params(params)
        if self.zero_stage == 1:
            inner = self._zero_init(params)
        else:
            inner = self.actual_optimizer.init(params)
        zeros = jax.tree.map(jnp.zeros_like, params) if self.double_buffering else ()
        return MultiNodeOptimizerState(
            inner=inner,
            step=jnp.zeros((), jnp.int32),
            comm_buf=zeros,
        )

    # ------------------------------------------------------------------
    # ZeRO-1 plumbing: flat padded buffer, per-device shard
    # ------------------------------------------------------------------
    def _zero_geometry(self, params):
        n = self.communicator.device_size
        total = sum(l.size for l in jax.tree.leaves(params))
        pad = (-total) % n
        return n, total, (total + pad) // n

    def _zero_pack(self, tree, padded_size):
        from chainermn_tpu.communicators.xla_ici import pack

        flat, unpack = pack(jax.tree.map(lambda x: x.astype(jnp.float32), tree))
        if flat.size < padded_size:
            flat = jnp.concatenate(
                [flat, jnp.zeros((padded_size - flat.size,), flat.dtype)]
            )
        return flat, unpack

    def _zero_inner_spec(self, shard_size):
        """Per-leaf PartitionSpecs for the sharded inner state: flat-shard
        leaves ride the world axes, scalars (e.g. adam's count) replicate."""
        comm = self.communicator
        world = comm.axes if len(comm.axes) > 1 else comm.axes[0]

        def leaf_spec(leaf):
            shape = getattr(leaf, "shape", ())
            return P(world) if (len(shape) == 1 and shape[0] == shard_size) else P()

        shard = jax.ShapeDtypeStruct((shard_size,), jnp.float32)
        state_shape = jax.eval_shape(self.actual_optimizer.init, shard)
        return jax.tree.map(leaf_spec, state_shape)

    def _zero_init(self, params):
        comm = self.communicator
        n, total, shard_size = self._zero_geometry(params)

        def body(params):
            flat, _ = self._zero_pack(params, shard_size * n)
            mine = lax.dynamic_slice_in_dim(
                flat, comm.axis_index() * shard_size, shard_size
            )
            return self.actual_optimizer.init(mine)

        return jax.jit(
            comm.shard_map(
                body, in_specs=(P(),), out_specs=self._zero_inner_spec(shard_size)
            )
        )(params)

    def broadcast_params(self, params):
        """Host-plane replication from process 0 (reference
        ``broadcast_data``).  A no-op on one host: device-plane replication
        is the sharding's job under jit."""
        if self.communicator.size > 1:
            from jax.experimental import multihost_utils

            params = multihost_utils.broadcast_one_to_all(params)
        return params

    def make_train_step(
        self,
        loss_fn: Callable,
        batch_spec=None,
        donate: bool = True,
        has_aux: bool = False,
        rng: Any = None,
    ):
        """Build the jitted SPMD training step.

        ``loss_fn(params, batch) -> loss`` (or ``(loss, aux)`` with
        ``has_aux``) computes the *local* mean loss on one device's batch
        shard; the step averages gradients with the communicator's
        characteristic collective pattern and applies the inner optimizer.

        With ``rng`` (a base PRNGKey), ``loss_fn(params, batch, rng)`` is
        called with a key folded over (step, device rank) — per-device
        dropout/augmentation randomness that stays reproducible.

        Returns ``step(params, state, batch) -> (params, state, loss[, aux])``.
        """
        comm = self.communicator
        axes = comm.axes
        if batch_spec is None:
            batch_spec = P(axes if len(axes) > 1 else axes[0])
        opt = self.actual_optimizer
        if self.zero_stage == 1:
            return self._make_zero_train_step(
                loss_fn, batch_spec, donate, has_aux, rng
            )

        def body(params, state, batch):
            if rng is not None:
                key = jax.random.fold_in(
                    jax.random.fold_in(rng, state.step), comm.axis_index()
                )
                wrapped = lambda p, b: loss_fn(p, b, key)  # noqa: E731
            else:
                wrapped = loss_fn
            grad_fn = jax.value_and_grad(wrapped, has_aux=has_aux)
            out, grads = grad_fn(params, batch)
            loss, aux = out if has_aux else (out, None)
            loss = lax.pmean(loss, axes)

            if self.double_buffering:
                # Reference _DoubleBufferingOptimizer: allreduce this
                # step's grads into buffer B, *apply* last step's averaged
                # buffer A; skip the inner update entirely on step 0.
                new_mean = comm.allreduce_grad(grads)
                stale = state.comm_buf

                def do_update(operand):
                    params, inner, stale = operand
                    updates, inner = opt.update(stale, inner, params)
                    return optax.apply_updates(params, updates), inner

                params, inner = lax.cond(
                    state.step > 0,
                    do_update,
                    lambda operand: (operand[0], operand[1]),
                    (params, state.inner, stale),
                )
                new_state = MultiNodeOptimizerState(
                    inner=inner, step=state.step + 1, comm_buf=new_mean
                )
            else:
                grads = comm.allreduce_grad(grads)
                updates, inner = opt.update(grads, state.inner, params)
                params = optax.apply_updates(params, updates)
                new_state = MultiNodeOptimizerState(
                    inner=inner, step=state.step + 1, comm_buf=()
                )
            if has_aux:
                return params, new_state, loss, aux
            return params, new_state, loss

        n_out = 4 if has_aux else 3
        mapped = comm.shard_map(
            body,
            in_specs=(P(), P(), batch_spec),
            out_specs=(P(),) * n_out,
        )
        donate_argnums = (0, 1) if donate else ()
        jitted = jax.jit(mapped, donate_argnums=donate_argnums)
        n_dev = comm.device_size

        @functools.wraps(jitted)
        def step(params, state, batch):
            for leaf in jax.tree.leaves(batch):
                if hasattr(leaf, "shape") and leaf.shape and leaf.shape[0] % n_dev:
                    raise ValueError(
                        f"global batch axis ({leaf.shape[0]}) must be divisible "
                        f"by the communicator's device count ({n_dev}); pad or "
                        f"drop the remainder (see datasets.toy.batch_iterator "
                        f"drop_last)"
                    )
            return jitted(params, state, batch)

        return step

    def _make_zero_train_step(self, loss_fn, batch_spec, donate, has_aux, rng):
        """ZeRO-1 step: reduce-scatter grads → update local flat shard →
        all-gather params.  Communication volume equals one allreduce
        (reduce-scatter + all-gather IS a ring allreduce split in half), so
        this costs nothing extra on the wire while dividing optimizer-state
        memory by the world size."""
        comm = self.communicator
        axes = comm.axes
        world = axes if len(axes) > 1 else axes[0]
        opt = self.actual_optimizer

        def body(params, state, batch):
            if rng is not None:
                key = jax.random.fold_in(
                    jax.random.fold_in(rng, state.step), comm.axis_index()
                )
                wrapped = lambda p, b: loss_fn(p, b, key)  # noqa: E731
            else:
                wrapped = loss_fn
            out, grads = jax.value_and_grad(wrapped, has_aux=has_aux)(params, batch)
            loss, aux = out if has_aux else (out, None)
            loss = lax.pmean(loss, axes)

            n, total, shard_size = self._zero_geometry(params)
            gflat, _ = self._zero_pack(grads, shard_size * n)
            if comm.allreduce_grad_dtype is not None:
                gflat = gflat.astype(comm.allreduce_grad_dtype)
            gshard = (
                lax.psum_scatter(gflat, world, scatter_dimension=0, tiled=True) / n
            ).astype(jnp.float32)

            pflat, unpack = self._zero_pack(params, shard_size * n)
            pshard = lax.dynamic_slice_in_dim(
                pflat, comm.axis_index() * shard_size, shard_size
            )
            updates, inner = opt.update(gshard, state.inner, pshard)
            pshard = optax.apply_updates(pshard, updates)
            pfull = lax.all_gather(pshard, world, axis=0, tiled=True)
            new_params = unpack(pfull[: shard_size * n])
            new_params = jax.tree.map(
                lambda x, ref: x.astype(ref.dtype), new_params, params
            )
            new_state = MultiNodeOptimizerState(
                inner=inner, step=state.step + 1, comm_buf=()
            )
            if has_aux:
                return new_params, new_state, loss, aux
            return new_params, new_state, loss

        # Geometry depends only on parameter shapes; derive the inner-state
        # spec lazily at first call via closure over the real params.
        def make(params_example):
            n, total, shard = self._zero_geometry(params_example)
            state_spec = MultiNodeOptimizerState(
                inner=self._zero_inner_spec(shard), step=P(), comm_buf=(),
            )
            n_out = 4 if has_aux else 3
            mapped = comm.shard_map(
                body,
                in_specs=(P(), state_spec, batch_spec),
                out_specs=(P(), state_spec) + (P(),) * (n_out - 2),
            )
            return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())

        compiled = {}

        def step(params, state, batch):
            # PyTreeDefs are hashable and stable — safe cache keys (an id()
            # of a temporary would be reusable after GC).
            key = jax.tree.structure(params)
            fn = compiled.get(key)
            if fn is None:
                fn = compiled[key] = make(params)
            return fn(params, state, batch)

        return step

    def make_train_step_with_state(
        self,
        loss_fn: Callable,
        batch_spec=None,
        donate: bool = True,
    ):
        """Like :meth:`make_train_step` for models with non-trainable mutable
        state (BatchNorm statistics etc. — flax's ``batch_stats``).

        ``loss_fn(params, model_state, batch) -> (loss, new_model_state)``.
        The new model state is ``pmean``-synchronized across the world —
        cross-replica BatchNorm, a strict improvement over the reference's
        per-GPU statistics.

        Returns ``step(params, opt_state, model_state, batch) ->
        (params, opt_state, model_state, loss)``.
        """
        if self.double_buffering:
            raise NotImplementedError(
                "double_buffering with mutable model state is not supported "
                "yet; use make_train_step or double_buffering=False"
            )
        comm = self.communicator
        axes = comm.axes
        if batch_spec is None:
            batch_spec = P(axes if len(axes) > 1 else axes[0])
        opt = self.actual_optimizer

        def body(params, state, model_state, batch):
            (loss, new_model_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, model_state, batch)
            loss = lax.pmean(loss, axes)
            new_model_state = jax.tree.map(
                lambda x: lax.pmean(x, axes)
                if jnp.issubdtype(x.dtype, jnp.floating)
                else x,
                new_model_state,
            )
            grads = comm.allreduce_grad(grads)
            updates, inner = opt.update(grads, state.inner, params)
            params = optax.apply_updates(params, updates)
            new_state = MultiNodeOptimizerState(
                inner=inner, step=state.step + 1, comm_buf=()
            )
            return params, new_state, new_model_state, loss

        mapped = comm.shard_map(
            body,
            in_specs=(P(), P(), P(), batch_spec),
            out_specs=(P(),) * 4,
        )
        donate_argnums = (0, 1, 2) if donate else ()
        return jax.jit(mapped, donate_argnums=donate_argnums)

    # ------------------------------------------------------------------
    # Imperative parity API (reference: optimizer.setup(model) + update())
    # ------------------------------------------------------------------
    def setup(self, params, loss_fn: Callable, batch_spec=None):
        self._params = self.broadcast_params(params)
        self._state = self.init(self._params)
        self._step_fn = self.make_train_step(
            loss_fn, batch_spec=batch_spec, donate=False
        )
        return self

    def update(self, batch):
        """Imperative one-step update, mirroring the reference's
        ``optimizer.update(loss_func, *args)`` call shape."""
        if self._step_fn is None:
            raise RuntimeError("call setup(params, loss_fn) before update()")
        self._params, self._state, loss = self._step_fn(
            self._params, self._state, batch
        )
        return loss

    @property
    def target(self):
        """Current parameters (reference: ``optimizer.target`` is the model)."""
        return self._params

    @property
    def t(self):
        return int(self._state.step) if self._state is not None else 0


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator: CommunicatorBase,
    double_buffering: bool = False,
    zero_stage: int = 0,
) -> MultiNodeOptimizer:
    """Reference-parity factory (REF:chainermn/optimizers.py), extended
    with ``zero_stage=1`` optimizer-state sharding."""
    return MultiNodeOptimizer(
        actual_optimizer,
        communicator,
        double_buffering=double_buffering,
        zero_stage=zero_stage,
    )
