"""Multi-node optimizer — the data-parallel hot path.

Reference: REF:chainermn/optimizers.py — ``create_multi_node_optimizer(
actual_optimizer, communicator, double_buffering=False)`` wraps any Chainer
optimizer; on ``update()`` it (first call) broadcasts model parameters from
rank 0, then runs local backward, ``communicator.allreduce_grad(model)``,
and the inner optimizer's update.  ``_DoubleBufferingOptimizer`` overlaps
this step's allreduce with the next step's compute, applying one-step-stale
averaged gradients.

TPU-native translation (SURVEY §7 "hard part 2" — the eager-API ↔
traced-step impedance): the reference's imperative per-step
``allreduce_grad`` call becomes a collective *traced into* one jitted step
function.  ``make_train_step`` builds that step: a ``shard_map`` over the
communicator's mesh computes per-device gradients on the local batch shard,
runs the communicator's characteristic allreduce, and applies an inner
`optax` transformation on the (now replicated) mean gradients.  XLA then
owns the overlap: async collectives hide the allreduce behind surrounding
compute where data dependence allows, which is what the reference's
dedicated side stream bought it.

Double buffering keeps its reference *semantics* (apply one-step-stale
means; the first call only reduces, no update) because the staleness — not
the stream machinery — is what changes training behavior; the overlap
itself widens, since with stale application the collective's result is not
needed until the *next* step and XLA may overlap it across the entire
step boundary.

The imperative parity surface (``setup``/``update``/``target``) is a thin
stateful veneer over the functional path for users arriving from the
reference API.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.observability.spans import named_scope


def _check_batch_divisibility(batch, n_dev, n_accum=1):
    quantum = n_dev * n_accum
    for leaf in jax.tree.leaves(batch):
        if hasattr(leaf, "shape") and leaf.shape and leaf.shape[0] % quantum:
            raise ValueError(
                f"global batch axis ({leaf.shape[0]}) must be divisible by "
                f"device count x n_accum ({n_dev} x {n_accum} = {quantum}); "
                f"pad or drop the remainder (see datasets.toy.batch_iterator "
                f"drop_last)"
            )


def _instrument_step(step_fn):
    """Telemetry wrapper for a built train step: when a Reporter or
    StepRecorder is installed (``observability.telemetry_active``) each
    call runs under ``span("train_step")`` — profiler annotation +
    host-side duration into both sinks — and bumps the reporter's
    ``train_step_calls`` counter.  With no telemetry installed the cost
    is one boolean check, so steps stay wrappable unconditionally."""
    from chainermn_tpu.observability import spans as _spans

    @functools.wraps(step_fn)
    def instrumented(*args, **kwargs):
        if not _spans.telemetry_active():
            return step_fn(*args, **kwargs)
        from chainermn_tpu.observability import reporter as _rep

        with _spans.span("train_step"):
            out = step_fn(*args, **kwargs)
        rep = _rep.get_reporter()
        if rep is not None:
            rep.count("train_step_calls")
        return out

    # Keep jit's AOT surface reachable (bench.py lowers the step for
    # XLA's cost model; the recompile-count guard reads _cache_size);
    # plain-function steps just skip this.
    for attr in ("lower", "eval_shape", "trace", "_cache_size"):
        if hasattr(step_fn, attr):
            setattr(instrumented, attr, getattr(step_fn, attr))
    return instrumented


def _run_first_call_lint(step_fn, comm, mode, args, kwargs):
    """One lint pass over the step being compiled for the first time.
    Lint infrastructure failures must never take down training, so
    everything short of a strict-mode violation is a warning."""
    import warnings

    try:
        from chainermn_tpu.analysis import analyze_fn

        report = analyze_fn(step_fn, *args, comm=comm, **kwargs)
    except Exception as e:  # tracing oddity, not a user bug
        warnings.warn(f"CHAINERMN_TPU_LINT: lint pass failed: {e!r}")
        return
    try:
        from chainermn_tpu.observability import reporter as _rep
        from chainermn_tpu.observability import step_log as _sl

        rep = _rep.get_reporter()
        if rep is not None:
            rep.count("lint/findings", len(report.findings))
            rep.count("lint/errors", len(report.errors))
        rec = _sl.current_recorder()
        if rec is not None:
            rec.record(
                "lint",
                rules_run=list(report.rules_run),
                findings=[f.summary() for f in report.findings],
            )
    except Exception:
        pass
    if report.errors:
        if mode == "strict":
            from chainermn_tpu.analysis import LintError

            raise LintError(report)
        warnings.warn(
            "CHAINERMN_TPU_LINT found problems in the train step:\n"
            + report.render()
        )


def _lint_hook(step_fn, comm):
    """Opt-in static lint at the step's first call (the call that pays
    for compilation anyway): ``CHAINERMN_TPU_LINT=1`` warns and reports
    through the Reporter/step log, ``=strict`` raises ``LintError``.
    Unset, the step function passes through untouched — and after the
    first call the cost is one list check."""
    mode = os.environ.get("CHAINERMN_TPU_LINT", "").strip().lower()
    if mode in ("", "0", "off", "false"):
        return step_fn
    done = []

    @functools.wraps(step_fn)
    def linted(*args, **kwargs):
        if not done:
            done.append(True)
            _run_first_call_lint(step_fn, comm, mode, args, kwargs)
        return step_fn(*args, **kwargs)

    for attr in ("lower", "eval_shape", "trace", "_cache_size"):
        if hasattr(step_fn, attr):
            setattr(linted, attr, getattr(step_fn, attr))
    return linted


def flat_shard_state_spec(optimizer, shard_size: int, world):
    """Per-leaf PartitionSpecs for an optax state over a flat fp32 shard:
    shard-sized 1-D leaves ride the world axes, scalars (e.g. adam's count)
    replicate.  Shared by the ZeRO optimizer paths and the sharded
    MultiNodeChainList tier."""

    def leaf_spec(leaf):
        shape = getattr(leaf, "shape", ())
        return P(world) if (len(shape) == 1 and shape[0] == shard_size) else P()

    shard = jax.ShapeDtypeStruct((shard_size,), jnp.float32)
    state_shape = jax.eval_shape(optimizer.init, shard)
    return jax.tree.map(leaf_spec, state_shape)


class MultiNodeOptimizerState(NamedTuple):
    inner: Any            # the wrapped optax optimizer's state
    step: jnp.ndarray     # int32 step counter
    comm_buf: Any         # double buffering: previous step's averaged grads
                          # (None-like zeros tree when double_buffering=False)


class MultiNodeOptimizer:
    """Wrap an ``optax.GradientTransformation`` with distributed gradient
    averaging — the reference's ``_MultiNodeOptimizer`` reimagined for
    traced steps."""

    def __init__(
        self,
        actual_optimizer: optax.GradientTransformation,
        communicator: CommunicatorBase,
        double_buffering: bool = False,
        zero_stage: int = 0,
    ):
        """ZeRO staging (the TPU-native memory ladder the reference never
        had — its optimizer state, gradients, and parameters were fully
        replicated per GPU):

        - ``zero_stage=1``: optimizer state sharded 1/n per device.
          Gradients arrive by reduce-scatter, the inner optimizer updates
          only the local flat shard, updated parameters are all-gathered.
        - ``zero_stage=2``: additionally, with gradient accumulation
          (``n_accum > 1``) each microbatch's gradients are reduce-scattered
          immediately, so the accumulator is a 1/n shard instead of a full
          gradient tree.  Without accumulation it is identical to stage 1
          (inside one fused step XLA never materializes persistent full
          gradients anyway).
        - ``zero_stage=3``: master parameters themselves live sharded 1/n
          per device between steps as one flat fp32 buffer; each step
          all-gathers them, computes, reduce-scatters gradients, and
          updates only the local shard.  The train step then takes and
          returns the flat buffer — use :meth:`shard_params` /
          :meth:`materialize` to convert to/from the user pytree.
        """
        self.actual_optimizer = actual_optimizer
        self.communicator = communicator
        self.double_buffering = double_buffering
        if zero_stage not in (0, 1, 2, 3):
            raise ValueError("zero_stage must be 0, 1, 2 or 3")
        self.zero_stage = zero_stage
        # ZeRO-3 pack metadata: (treedef, [(shape, dtype, size)]) captured by
        # shard_params/init so the flat buffer can be unpacked without the
        # original tree in hand.  _z3_jit caches the shard/materialize jits
        # per metadata so repeated calls don't recompile.
        self._z3_meta = None
        self._z3_jit = {}
        # imperative-parity state (setup/update/target)
        self._params = None
        self._state = None
        self._step_fn = None
        self._setup_has_aux = False

    # ------------------------------------------------------------------
    # Functional API
    # ------------------------------------------------------------------
    def init(self, params, *, _skip_broadcast: bool = False
             ) -> MultiNodeOptimizerState:
        """Initialize optimizer state.  The analogue of the reference's
        first-``update`` ``broadcast_data``: parameters are replicated from
        process 0 so every host starts identical.  (``_skip_broadcast``:
        internal — setup() broadcasts once itself and must not pay the
        full-tree collective twice.)"""
        if not _skip_broadcast:
            params = self.broadcast_params(params)
        if self.zero_stage == 3:
            self._capture_z3_meta(params)
        if self.zero_stage > 0:
            inner = self._zero_init(params)
        else:
            inner = self.actual_optimizer.init(params)
        if not self.double_buffering:
            zeros = ()
        elif self.zero_stage > 0:
            # Stale means live as the 1/n fp32 gradient shard — double
            # buffering costs shard-sized memory under ZeRO, not a full
            # gradient tree.
            n, _, shard_size = self._zero_geometry(params)
            zeros = jnp.zeros((shard_size * n,), jnp.float32)
        else:
            zeros = jax.tree.map(jnp.zeros_like, params)
        return MultiNodeOptimizerState(
            inner=inner,
            step=jnp.zeros((), jnp.int32),
            comm_buf=zeros,
        )

    # ------------------------------------------------------------------
    # ZeRO-1 plumbing: flat padded buffer, per-device shard
    # ------------------------------------------------------------------
    def _zero_geometry(self, params):
        n = self.communicator.device_size
        total = sum(l.size for l in jax.tree.leaves(params))
        pad = (-total) % n
        return n, total, (total + pad) // n

    def _zero_pack(self, tree, padded_size):
        from chainermn_tpu.communicators.packing import pack_tree

        return pack_tree(
            jax.tree.map(
                lambda x: x if x.dtype == jnp.float32
                else x.astype(jnp.float32),
                tree,
            ),
            pad_to=padded_size,
        )

    def _zero_inner_spec(self, shard_size):
        return flat_shard_state_spec(
            self.actual_optimizer, shard_size, self.communicator.world_axes
        )

    def _zero_init(self, params):
        comm = self.communicator
        n, total, shard_size = self._zero_geometry(params)

        def body(params):
            flat, _ = self._zero_pack(params, shard_size * n)
            mine = lax.dynamic_slice_in_dim(
                flat, comm.axis_index() * shard_size, shard_size
            )
            return self.actual_optimizer.init(mine)

        return jax.jit(
            comm.shard_map(
                body, in_specs=(P(),), out_specs=self._zero_inner_spec(shard_size)
            )
        )(params)

    # ------------------------------------------------------------------
    # ZeRO-3 plumbing: params live as ONE flat fp32 buffer sharded P(world)
    # ------------------------------------------------------------------
    def _capture_z3_meta(self, params):
        leaves, treedef = jax.tree.flatten(params)
        self._z3_meta = (
            treedef,
            [(l.shape, l.dtype, l.size) for l in leaves],
        )

    def _z3_unpack(self, buf):
        """Unflatten the gathered fp32 buffer back into the user pytree at
        each leaf's original shape and dtype (the forward-compute copy)."""
        treedef, metas = self._z3_meta
        out, off = [], 0
        for shape, dtype, size in metas:
            out.append(buf[off : off + size].reshape(shape).astype(dtype))
            off += size
        return jax.tree.unflatten(treedef, out)

    def _world_axis(self):
        comm = self.communicator
        return comm.axes if len(comm.axes) > 1 else comm.axes[0]

    def _z3_key(self, kind):
        treedef, metas = self._z3_meta
        return (kind, treedef, tuple(metas))

    def shard_params(self, params):
        """ZeRO-3 entry: user pytree → flat fp32 master buffer, one 1/n
        shard resident per device.  The returned array is what the stage-3
        train step takes and returns in place of the pytree."""
        if self.zero_stage != 3:
            raise ValueError("shard_params is only meaningful for zero_stage=3")
        comm = self.communicator
        self._capture_z3_meta(params)
        n, _, shard_size = self._zero_geometry(params)
        world = self._world_axis()

        fn = self._z3_jit.get(self._z3_key("shard"))
        if fn is None:

            def body(tree):
                flat, _ = self._zero_pack(tree, shard_size * n)
                return lax.dynamic_slice_in_dim(
                    flat, comm.axis_index() * shard_size, shard_size
                )

            fn = jax.jit(comm.shard_map(body, in_specs=(P(),), out_specs=P(world)))
            self._z3_jit[self._z3_key("shard")] = fn
        return fn(params)

    def materialize(self, flat):
        """ZeRO-3 exit: flat sharded master buffer → replicated user pytree
        (for evaluation, checkpoint export, or leaving stage-3 training)."""
        if self._z3_meta is None:
            raise RuntimeError("call shard_params (or init) before materialize")
        comm = self.communicator
        world = self._world_axis()

        fn = self._z3_jit.get(self._z3_key("mat"))
        if fn is None:

            def body(local):
                full = lax.all_gather(local, world, axis=0, tiled=True)
                return self._z3_unpack(full)

            fn = jax.jit(comm.shard_map(body, in_specs=(P(world),), out_specs=P()))
            self._z3_jit[self._z3_key("mat")] = fn
        return fn(flat)

    def broadcast_params(self, params):
        """Host-plane replication from process 0 (reference
        ``broadcast_data``).  A no-op on one host: device-plane replication
        is the sharding's job under jit."""
        if self.communicator.size > 1:
            from jax.experimental import multihost_utils

            params = multihost_utils.broadcast_one_to_all(params)
        return params

    # ------------------------------------------------------------------
    # Microbatch gradient machinery shared by every stage
    # ------------------------------------------------------------------
    def _make_micro_grad_fn(self, loss_fn, has_aux, loss_scale):
        """Return ``one(params, microbatch, key) -> (loss, aux, grads)``.

        With ``loss_scale`` the returned gradients are SCALED — they stay
        scaled through accumulation and the (possibly reduced-precision)
        collective, preserving small-magnitude structure on the wire, and
        are unscaled by the caller just before the optimizer update.  The
        returned loss is always unscaled.
        """

        def one(params, mb, key):
            f = loss_fn if key is None else (lambda p, b: loss_fn(p, b, key))
            if loss_scale is not None:
                if has_aux:
                    g = lambda p, b: (  # noqa: E731
                        lambda o: (o[0] * loss_scale, o[1])
                    )(f(p, b))
                else:
                    g = lambda p, b: f(p, b) * loss_scale  # noqa: E731
            else:
                g = f
            out, grads = jax.value_and_grad(g, has_aux=has_aux)(params, mb)
            loss, aux = out if has_aux else (out, None)
            if loss_scale is not None:
                loss = loss / loss_scale
            return loss, aux, grads

        return one

    def _split_micro(self, batch, n_accum):
        """(B, ...) local batch → (n_accum, B/n_accum, ...) microbatches."""
        return jax.tree.map(
            lambda x: x.reshape(n_accum, x.shape[0] // n_accum, *x.shape[1:]),
            batch,
        )

    def _base_key(self, rng, step):
        if rng is None:
            return None
        return jax.random.fold_in(
            jax.random.fold_in(rng, step), self.communicator.axis_index()
        )

    def _accum_local_grads(self, one, params, batch, base_key, n_accum):
        """Scan the microbatches, accumulating FULL local gradient trees
        (stages 0 and 1).  Returns (mean_loss, stacked_aux, mean_grads)."""
        with named_scope("fwd-bwd"):
            if n_accum == 1:
                loss, aux, grads = one(
                    params, batch, base_key
                )
                return loss, aux, grads

            micro = self._split_micro(batch, n_accum)

            def mb(carry, xs):
                gacc, lacc = carry
                i, b = xs
                key = (None if base_key is None
                       else jax.random.fold_in(base_key, i))
                loss, aux, grads = one(params, b, key)
                gacc = jax.tree.map(jnp.add, gacc, grads)
                return (gacc, lacc + loss), aux

            zeros = jax.tree.map(jnp.zeros_like, params)
            (gacc, lsum), auxs = lax.scan(
                mb, (zeros, jnp.zeros((), jnp.float32)),
                (jnp.arange(n_accum), micro)
            )
            grads = jax.tree.map(lambda g: g / n_accum, gacc)
            return lsum / n_accum, auxs, grads

    def _apply_update(self, params, state, grads, loss_scale=None,
                      overlap=None):
        """Allreduce local grads and apply the inner optimizer — the shared
        tail of the stage-0 step bodies.

        With ``double_buffering``: allreduce this step's grads into buffer
        B, *apply* last step's averaged buffer A (reference
        _DoubleBufferingOptimizer), skipping the inner update entirely on
        step 0.  Scaled gradients (``loss_scale``) are unscaled exactly
        once, at application time.

        ``overlap`` pins the communicator's staged bucket emission for this
        step (``None`` defers to ctor/env — see
        :meth:`CommunicatorBase.allreduce_grad`): when on, each bucket's
        pack+allreduce is emitted as its last grad leaf becomes available
        (reverse leaf-production order), generalizing the double-buffering
        idea — instead of hiding the whole allreduce behind the *next*
        step's compute at one-step staleness, buckets hide behind *this*
        step's remaining backward compute with no staleness at all.
        """
        comm = self.communicator
        opt = self.actual_optimizer
        if self.double_buffering:
            with named_scope("allreduce"):
                new_mean = comm.allreduce_grad(grads, overlap=overlap)
            stale = state.comm_buf

            def do_update(operand):
                params, inner, stale = operand
                if loss_scale is not None:
                    stale = jax.tree.map(lambda g: g / loss_scale, stale)
                updates, inner = opt.update(stale, inner, params)
                return optax.apply_updates(params, updates), inner

            with named_scope("opt-update"):
                params, inner = lax.cond(
                    state.step > 0,
                    do_update,
                    lambda operand: (operand[0], operand[1]),
                    (params, state.inner, stale),
                )
            return params, MultiNodeOptimizerState(
                inner=inner, step=state.step + 1, comm_buf=new_mean
            )
        with named_scope("allreduce"):
            grads = comm.allreduce_grad(grads, overlap=overlap)
        if loss_scale is not None:
            grads = jax.tree.map(lambda g: g / loss_scale, grads)
        with named_scope("opt-update"):
            updates, inner = opt.update(grads, state.inner, params)
            params = optax.apply_updates(params, updates)
        return params, MultiNodeOptimizerState(
            inner=inner, step=state.step + 1, comm_buf=()
        )

    def _apply_shard_update(self, pshard, state, gshard, loss_scale=None):
        """The ZeRO analogue of :meth:`_apply_update`: apply a gradient
        *shard* to the local parameter shard.  With ``double_buffering``
        the stale shard in ``comm_buf`` is applied (skipping step 0) and
        this step's ``gshard`` is stored for the next — identical staleness
        semantics to stage 0, at 1/n the buffer memory.  Scaled gradients
        are unscaled exactly once, at application time."""
        opt = self.actual_optimizer
        if self.double_buffering:

            def do_update(operand):
                pshard, inner, stale = operand
                if loss_scale is not None:
                    stale = stale / loss_scale
                updates, inner = opt.update(stale, inner, pshard)
                return optax.apply_updates(pshard, updates), inner

            pshard, inner = lax.cond(
                state.step > 0,
                do_update,
                lambda operand: (operand[0], operand[1]),
                (pshard, state.inner, state.comm_buf),
            )
            new_state = MultiNodeOptimizerState(
                inner=inner, step=state.step + 1, comm_buf=gshard
            )
            return pshard, new_state
        if loss_scale is not None:
            gshard = gshard / loss_scale
        updates, inner = opt.update(gshard, state.inner, pshard)
        pshard = optax.apply_updates(pshard, updates)
        return pshard, MultiNodeOptimizerState(
            inner=inner, step=state.step + 1, comm_buf=()
        )

    def _zero_param_update(
        self, params, state, gshard, shard_size, n, loss_scale=None
    ):
        """The ZeRO-1/2 parameter tail shared by the stateless and
        with-model-state steps: pack params → take the local shard → apply
        the (possibly stale) gradient shard → all-gather → unpack at the
        original dtypes."""
        comm = self.communicator
        world = self._world_axis()
        pflat, unpack = self._zero_pack(params, shard_size * n)
        pshard = lax.dynamic_slice_in_dim(
            pflat, comm.axis_index() * shard_size, shard_size
        )
        pshard, new_state = self._apply_shard_update(
            pshard, state, gshard, loss_scale
        )
        pfull = lax.all_gather(pshard, world, axis=0, tiled=True)
        new_params = unpack(pfull[: shard_size * n])
        new_params = jax.tree.map(
            lambda x, ref: x.astype(ref.dtype), new_params, params
        )
        return new_params, new_state

    def _zero_state_spec(self, shard_size):
        """The MultiNodeOptimizerState PartitionSpec for ZeRO steps: inner
        state sharded over the world, comm_buf likewise when double
        buffering holds the stale gradient shard."""
        world = self._world_axis()
        return MultiNodeOptimizerState(
            inner=self._zero_inner_spec(shard_size),
            step=P(),
            comm_buf=P(world) if self.double_buffering else (),
        )

    def _finalize_step(self, step_fn):
        """Every built train step exits through here: the opt-in lint
        hook (innermost, so it traces the bare step) then telemetry."""
        return _instrument_step(_lint_hook(step_fn, self.communicator))

    def make_train_step(
        self,
        loss_fn: Callable,
        batch_spec=None,
        donate: bool = True,
        has_aux: bool = False,
        rng: Any = None,
        n_accum: int = 1,
        loss_scale: float | None = None,
        overlap: bool | None = None,
    ):
        """Build the jitted SPMD training step.

        ``loss_fn(params, batch) -> loss`` (or ``(loss, aux)`` with
        ``has_aux``) computes the *local* mean loss on one device's batch
        shard; the step averages gradients with the communicator's
        characteristic collective pattern and applies the inner optimizer.

        With ``rng`` (a base PRNGKey), ``loss_fn(params, batch, rng)`` is
        called with a key folded over (step, device rank) — per-device
        dropout/augmentation randomness that stays reproducible.

        ``n_accum > 1`` splits each device's batch shard into that many
        microbatches and accumulates gradients over a ``lax.scan`` before
        the collective — same math as the full batch (equal microbatch
        sizes), bounded activation memory.  With ``has_aux`` the aux is
        then stacked along a leading ``n_accum`` axis.

        ``loss_scale`` multiplies the loss before differentiation and
        unscales gradients after communication — parity knob for fp16-style
        mixed precision (bf16, the TPU default, does not need it).

        ``overlap`` pins the staged bucket/allreduce pipeline for this
        step: buckets are emitted in reverse leaf-production order so each
        ``all-reduce-start`` can straddle the remaining backward compute
        (XLA async collectives + the latency-hiding scheduler).  ``None``
        (default) resolves communicator ctor → ``CHAINERMN_TPU_OVERLAP``
        env (default ON); ``False`` forces the eager pack-all-then-reduce
        schedule.  Bit-exact either way.  ZeRO steps reduce-scatter one
        flat shard and have nothing to stage, so the knob is inert there.

        Returns ``step(params, state, batch) -> (params, state, loss[, aux])``.
        """
        comm = self.communicator
        axes = comm.axes
        if batch_spec is None:
            batch_spec = P(axes if len(axes) > 1 else axes[0])
        opt = self.actual_optimizer
        if n_accum < 1:
            raise ValueError(f"n_accum must be >= 1, got {n_accum}")
        if self.zero_stage in (1, 2):
            return self._finalize_step(self._make_zero_train_step(
                loss_fn, batch_spec, donate, has_aux, rng, n_accum, loss_scale
            ))
        if self.zero_stage == 3:
            return self._finalize_step(self._make_zero3_train_step(
                loss_fn, batch_spec, donate, has_aux, rng, n_accum, loss_scale
            ))
        one = self._make_micro_grad_fn(loss_fn, has_aux, loss_scale)

        def body(params, state, batch):
            loss, aux, grads = self._accum_local_grads(
                one, params, batch, self._base_key(rng, state.step), n_accum
            )
            loss = lax.pmean(loss, axes)
            params, new_state = self._apply_update(
                params, state, grads, loss_scale, overlap=overlap
            )
            if has_aux:
                return params, new_state, loss, aux
            return params, new_state, loss

        n_out = 4 if has_aux else 3
        mapped = comm.shard_map(
            body,
            in_specs=(P(), P(), batch_spec),
            out_specs=(P(),) * n_out,
        )
        donate_argnums = (0, 1) if donate else ()
        jitted = jax.jit(mapped, donate_argnums=donate_argnums)
        n_dev = comm.device_size

        @functools.wraps(jitted)
        def step(params, state, batch):
            _check_batch_divisibility(batch, n_dev, n_accum)
            return jitted(params, state, batch)

        if hasattr(jitted, "_cache_size"):
            step._cache_size = jitted._cache_size
        return self._finalize_step(step)

    def _scatter_grads(self, grads, shard_size, n, world):
        """Pack a full local gradient tree and reduce-scatter it to this
        device's fp32 flat shard (mean over the world)."""
        comm = self.communicator
        gflat, _ = self._zero_pack(grads, shard_size * n)
        if comm.allreduce_grad_dtype is not None:
            gflat = gflat.astype(comm.allreduce_grad_dtype)
        with named_scope("allreduce"):
            gshard = lax.psum_scatter(
                gflat, world, scatter_dimension=0, tiled=True
            ) / n
        return gshard.astype(jnp.float32)

    def _accum_scattered_grads(
        self, one, params, batch, base_key, n_accum, shard_size, n, world
    ):
        """Scan the microbatches, reduce-scattering each one's gradients and
        accumulating only the 1/n fp32 shard (ZeRO-2/3).  Returns
        ``(gshard, mean_loss, aux)``; with ``n_accum == 1`` there is no scan
        and aux comes back unstacked, matching the stage-0/1 contract."""
        if n_accum == 1:
            loss, aux, grads = one(params, batch, base_key)
            return self._scatter_grads(grads, shard_size, n, world), loss, aux

        micro = self._split_micro(batch, n_accum)

        def mb(carry, xs):
            sacc, lacc = carry
            i, b = xs
            key = None if base_key is None else jax.random.fold_in(base_key, i)
            loss, aux, grads = one(params, b, key)
            sacc = sacc + self._scatter_grads(grads, shard_size, n, world)
            return (sacc, lacc + loss), aux

        (sacc, lsum), aux = lax.scan(
            mb,
            (jnp.zeros((shard_size,), jnp.float32),
             jnp.zeros((), jnp.float32)),
            (jnp.arange(n_accum), micro),
        )
        return sacc / n_accum, lsum / n_accum, aux

    def _make_zero_train_step(
        self, loss_fn, batch_spec, donate, has_aux, rng, n_accum, loss_scale
    ):
        """ZeRO-1/2 step: reduce-scatter grads → update local flat shard →
        all-gather params.  Communication volume equals one allreduce
        (reduce-scatter + all-gather IS a ring allreduce split in half), so
        this costs nothing extra on the wire while dividing optimizer-state
        memory by the world size.

        Stage 2 (only distinct under gradient accumulation): each
        microbatch's gradients are reduce-scattered inside the scan and only
        the 1/n fp32 shard is accumulated — gradient-accumulator memory
        drops from a full tree to ``total/n`` at the price of ``n_accum``
        smaller collectives instead of one (same total bytes on the wire,
        more latency terms).
        """
        comm = self.communicator
        axes = comm.axes
        world = self._world_axis()
        one = self._make_micro_grad_fn(loss_fn, has_aux, loss_scale)
        per_micro_scatter = self.zero_stage == 2 and n_accum > 1

        def body(params, state, batch):
            n, total, shard_size = self._zero_geometry(params)
            base_key = self._base_key(rng, state.step)

            if per_micro_scatter:
                gshard, loss, aux = self._accum_scattered_grads(
                    one, params, batch, base_key, n_accum, shard_size, n, world
                )
            else:
                loss, aux, grads = self._accum_local_grads(
                    one, params, batch, base_key, n_accum
                )
                gshard = self._scatter_grads(grads, shard_size, n, world)
            loss = lax.pmean(loss, axes)
            new_params, new_state = self._zero_param_update(
                params, state, gshard, shard_size, n, loss_scale
            )
            if has_aux:
                return new_params, new_state, loss, aux
            return new_params, new_state, loss

        # Geometry depends only on parameter shapes; derive the inner-state
        # spec lazily at first call via closure over the real params.
        def make(params_example):
            n, total, shard = self._zero_geometry(params_example)
            state_spec = self._zero_state_spec(shard)
            n_out = 4 if has_aux else 3
            mapped = comm.shard_map(
                body,
                in_specs=(P(), state_spec, batch_spec),
                out_specs=(P(), state_spec) + (P(),) * (n_out - 2),
            )
            return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())

        compiled = {}

        def step(params, state, batch):
            # PyTreeDefs are hashable and stable — safe cache keys (an id()
            # of a temporary would be reusable after GC).
            _check_batch_divisibility(batch, comm.device_size, n_accum)
            key = jax.tree.structure(params)
            fn = compiled.get(key)
            if fn is None:
                fn = compiled[key] = make(params)
            return fn(params, state, batch)

        return step

    def _make_zero3_train_step(
        self, loss_fn, batch_spec, donate, has_aux, rng, n_accum, loss_scale
    ):
        """ZeRO-3 step: master parameters are ONE flat fp32 buffer sharded
        1/n per device *between* steps.  Each step all-gathers the buffer,
        unpacks it into the user pytree at compute dtype, runs fwd/bwd,
        reduce-scatters gradients, and updates only the local shard — the
        returned buffer is again 1/n resident per device.

        Per-step wire cost is one all-gather (params) + one reduce-scatter
        (grads) = the volume of one ring allreduce; the gathered compute
        copy is transient within the step (XLA frees it after backward), so
        persistent parameter + optimizer memory is ``O(total/n)``.

        The step signature is ``step(flat_params, state, batch)`` with
        ``flat_params`` from :meth:`shard_params`; recover the pytree with
        :meth:`materialize`.
        """
        comm = self.communicator
        axes = comm.axes
        world = self._world_axis()
        one = self._make_micro_grad_fn(loss_fn, has_aux, loss_scale)

        def body(pshard, state, batch):
            n = comm.device_size
            shard_size = pshard.shape[0]
            pfull = lax.all_gather(pshard, world, axis=0, tiled=True)
            params = self._z3_unpack(pfull)
            base_key = self._base_key(rng, state.step)
            gshard, loss, aux = self._accum_scattered_grads(
                one, params, batch, base_key, n_accum, shard_size, n, world
            )
            loss = lax.pmean(loss, axes)
            new_pshard, new_state = self._apply_shard_update(
                pshard, state, gshard, loss_scale
            )
            if has_aux:
                return new_pshard, new_state, loss, aux
            return new_pshard, new_state, loss

        def make(flat_example):
            shard = flat_example.shape[0] // comm.device_size
            state_spec = self._zero_state_spec(shard)
            n_out = 4 if has_aux else 3
            mapped = comm.shard_map(
                body,
                in_specs=(P(world), state_spec, batch_spec),
                out_specs=(P(world), state_spec) + (P(),) * (n_out - 2),
            )
            return jax.jit(mapped, donate_argnums=(0, 1) if donate else ())

        compiled = {}

        def step(flat_params, state, batch):
            if self._z3_meta is None:
                raise RuntimeError(
                    "zero_stage=3: call init(params) (or shard_params) first"
                )
            _check_batch_divisibility(batch, comm.device_size, n_accum)
            # The traced body bakes in the unpack metadata, so the cache key
            # must include it — same padded size with a different tree
            # layout must re-trace, not silently reuse the wrong unpacking.
            treedef, metas = self._z3_meta
            key = (flat_params.shape, treedef, tuple(metas))
            fn = compiled.get(key)
            if fn is None:
                fn = compiled[key] = make(flat_params)
            return fn(flat_params, state, batch)

        return step

    def make_train_step_with_state(
        self,
        loss_fn: Callable,
        batch_spec=None,
        donate: bool = True,
        overlap: bool | None = None,
    ):
        """Like :meth:`make_train_step` for models with non-trainable mutable
        state (BatchNorm statistics etc. — flax's ``batch_stats``).

        ``overlap`` pins the staged bucket/allreduce pipeline exactly as in
        :meth:`make_train_step` (``None`` = ctor → env, default ON;
        bit-exact either way; inert for ZeRO).

        ``loss_fn(params, model_state, batch) -> (loss, new_model_state)``.
        The new model state is ``pmean``-synchronized across the world —
        cross-replica BatchNorm, a strict improvement over the reference's
        per-GPU statistics.

        Returns ``step(params, opt_state, model_state, batch) ->
        (params, opt_state, model_state, loss)``.

        ``double_buffering`` works here too: step N applies step N−1's
        averaged gradients (first step reduce-only), while model state
        (BatchNorm statistics) always updates from the CURRENT step —
        statistics are running estimates, not gradients, so staleness
        semantics do not apply to them.

        ZeRO works here too: stages 1/2 keep the pytree step signature with
        the optimizer state sharded; stage 3 takes/returns the flat sharded
        master buffer in place of the params pytree (as
        :meth:`make_train_step` does) — ``step(flat_params, opt_state,
        model_state, batch)``.
        """
        comm = self.communicator
        axes = comm.axes
        if batch_spec is None:
            batch_spec = P(axes if len(axes) > 1 else axes[0])

        def grads_and_state(params, model_state, batch):
            (loss, new_model_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, model_state, batch)
            loss = lax.pmean(loss, axes)
            new_model_state = jax.tree.map(
                lambda x: lax.pmean(x, axes)
                if jnp.issubdtype(x.dtype, jnp.floating)
                else x,
                new_model_state,
            )
            return loss, new_model_state, grads

        if self.zero_stage > 0:
            return self._finalize_step(self._make_zero_with_state_step(
                grads_and_state, batch_spec, donate
            ))

        def body(params, state, model_state, batch):
            loss, new_model_state, grads = grads_and_state(
                params, model_state, batch
            )
            params, new_state = self._apply_update(
                params, state, grads, overlap=overlap
            )
            return params, new_state, new_model_state, loss

        mapped = comm.shard_map(
            body,
            in_specs=(P(), P(), P(), batch_spec),
            out_specs=(P(),) * 4,
        )
        donate_argnums = (0, 1, 2) if donate else ()
        return self._finalize_step(
            jax.jit(mapped, donate_argnums=donate_argnums)
        )

    def _make_zero_with_state_step(self, grads_and_state, batch_spec, donate):
        """ZeRO tails for the with-model-state step.  Stages 1/2 are
        identical here (stage 2's distinct behavior only exists under
        gradient accumulation, which the with-state surface does not
        expose); stage 3 trades the pytree for the flat sharded buffer."""
        comm = self.communicator
        world = self._world_axis()

        if self.zero_stage in (1, 2):

            def body(params, state, model_state, batch):
                n, total, shard_size = self._zero_geometry(params)
                loss, new_model_state, grads = grads_and_state(
                    params, model_state, batch
                )
                gshard = self._scatter_grads(grads, shard_size, n, world)
                new_params, new_state = self._zero_param_update(
                    params, state, gshard, shard_size, n
                )
                return new_params, new_state, new_model_state, loss

            def make(params_example):
                n, total, shard = self._zero_geometry(params_example)
                state_spec = self._zero_state_spec(shard)
                mapped = comm.shard_map(
                    body,
                    in_specs=(P(), state_spec, P(), batch_spec),
                    out_specs=(P(), state_spec, P(), P()),
                )
                return jax.jit(
                    mapped, donate_argnums=(0, 1, 2) if donate else ()
                )

            compiled = {}

            def step(params, state, model_state, batch):
                _check_batch_divisibility(batch, comm.device_size)
                key = jax.tree.structure(params)
                fn = compiled.get(key)
                if fn is None:
                    fn = compiled[key] = make(params)
                return fn(params, state, model_state, batch)

            return step

        # zero_stage == 3: flat sharded master buffer in place of params.
        def body3(pshard, state, model_state, batch):
            n = comm.device_size
            shard_size = pshard.shape[0]
            pfull = lax.all_gather(pshard, world, axis=0, tiled=True)
            params = self._z3_unpack(pfull)
            loss, new_model_state, grads = grads_and_state(
                params, model_state, batch
            )
            gshard = self._scatter_grads(grads, shard_size, n, world)
            new_pshard, new_state = self._apply_shard_update(
                pshard, state, gshard
            )
            return new_pshard, new_state, new_model_state, loss

        def make3(flat_example):
            shard = flat_example.shape[0] // comm.device_size
            state_spec = self._zero_state_spec(shard)
            mapped = comm.shard_map(
                body3,
                in_specs=(P(world), state_spec, P(), batch_spec),
                out_specs=(P(world), state_spec, P(), P()),
            )
            return jax.jit(mapped, donate_argnums=(0, 1, 2) if donate else ())

        compiled3 = {}

        def step3(flat_params, state, model_state, batch):
            if self._z3_meta is None:
                raise RuntimeError(
                    "zero_stage=3: call init(params) (or shard_params) first"
                )
            _check_batch_divisibility(batch, comm.device_size)
            treedef, metas = self._z3_meta
            key = (flat_params.shape, treedef, tuple(metas))
            fn = compiled3.get(key)
            if fn is None:
                fn = compiled3[key] = make3(flat_params)
            return fn(flat_params, state, model_state, batch)

        return step3

    # ------------------------------------------------------------------
    # Imperative parity API (reference: optimizer.setup(model) + update())
    # ------------------------------------------------------------------
    def setup(self, params, loss_fn: Callable, batch_spec=None, *,
              rng: Any = None, n_accum: int = 1, has_aux: bool = False,
              loss_scale: float | None = None):
        """Imperative surface with the FULL feature matrix of
        :meth:`make_train_step` — ``rng`` (per-(step, device) dropout
        keys), ``n_accum`` (gradient accumulation), ``has_aux`` (update()
        returns ``(loss, aux)``), ``loss_scale``, and every
        ``zero_stage`` incl. 3 (parameters live as the flat sharded
        master buffer internally; :attr:`target` materializes them)."""
        # Exactly ONE full-tree broadcast: init() is told to skip its
        # own (the reference's first-update broadcast_data contract is
        # still honored — by this call).
        params = self.broadcast_params(params)
        self._state = self.init(params, _skip_broadcast=True)
        self._params = (
            self.shard_params(params) if self.zero_stage == 3 else params
        )
        self._step_fn = self.make_train_step(
            loss_fn, batch_spec=batch_spec, donate=False,
            rng=rng, n_accum=n_accum, has_aux=has_aux,
            loss_scale=loss_scale,
        )
        self._setup_has_aux = has_aux
        return self

    def update(self, batch):
        """Imperative one-step update, mirroring the reference's
        ``optimizer.update(loss_func, *args)`` call shape.  Returns the
        loss, or ``(loss, aux)`` when setup() was given ``has_aux``."""
        if self._step_fn is None:
            raise RuntimeError("call setup(params, loss_fn) before update()")
        out = self._step_fn(self._params, self._state, batch)
        if self._setup_has_aux:
            self._params, self._state, loss, aux = out
            return loss, aux
        self._params, self._state, loss = out
        return loss

    @property
    def target(self):
        """Current parameters (reference: ``optimizer.target`` is the
        model).  Under ``zero_stage=3`` the sharded master buffer is
        materialized back to the parameter tree."""
        if self.zero_stage == 3 and self._params is not None:
            return self.materialize(self._params)
        return self._params

    @property
    def t(self):
        return int(self._state.step) if self._state is not None else 0


def create_multi_node_optimizer(
    actual_optimizer: optax.GradientTransformation,
    communicator: CommunicatorBase,
    double_buffering: bool = False,
    zero_stage: int = 0,
) -> MultiNodeOptimizer:
    """Reference-parity factory (REF:chainermn/optimizers.py), extended
    with ZeRO sharding: ``zero_stage=1`` (optimizer state), ``2`` (+ sharded
    gradient accumulation), ``3`` (+ sharded master parameters)."""
    return MultiNodeOptimizer(
        actual_optimizer,
        communicator,
        double_buffering=double_buffering,
        zero_stage=zero_stage,
    )
