"""Declarative sharding plans: one ordered rule table drives params,
grads, and optimizer moments.

A :class:`ShardingPlan` is a named, ordered list of ``(name, path-regex,
PartitionSpec)`` rules.  Resolution walks any pytree, joins each leaf's
tree path with ``/`` (the spelling ``tools.lint`` and the census tests
already use), and takes the FIRST rule whose regex ``re.search``-matches
the path and whose optional rank gate matches the leaf — scalar leaves
are auto-replicated before any rule is consulted.  Because matching is
substring search over the joined path, the SAME table resolves:

* **params** — ``layer_0/.../query/kernel``;
* **grads** — identical tree structure, identical paths;
* **optimizer moments** — optax state paths EMBED the parameter path
  (``0/mu/params/layer_0/.../query/kernel``), so the query rule matches
  the moment leaf too, and adam's scalar ``count`` auto-replicates.

That one-pass property is what lets :func:`~chainermn_tpu.parallel.
sharding.make_gspmd_train_step`, the optimizer moment placement, and the
tensor-parallel :class:`~chainermn_tpu.serving.engine.InferenceEngine`
all consume the same plan object instead of re-deriving layouts
per-consumer.  Built-in plans live in
:mod:`chainermn_tpu.sharding.registry`; coverage is lintable via
:func:`validate` (lint rule R006) and browsable via ``python -m
chainermn_tpu.tools.shardplan``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def tree_path_str(path) -> str:
    """``/``-joined spelling of a ``tree_map_with_path`` key path —
    ``DictKey``/``GetAttrKey``/``SequenceKey`` all flatten to their bare
    name, matching the path strings the lint fixtures and
    ``transformer_param_spec`` key on."""
    keys = []
    for entry in path:
        if hasattr(entry, "key"):
            keys.append(str(entry.key))
        elif hasattr(entry, "name"):
            keys.append(str(entry.name))
        elif hasattr(entry, "idx"):
            keys.append(str(entry.idx))
        else:
            keys.append(str(entry))
    return "/".join(keys)


def _spec_axes(spec: P):
    """Every mesh-axis name a PartitionSpec mentions, in entry order
    (tuple entries like ``("data", "model")`` flatten)."""
    axes = []
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.extend(str(a) for a in entry)
        else:
            axes.append(str(entry))
    return axes


@dataclasses.dataclass(frozen=True)
class PlanRule:
    """One row of the table: ``pattern`` is ``re.search``-ed against the
    ``/``-joined leaf path; ``ndim`` (when set) additionally gates on
    the leaf's rank — the regex-table rendering of the old
    ``transformer_param_spec`` shape conditions (a ``query`` *bias* is
    2-D and must fall through to replication)."""

    name: str
    pattern: str
    spec: P
    ndim: Optional[int] = None

    def matches(self, path: str, shape: Tuple[int, ...]) -> bool:
        if self.ndim is not None and len(shape) != self.ndim:
            return False
        return re.search(self.pattern, path) is not None


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """An ordered rule table with a name, the mesh axes it shards over,
    and (optionally) a separate table for optimizer moments.

    ``moment_rules`` exists for ZeRO-style plans where the *parameters*
    stay replicated but the optimizer state shards; every other plan
    leaves it ``None`` and moments resolve through ``rules`` (their
    paths embed the parameter path, so they land on their parameter's
    spec automatically)."""

    name: str
    rules: Tuple[PlanRule, ...]
    axes: Tuple[str, ...] = ()
    description: str = ""
    moment_rules: Optional[Tuple[PlanRule, ...]] = None

    # -- matching ------------------------------------------------------
    def match(self, path: str, shape: Tuple[int, ...],
              rules: Optional[Tuple[PlanRule, ...]] = None
              ) -> Optional[PlanRule]:
        """First rule matching ``(path, shape)``, or None.  Scalars are
        NOT special-cased here — resolvers auto-replicate them before
        consulting the table."""
        for rule in (self.rules if rules is None else rules):
            if rule.matches(path, shape):
                return rule
        return None

    def spec_for(self, path: str, shape: Tuple[int, ...],
                 rules: Optional[Tuple[PlanRule, ...]] = None) -> P:
        if len(shape) == 0:
            return P()
        rule = self.match(path, shape, rules)
        if rule is None:
            raise ValueError(
                f"sharding plan {self.name!r} has no rule matching leaf "
                f"'{path}' (shape {tuple(shape)}) — every non-scalar "
                "leaf must match a rule (add one, or a terminal "
                "catch-all like PlanRule('replicate', r'.*', P()))"
            )
        return rule.spec

    # -- resolution ----------------------------------------------------
    def resolve(self, tree):
        """PartitionSpec pytree for ``tree`` (params or grads — or any
        pytree whose paths the rules understand).  Scalar leaves resolve
        to ``P()`` without consulting the table; a non-scalar leaf no
        rule matches raises (coverage is the plan's contract — R006 and
        :func:`validate` report it without raising)."""
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec_for(
                tree_path_str(path), tuple(getattr(leaf, "shape", ()))
            ),
            tree,
        )

    def resolve_moments(self, opt_state):
        """PartitionSpec pytree for an optax state.  Moment leaves carry
        their parameter's path as a suffix, so the parameter rules match
        them directly; ``moment_rules`` (ZeRO plans) overrides the table
        used.  Scalar state (adam's ``count``) auto-replicates."""
        rules = self.moment_rules if self.moment_rules is not None \
            else self.rules
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec_for(
                tree_path_str(path), tuple(getattr(leaf, "shape", ())),
                rules,
            ),
            opt_state,
        )

    def shardings(self, mesh, tree):
        """``resolve`` lifted to :class:`NamedSharding`s over ``mesh`` —
        what ``jax.device_put`` / ``jit(in_shardings=...)`` consume."""
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            self.resolve(tree),
            is_leaf=lambda x: isinstance(x, P),
        )

    def explain(self, tree) -> List[Dict[str, Any]]:
        """Leaf-by-leaf resolution table (the ``tools.shardplan --show``
        payload): ``[{"path", "shape", "rule", "spec"}]`` in tree
        order.  Unmatched leaves get ``rule=None, spec=None`` instead of
        raising, so a broken plan can still be displayed."""
        rows = []
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            p = tree_path_str(path)
            shape = tuple(getattr(leaf, "shape", ()))
            if len(shape) == 0:
                rows.append({"path": p, "shape": shape,
                             "rule": "<scalar>", "spec": str(P())})
                continue
            rule = self.match(p, shape)
            rows.append({
                "path": p, "shape": shape,
                "rule": rule.name if rule else None,
                "spec": str(rule.spec) if rule else None,
            })
        return rows


@dataclasses.dataclass
class PlanValidation:
    """Structured :func:`validate` result.  ``unmatched`` and
    ``conflicts`` are the error classes (what lint rule R006 fires on);
    ``shadowed`` rules are advisory — a rule every one of whose
    candidate leaves was claimed by an earlier rule is dead weight, but
    the resolution is still well-defined."""

    plan: str
    unmatched: List[str] = dataclasses.field(default_factory=list)
    shadowed: List[str] = dataclasses.field(default_factory=list)
    #: ``[{"path", "rule", "reason"}]`` — a matched rule whose spec
    #: cannot legally apply to the leaf (rank overflow, a mesh axis used
    #: twice, an axis missing from the mesh, indivisible dims).
    conflicts: List[Dict[str, str]] = dataclasses.field(
        default_factory=list)
    n_leaves: int = 0
    n_sharded: int = 0

    @property
    def ok(self) -> bool:
        return not self.unmatched and not self.conflicts

    def summary(self) -> dict:
        return {
            "plan": self.plan,
            "ok": self.ok,
            "unmatched": list(self.unmatched),
            "shadowed": list(self.shadowed),
            "conflicts": [dict(c) for c in self.conflicts],
            "n_leaves": self.n_leaves,
            "n_sharded": self.n_sharded,
        }

    def render(self) -> str:
        lines = [f"plan {self.plan!r}: "
                 f"{'ok' if self.ok else 'FINDINGS'} "
                 f"({self.n_sharded}/{self.n_leaves} leaves sharded)"]
        for p in self.unmatched:
            lines.append(f"  unmatched leaf: {p}")
        for c in self.conflicts:
            lines.append(
                f"  conflict at {c['path']} (rule {c['rule']}): "
                f"{c['reason']}"
            )
        for r in self.shadowed:
            lines.append(f"  shadowed rule: {r}")
        return "\n".join(lines)


def validate(plan: ShardingPlan, params, mesh=None) -> PlanValidation:
    """Check ``plan`` against a parameter pytree (arrays OR
    ``ShapeDtypeStruct``s — only paths and shapes are read).

    Reported:

    * **unmatched** — non-scalar leaves no rule matches (resolution
      would raise);
    * **conflicts** — a matched spec that cannot apply: more entries
      than the leaf has dims, the same mesh axis in two entries, or —
      when ``mesh`` is given — an axis the mesh lacks / a sharded dim
      the axis size does not divide;
    * **shadowed** — rules whose every candidate leaf was claimed by an
      earlier rule (advisory: dead table rows, often a mis-ordered
      catch-all).
    """
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    out = PlanValidation(plan=plan.name)
    claimed: Dict[str, set] = {r.name: set() for r in plan.rules}
    candidates: Dict[str, set] = {r.name: set() for r in plan.rules}
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape)) \
        if mesh is not None else None

    for path, leaf in leaves:
        p = tree_path_str(path)
        shape = tuple(getattr(leaf, "shape", ()))
        out.n_leaves += 1
        if len(shape) == 0:
            continue
        hit = None
        for rule in plan.rules:
            if not rule.matches(p, shape):
                continue
            candidates[rule.name].add(p)
            if hit is None:
                hit = rule
                claimed[rule.name].add(p)
        if hit is None:
            out.unmatched.append(p)
            continue
        spec = hit.spec
        axes = _spec_axes(spec)
        if axes:
            out.n_sharded += 1
        if len(tuple(spec)) > len(shape):
            out.conflicts.append({
                "path": p, "rule": hit.name,
                "reason": f"spec {spec} has {len(tuple(spec))} entries "
                          f"for a rank-{len(shape)} leaf",
            })
            continue
        dupes = {a for a in axes if axes.count(a) > 1}
        if dupes:
            out.conflicts.append({
                "path": p, "rule": hit.name,
                "reason": f"mesh axis {sorted(dupes)} appears in more "
                          f"than one entry of spec {spec}",
            })
            continue
        if axis_sizes is not None:
            missing = [a for a in axes if a not in axis_sizes]
            if missing:
                out.conflicts.append({
                    "path": p, "rule": hit.name,
                    "reason": f"spec {spec} names axes {missing} absent "
                              f"from the mesh {tuple(axis_sizes)}",
                })
                continue
            for dim, entry in zip(shape, tuple(spec)):
                if entry is None:
                    continue
                names = entry if isinstance(entry, (tuple, list)) \
                    else (entry,)
                size = 1
                for a in names:
                    size *= axis_sizes[str(a)]
                if size and dim % size:
                    out.conflicts.append({
                        "path": p, "rule": hit.name,
                        "reason": f"dim {dim} not divisible by axis "
                                  f"size {size} ({'×'.join(map(str, names))})",
                    })
                    break

    for rule in plan.rules:
        if candidates[rule.name] and not claimed[rule.name]:
            out.shadowed.append(rule.name)
    return out
