"""Built-in sharding plans.

Every plan here ends in a terminal catch-all rule, so any model in
:mod:`chainermn_tpu.models` resolves with zero unmatched leaves (lint
rule R006 enforces exactly that).  The ``tp`` table is the declarative
rendering of the old hand-wired ``transformer_param_spec`` — same
specs, leaf for leaf — plus a KV-page rule so the SAME table drives the
tensor-parallel :class:`~chainermn_tpu.serving.engine.InferenceEngine`
cache.

Plans compose with the mesh at the call site: a plan only says *which
named axes* shard *which leaves*; ``plans_for_mesh`` filters the
registry down to plans whose axes the mesh actually has (the autotuner's
``layout`` search space).
"""

from __future__ import annotations

from typing import Dict, List

from jax.sharding import PartitionSpec as P

from chainermn_tpu.sharding.plan import PlanRule, ShardingPlan, validate

_REGISTRY: Dict[str, ShardingPlan] = {}


def register_plan(plan: ShardingPlan, *, overwrite: bool = False
                  ) -> ShardingPlan:
    """Add ``plan`` to the registry (used by the built-ins below and by
    user code defining project-local layouts)."""
    if plan.name in _REGISTRY and not overwrite:
        raise ValueError(
            f"sharding plan {plan.name!r} is already registered "
            "(pass overwrite=True to replace it)"
        )
    _REGISTRY[plan.name] = plan
    return plan


def get_plan(name: str) -> ShardingPlan:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown sharding plan {name!r}; registered plans: "
            f"{sorted(_REGISTRY)}"
        ) from None


def list_plans() -> List[ShardingPlan]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def plans_for_mesh(mesh, params=None) -> List[ShardingPlan]:
    """Registry plans whose every axis exists on ``mesh`` — and, when a
    parameter tree is given, that :func:`validate` clean against it
    (including mesh divisibility).  This is the autotune ``layout``
    candidate set."""
    out = []
    for plan in list_plans():
        if not set(plan.axes) <= set(mesh.axis_names):
            continue
        if params is not None and not validate(plan, params, mesh).ok:
            continue
        out.append(plan)
    return out


# ---------------------------------------------------------------------
# Rule blocks (shared between plans)
# ---------------------------------------------------------------------

_REPLICATE = PlanRule("replicate", r".*", P())

# The transformer TP block: identical specs to the retired hand-wired
# transformer_param_spec, rule for rule.  ndim gates stand in for its
# shape conditions (a query *bias* is 2-D and falls through to
# replication, exactly as before).
_TP_RULES = (
    # fused or per-head attention projections: (d_model, heads, d_head)
    PlanRule("attention_qkv", r"(query|key|value)",
             P(None, "model", None), ndim=3),
    # output projection: (heads, d_head, d_model)
    PlanRule("attention_out", r"(out/kernel$|/out/)",
             P("model", None, None), ndim=3),
    # FFN up/down projections (megatron column/row split)
    PlanRule("ffn_in", r"wi/kernel", P(None, "model")),
    PlanRule("ffn_out", r"wo/kernel", P("model", None)),
    # paged KV cache: (page_count, page_size, n_kv, d_head) — shard the
    # KV-head axis so TP decode keeps heads local (serving engine only;
    # params never match, these leaves are rank 4 and named *_pages)
    PlanRule("kv_pages", r"(k|v)_pages$",
             P(None, None, "model", None), ndim=4),
    _REPLICATE,
)

# FSDP block: shard the trailing (output-features) dim of every kernel
# over the data axis, and the vocab dim of embedding tables; everything
# else (biases, norm scales, BN stats) replicates.
_FSDP_RULES = (
    PlanRule("embedding", r"embedding$", P("data", None), ndim=2),
    PlanRule("kernel_2d", r"kernel$", P(None, "data"), ndim=2),
    PlanRule("kernel_3d", r"kernel$", P(None, None, "data"), ndim=3),
    PlanRule("kernel_4d", r"kernel$", P(None, None, None, "data"),
             ndim=4),
    _REPLICATE,
)


# ---------------------------------------------------------------------
# Built-in plans
# ---------------------------------------------------------------------

register_plan(ShardingPlan(
    name="dp",
    rules=(_REPLICATE,),
    axes=("data",),
    description="Pure data parallelism: params, moments, and cache "
                "replicated; only the batch shards.",
))

register_plan(ShardingPlan(
    name="tp",
    rules=_TP_RULES,
    axes=("model",),
    description="Megatron tensor parallelism for attention/FFN "
                "families (transformer, ViT): heads and FFN hidden "
                "shard over 'model'; KV pages shard for TP decode.",
))

register_plan(ShardingPlan(
    name="dp_tp",
    rules=_TP_RULES,
    axes=("data", "model"),
    description="Composed DP×TP on a 2-D ('data', 'model') mesh: the "
                "tp rule table for params/moments, batch over 'data'.",
))

register_plan(ShardingPlan(
    name="sp",
    rules=(_REPLICATE,),
    axes=("sp",),
    description="Sequence-parallel prefill (serving engine): params and "
                "KV pages replicated over 'sp'; only the chunk "
                "program's token axis shards (shard_map inside the "
                "engine's sp prefill step), so one slice's activations "
                "split across devices while decode stays single-chip.",
))

register_plan(ShardingPlan(
    name="fsdp",
    rules=_FSDP_RULES,
    axes=("data",),
    description="Fully-sharded data parallelism: every kernel and "
                "embedding shards one dim over 'data'; GSPMD "
                "gathers/scatters around use.",
))

register_plan(ShardingPlan(
    name="zero",
    rules=(_REPLICATE,),
    moment_rules=_FSDP_RULES,
    axes=("data",),
    description="ZeRO-1 in GSPMD form: params replicated, optimizer "
                "moments sharded over 'data' via the FSDP rule block.",
))
