"""Declarative sharding-plan subsystem.

One ordered ``(name, path-regex, PartitionSpec)`` rule table — a
:class:`ShardingPlan` — resolves params, grads, and optimizer moments
in one pass, and the same table drives the tensor-parallel serving
engine.  See :mod:`chainermn_tpu.sharding.plan` for the resolution
contract, :mod:`chainermn_tpu.sharding.registry` for the built-in
``dp`` / ``tp`` / ``dp_tp`` / ``fsdp`` / ``zero`` plans, lint rule R006
for coverage enforcement, and ``python -m chainermn_tpu.tools.shardplan``
for the browser CLI.
"""

from chainermn_tpu.sharding.plan import (  # noqa: F401
    PlanRule,
    PlanValidation,
    ShardingPlan,
    tree_path_str,
    validate,
)
from chainermn_tpu.sharding.registry import (  # noqa: F401
    get_plan,
    list_plans,
    plans_for_mesh,
    register_plan,
)

__all__ = [
    "PlanRule",
    "PlanValidation",
    "ShardingPlan",
    "tree_path_str",
    "validate",
    "get_plan",
    "list_plans",
    "plans_for_mesh",
    "register_plan",
]
