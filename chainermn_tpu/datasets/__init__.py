from chainermn_tpu.datasets.scatter_dataset import (  # noqa: F401
    scatter_dataset,
    scatter_index,
    create_empty_dataset,
    SubDataset,
    get_n_iterations_for_one_epoch,
)
from chainermn_tpu.datasets.multiprocess_iterator import (  # noqa: F401
    MultiprocessBatchLoader,
)
