"""Self-contained datasets for the examples and smoke tests.

The reference's examples pull MNIST/ImageNet via Chainer's downloaders;
this environment is zero-egress, so the examples default to deterministic
synthetic datasets with the same shapes/cardinalities (real data can be
pointed to with ``--data-dir`` where the loaders accept npz/folder input).
"""

from __future__ import annotations

import numpy as np


class SyntheticImageDataset:
    """Deterministic labeled images: class-dependent means + noise, so a
    model can actually fit them (loss decreases, accuracy climbs) — making
    the examples honest end-to-end smoke tests, not shape checks."""

    def __init__(
        self,
        n: int = 2048,
        shape=(28, 28),
        n_classes: int = 10,
        seed: int = 0,
        flat: bool = False,
    ):
        rng = np.random.RandomState(seed)
        self.n_classes = n_classes
        self.labels = rng.randint(0, n_classes, size=n).astype(np.int32)
        # Class prototypes come from a FIXED seed so train/val splits (built
        # with different `seed`s) share the same underlying classes.
        base = np.random.RandomState(1234).randn(n_classes, *shape).astype(np.float32)
        noise = rng.randn(n, *shape).astype(np.float32) * 0.5
        self.images = base[self.labels] + noise
        if flat:
            self.images = self.images.reshape(n, -1)

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i):
        return self.images[i], self.labels[i]


class SyntheticSeqDataset:
    """Synthetic 'translation' pairs: target = reversed source with a vocab
    offset — learnable by a seq2seq model, mirroring the reference's
    seq2seq example's role as an acceptance test."""

    def __init__(self, n=1024, src_len=12, tgt_len=12, vocab=64, seed=0):
        rng = np.random.RandomState(seed)
        self.vocab = vocab
        # Reserve 0=pad, 1=bos, 2=eos.
        self.src = rng.randint(3, vocab, size=(n, src_len)).astype(np.int32)
        self.tgt = np.flip(self.src, axis=1).copy()

    def __len__(self):
        return len(self.src)

    def __getitem__(self, i):
        return self.src[i], self.tgt[i]


class ExplodingDataset:
    """Raises at one index — lets tests assert that loader worker failures
    propagate to the training loop instead of hanging it.  Module-level so
    spawn-based loader workers can unpickle it."""

    def __init__(self, inner, explode_at: int):
        self.inner = inner
        self.explode_at = explode_at

    def __len__(self):
        return len(self.inner)

    def __getitem__(self, i):
        if i == self.explode_at:
            raise ValueError(f"synthetic item failure at {i}")
        return self.inner[i]


def batch_iterator(dataset, batch_size, *, shuffle=True, seed=0, drop_last=True):
    """Minimal epoch iterator over an indexable dataset, yielding stacked
    numpy batches — the examples' stand-in for Chainer's iterators.

    Batch assembly goes through the native ``parallel_gather`` (csrc/
    hostbuf.cpp): a multithreaded memcpy into the contiguous batch buffer,
    the ``pack_params`` idea of
    REF:chainermn/communicators/_memory_utility.py applied to the one
    host-side copy that sits on the input-pipeline critical path."""
    from chainermn_tpu.utils import native

    n = len(dataset)
    order = np.random.RandomState(seed).permutation(n) if shuffle else np.arange(n)
    stop = n - (n % batch_size) if drop_last else n
    for start in range(0, stop, batch_size):
        idx = order[start : start + batch_size]
        items = [dataset[int(i)] for i in idx]
        yield tuple(
            native.parallel_gather([np.asarray(it[j]) for it in items])
            for j in range(len(items[0]))
        )
