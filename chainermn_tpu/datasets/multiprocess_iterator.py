"""Multi-process batch loader — the ``MultiprocessIterator`` role.

The reference's ImageNet example fed each rank through Chainer's
``MultiprocessIterator`` (REF:chainermn examples/imagenet/train_imagenet.py;
the iterator itself lives in Chainer): background *worker processes* fetch
and decode dataset items so the training loop never blocks on item
assembly.  This is that component, shaped for a TPU host:

* Workers are **separate processes** (``spawn`` start method — forking a
  process that has initialized XLA/PJRT is unsafe), so item fetch/decode
  escapes the GIL entirely, unlike the single prefetch *thread* of
  :func:`chainermn_tpu.iterators.create_prefetch_iterator` (which remains
  the host→device staging stage downstream of this loader).
* Batch rows are written by workers **directly into shared-memory slots**
  (``multiprocessing.shared_memory``) — the batch never crosses the
  process boundary through a pickle pipe.  This is the pinned-staging idea
  of REF:chainermn/communicators/_memory_utility.py applied to the input
  pipeline: one buffer, many writers, zero re-copies.
* The parent hands out numpy views of the slot (``copy=False``) or fresh
  arrays (``copy=True``), reordering worker completions so iteration order
  is deterministic and identical to ``datasets.toy.batch_iterator`` with
  the same (shuffle, seed, drop_last).

Workers import only numpy + the pickled dataset — never jax — so spawn
start-up stays cheap and no worker ever touches the TPU runtime.
"""

from __future__ import annotations

import collections
import itertools
import multiprocessing as mp
import os
import queue as _queue
import traceback
from multiprocessing import shared_memory

import numpy as np

_SENTINEL = None


def _probe(dataset):
    """Per-component (shape, dtype) of one item; items must be fixed-shape."""
    item = dataset[0]
    if not isinstance(item, (tuple, list)):
        item = (item,)
    return [(np.asarray(c).shape, np.asarray(c).dtype) for c in item]


def _worker_main(dataset, shm_names, batch_size, specs, task_q, done_q):
    """Worker loop: fetch items, write rows straight into the shared slot.

    Runs in a spawned process; must not import jax (and does not — only
    numpy and the user's dataset code run here).
    """
    try:
        shms = [
            [shared_memory.SharedMemory(name=nm) for nm in slot_names]
            for slot_names in shm_names
        ]
        views = [
            [
                np.ndarray((batch_size, *shape), dtype, buffer=shm.buf)
                for shm, (shape, dtype) in zip(slot, specs)
            ]
            for slot in shms
        ]
        while True:
            task = task_q.get()
            if task is _SENTINEL:
                return
            gen, seq, slot, indices = task
            try:
                dst = views[slot]
                for row, idx in enumerate(indices):
                    item = dataset[int(idx)]
                    if not isinstance(item, (tuple, list)):
                        item = (item,)
                    for c, comp in enumerate(item):
                        dst[c][row] = comp
                done_q.put((gen, seq, slot, len(indices), None))
            except BaseException:  # noqa: BLE001 — relayed to parent
                done_q.put((gen, seq, slot, 0, traceback.format_exc()))
    except BaseException:  # noqa: BLE001 — setup failure: poison the parent
        try:
            done_q.put((-1, -1, -1, 0, traceback.format_exc()))
        except Exception:
            pass
    finally:
        try:
            for slot in shms:
                for shm in slot:
                    shm.close()
        except Exception:
            pass


class MultiprocessBatchLoader:
    """Iterable of stacked-numpy batches assembled by worker processes.

    Parameters mirror :func:`chainermn_tpu.datasets.toy.batch_iterator`
    (same order semantics for the same ``shuffle``/``seed``/``drop_last``),
    plus:

    ``n_workers``
        Worker process count (default: ``min(2, cpu_count)``).
    ``n_slots``
        Shared-memory batch slots in flight (default ``2 * n_workers``);
        bounds both parallelism and host memory
        (``n_slots × batch_nbytes``).
    ``repeat``
        ``True`` → iterate epochs forever, reshuffling each epoch with
        ``seed + epoch`` (the resident-loop shape ``bench.py --pipeline``
        and real training use).
    ``copy``
        ``True`` (default) → yield fresh arrays, valid forever.
        ``False`` → yield zero-copy views of the shared slot; a yielded
        batch stays valid until ``n_slots - n_workers - 1`` further batches
        have been drawn (slots are recycled oldest-first).  The consumer
        must FINISH reading (or explicitly copy) the batch within that
        window: handing the view to an asynchronous consumer is unsound —
        ``jax.device_put`` dispatches async on accelerators and on the CPU
        backend zero-copy *aliases* the slot buffer permanently, so a
        recycled slot would corrupt the staged array.  When feeding a
        device, use ``copy=True``.

    Use as a context manager or call :meth:`close`; abandoning a running
    loader mid-epoch also shuts down cleanly via the iterator's ``finally``.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        *,
        n_workers: int = 0,
        n_slots: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        repeat: bool = False,
        copy: bool = True,
    ):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self._dataset = dataset
        self._n = len(dataset)
        if self._n == 0:
            raise ValueError("dataset is empty")
        if self._n < batch_size and drop_last:
            raise ValueError(
                f"dataset ({self._n}) smaller than one batch ({batch_size})"
            )
        self._batch_size = batch_size
        self._shuffle = shuffle
        self._seed = seed
        self._drop_last = drop_last
        self._repeat = repeat
        self._copy = copy
        self._specs = _probe(dataset)
        self._n_workers = n_workers if n_workers > 0 else min(
            2, os.cpu_count() or 1
        )
        self._n_slots = n_slots if n_slots > 0 else 2 * self._n_workers
        # copy=False hands out live slot views: with fewer than workers+2
        # slots there is no slot that is neither in-flight nor still-valid.
        if not copy:
            self._n_slots = max(self._n_slots, self._n_workers + 2)
        self._ctx = mp.get_context("spawn")
        self._task_q = self._ctx.Queue()
        self._done_q = self._ctx.Queue()
        self._shms: list[list[shared_memory.SharedMemory]] = []
        self._views: list[list[np.ndarray]] = []
        for _ in range(self._n_slots):
            slot_shms, slot_views = [], []
            for shape, dtype in self._specs:
                nbytes = int(np.prod((batch_size, *shape), dtype=np.int64)) * (
                    np.dtype(dtype).itemsize
                )
                shm = shared_memory.SharedMemory(create=True, size=max(nbytes, 1))
                slot_shms.append(shm)
                slot_views.append(
                    np.ndarray((batch_size, *shape), dtype, buffer=shm.buf)
                )
            self._shms.append(slot_shms)
            self._views.append(slot_views)
        shm_names = [[s.name for s in slot] for slot in self._shms]
        self._procs = [
            self._ctx.Process(
                target=_worker_main,
                args=(
                    dataset, shm_names, batch_size, self._specs,
                    self._task_q, self._done_q,
                ),
                daemon=True,
            )
            for _ in range(self._n_workers)
        ]
        for p in self._procs:
            p.start()
        self._closed = False
        # Tasks issued but not yet completed (across generations) and the
        # current iteration generation: an abandoned pass leaves in-flight
        # tasks whose completions must be consumed — and whose slots must
        # not be reassigned — before a new pass starts.
        self._outstanding = 0
        self._generation = 0

    # -- epoch index plan -------------------------------------------------
    def _epoch_batches(self, epoch: int):
        order = (
            np.random.RandomState(self._seed + epoch).permutation(self._n)
            if self._shuffle
            else np.arange(self._n)
        )
        stop = (
            self._n - (self._n % self._batch_size)
            if self._drop_last
            else self._n
        )
        for start in range(0, stop, self._batch_size):
            yield order[start : start + self._batch_size]

    def _all_batches(self):
        epochs = itertools.count() if self._repeat else range(1)
        for e in epochs:
            yield from self._epoch_batches(e)

    def __bool__(self):
        # Without this, bool(loader) falls back to __len__, which raises
        # for repeat=True — truthiness must stay cheap and total.
        return True

    def __len__(self):
        if self._repeat:
            raise TypeError(
                "MultiprocessBatchLoader with repeat=True is an infinite "
                "iterator and has no length; use len(loader) only with "
                "repeat=False (per-epoch batch count)"
            )
        per = (
            self._n // self._batch_size
            if self._drop_last
            else -(-self._n // self._batch_size)
        )
        return per

    # -- iteration --------------------------------------------------------
    def _settle(self):
        """Block until every issued task has completed, consuming (and
        discarding) their completions — called before a new pass so stale
        writes cannot race new slot assignments."""
        while self._outstanding:
            try:
                _gen, _seq, _slot, _count, err = self._done_q.get(timeout=60.0)
            except _queue.Empty:
                raise RuntimeError(
                    "MultiprocessBatchLoader: in-flight tasks never "
                    "completed (worker process died?)"
                ) from None
            self._outstanding -= 1
            if err is not None and _gen == -1:
                raise RuntimeError(
                    f"MultiprocessBatchLoader worker died:\n{err}"
                )

    def __iter__(self):
        # Eager checks (this wrapper is not a generator, so they fire at
        # iter() time, not first-next time), then the lazy batch generator.
        if self._closed:
            raise RuntimeError("loader is closed")
        self._settle()
        return self._iterate()

    def _iterate(self):
        self._generation += 1
        gen = self._generation
        tasks = self._all_batches()
        free = list(range(self._n_slots))
        # copy=False: keep recently-yielded slots out of the free pool so
        # the consumer's views stay valid for a documented window.
        keep = 0 if self._copy else max(1, self._n_slots - self._n_workers - 1)
        held: collections.deque = collections.deque()
        pending: dict = {}
        next_task = 0
        next_yield = 0

        def schedule():
            nonlocal next_task
            while free:
                idx = next(tasks, None)
                if idx is None:
                    return
                self._task_q.put((gen, next_task, free.pop(), idx))
                next_task += 1
                self._outstanding += 1

        try:
            schedule()
            while next_yield < next_task:
                while next_yield not in pending:
                    try:
                        g, seq, slot, count, err = self._done_q.get(
                            timeout=10.0
                        )
                    except _queue.Empty:
                        # ANY dead worker is fatal: its in-flight task (and
                        # completion) may be lost forever, so waiting on
                        # the survivors would hang the training loop.
                        dead = [
                            p for p in self._procs if not p.is_alive()
                        ]
                        if dead:
                            raise RuntimeError(
                                "MultiprocessBatchLoader: "
                                f"{len(dead)}/{len(self._procs)} worker "
                                "process(es) died (exitcodes "
                                f"{[p.exitcode for p in dead]}; killed by "
                                "the OOM killer? spawn requires an "
                                "importable __main__ module and a "
                                "picklable dataset)"
                            ) from None
                        continue
                    self._outstanding -= 1
                    if err is not None:
                        raise RuntimeError(
                            f"MultiprocessBatchLoader worker failed:\n{err}"
                        )
                    if g != gen:
                        continue  # stale completion from an abandoned pass
                    pending[seq] = (slot, count)
                slot, count = pending.pop(next_yield)
                next_yield += 1
                if self._copy:
                    batch = tuple(v[:count].copy() for v in self._views[slot])
                    free.append(slot)
                else:
                    batch = tuple(v[:count] for v in self._views[slot])
                    held.append(slot)
                    while len(held) > keep:
                        free.append(held.popleft())
                schedule()
                yield batch
        finally:
            pass  # in-flight tasks are settled by the next pass or close()

    # -- shutdown ---------------------------------------------------------
    def close(self):
        if self._closed:
            return
        self._closed = True
        for _ in self._procs:
            try:
                self._task_q.put(_SENTINEL)
            except Exception:
                pass
        for p in self._procs:
            p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()
                p.join(timeout=1.0)
        for q in (self._task_q, self._done_q):
            try:
                q.close()
                q.join_thread()
            except Exception:
                pass
        for slot in self._shms:
            for shm in slot:
                try:
                    shm.close()
                    shm.unlink()
                except Exception:
                    pass
        self._shms, self._views = [], []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
