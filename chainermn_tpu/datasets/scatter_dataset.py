"""Dataset scattering across hosts.

Reference: REF:chainermn/datasets/scatter_dataset.py —
``scatter_dataset(dataset, comm, root=0, shuffle=False, seed=None)``: the
root rank permutes indices (seeded), slices them into ``comm.size``
near-equal contiguous chunks, MPI-scatters the chunks (pickled), and each
rank wraps its slice in a Chainer ``SubDataset``.  Equal-ish per-rank epoch
lengths keep collectives in lockstep (SURVEY §3.4).

TPU-native translation: the unit of data loading under JAX is the *host*
(each process feeds its local chips, and per-device sharding happens when
the global batch array is formed), so the scatter is over
``comm.size = process_count`` host shards.  Because every process can
compute the same seeded permutation, no object transport is needed in the
common case — the "scatter" is a deterministic index computation, with the
root's permutation broadcast over the object plane only when an explicit
``indices``/unseeded shuffle makes ranks diverge.  Semantics preserved from
the reference: seeded global permutation, contiguous ±1-equal chunks,
``len(shard)`` differing by at most one across ranks.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase


class SubDataset:
    """A view of ``dataset`` at ``indices`` — the Chainer ``SubDataset``
    analogue, duck-typed to anything with ``__getitem__``/``__len__``."""

    def __init__(self, dataset, indices: np.ndarray):
        self._dataset = dataset
        self._indices = np.asarray(indices)

    def __len__(self):
        return len(self._indices)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._dataset[int(j)] for j in self._indices[i]]
        return self._dataset[int(self._indices[i])]

    @property
    def indices(self) -> np.ndarray:
        return self._indices


def scatter_index(
    n_total: int, comm: CommunicatorBase, root: int = 0,
    shuffle: bool = False, seed: Optional[int] = None,
) -> np.ndarray:
    """Compute this process's index shard of ``range(n_total)``.

    The chunking arithmetic mirrors the reference exactly: chunks are
    contiguous runs of the (permuted) index list, sizes differ by at most
    one, earlier ranks get the longer chunks.
    """
    if shuffle:
        if seed is None:
            # Ranks must agree on the permutation; without a seed the root
            # draws it and broadcasts (the reference's pickled scatter path).
            order = None
            if comm.rank == root:
                order = np.random.permutation(n_total)
            order = comm.bcast_obj(order, root=root)
        else:
            order = np.random.RandomState(seed).permutation(n_total)
    else:
        order = np.arange(n_total)

    size = comm.size
    base, rem = divmod(n_total, size)
    sizes = [base + (1 if r < rem else 0) for r in range(size)]
    offsets = np.cumsum([0] + sizes)
    r = comm.rank
    return order[offsets[r] : offsets[r + 1]]


def scatter_dataset(
    dataset,
    comm: CommunicatorBase,
    root: int = 0,
    shuffle: bool = False,
    seed: Optional[int] = None,
    force_equal_length: bool = True,
) -> SubDataset:
    """Shard ``dataset`` across processes (reference signature preserved).

    ``force_equal_length`` pads shorter shards by wrapping around their own
    indices so every rank sees the same epoch length — the lockstep
    guarantee the reference achieves with ±1 chunks; exact equality is the
    stricter contract a collective-per-step TPU loop wants.
    """
    idx = scatter_index(len(dataset), comm, root=root, shuffle=shuffle, seed=seed)
    if force_equal_length and comm.size > 1:
        max_len = -(-len(dataset) // comm.size)
        if len(idx) < max_len and len(idx) > 0:
            pad = idx[: max_len - len(idx)]
            idx = np.concatenate([idx, pad])
    return SubDataset(dataset, idx)


def create_empty_dataset(dataset):
    """Reference parity (REF:chainermn/datasets/empty_dataset.py): strip a
    dataset to its length only — used on non-root ranks that must agree on
    epoch structure without holding data."""
    return SubDataset(_Empty(len(dataset)), np.arange(len(dataset)))


class _Empty:
    def __init__(self, n):
        self._n = n

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        return ()


def get_n_iterations_for_one_epoch(dataset, local_batch_size: int) -> int:
    """Iterations per epoch given a per-host batch size (helper the
    reference keeps in its examples)."""
    return -(-len(dataset) // local_batch_size)
