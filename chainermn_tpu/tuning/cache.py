"""Persistent tune cache — measured-best kernel configs, remembered.

One JSON file maps cache keys (``kernel|device_kind|dtype|shape
bucket|flags``) to the winning config plus its measured time and enough
provenance to audit a pick later.  The file lives OUTSIDE the repo
(default ``/tmp/chainermn_tpu/tune_cache.json``; override with
``CHAINERMN_TPU_TUNE_CACHE``) so no test or bench run can dirty the
working tree, and writes are atomic (tempfile + ``os.replace``) so a
crashed tuner never leaves a torn file.  A corrupt or unreadable file
degrades to an empty cache — the ops then use their static defaults, the
same behavior as a miss.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Any, Dict, Optional

ENV_CACHE_PATH = "CHAINERMN_TPU_TUNE_CACHE"
ENV_AUTOTUNE = "CHAINERMN_TPU_AUTOTUNE"
DEFAULT_CACHE_PATH = "/tmp/chainermn_tpu/tune_cache.json"
CACHE_VERSION = 1

_TPU_BACKENDS = ("tpu", "axon")


def cache_path() -> str:
    """Cache file path: ``$CHAINERMN_TPU_TUNE_CACHE`` or the /tmp default
    — never a path inside the repository."""
    return os.environ.get(ENV_CACHE_PATH) or DEFAULT_CACHE_PATH


def autotune_enabled() -> bool:
    """May the measurement harness run at all?

    False under pytest (``PYTEST_CURRENT_TEST`` — the tier-1 determinism
    guard: a test run must never time kernels or write cache files) and
    when ``CHAINERMN_TPU_AUTOTUNE`` is ``0``/``off``/``false``.
    """
    if os.environ.get(ENV_AUTOTUNE, "").lower() in ("0", "off", "false"):
        return False
    if "PYTEST_CURRENT_TEST" in os.environ:
        return False
    return True


def runtime_lookup_enabled() -> bool:
    """May the ops consult the cache at trace time?

    Everything :func:`autotune_enabled` requires, plus a real TPU
    backend: off-TPU (CPU interpret mode, tests) the ops must be
    bit-identical to the static-default behavior, so the cache is never
    even read there.
    """
    if not autotune_enabled():
        return False
    try:
        import jax

        return jax.default_backend() in _TPU_BACKENDS
    except Exception:  # pragma: no cover - backend init failure
        return False


def device_kind() -> str:
    """First device's kind string (e.g. ``TPU v5e``) — part of every
    cache key, so configs tuned on one chip generation never leak onto
    another."""
    try:
        import jax

        return str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


def dtype_name(dtype) -> str:
    """Canonical dtype string for cache keys (``bfloat16``, ``float32``)."""
    import numpy as np

    try:
        return np.dtype(dtype).name
    except TypeError:
        return str(getattr(dtype, "name", dtype))


def bucket_pow2(n: int) -> int:
    """Shape bucket: the next power of two >= ``n``.  Kernel timing is
    insensitive within a ~2x size band, and bucketing keeps one tuned
    entry serving the whole band instead of fragmenting the cache per
    exact shape."""
    n = int(n)
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def make_key(kernel: str, dev_kind: str, dtype, shape_bucket, flags) -> str:
    """Canonical cache key.  ``shape_bucket``: sequence of (name, int)
    pairs, already bucketed by the caller; ``flags``: dict of static
    kernel options (causal/window/...).  Deterministic: flags are sorted,
    bools rendered as 0/1."""
    shape_s = "x".join(f"{k}{int(v)}" for k, v in shape_bucket)
    flag_s = ",".join(
        f"{k}={int(v) if isinstance(v, bool) else v}"
        for k, v in sorted(dict(flags).items())
    )
    return "|".join([kernel, dev_kind, dtype_name(dtype), shape_s, flag_s])


class TuneCache:
    """The persistent JSON cache.  Thread-safe; loads lazily; all write
    paths are atomic.  ``get``/``put`` speak plain config dicts."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or cache_path()
        self._entries: Dict[str, Dict[str, Any]] = {}
        self._loaded = False
        self._lock = threading.Lock()

    def load(self) -> "TuneCache":
        """Read the file; missing/corrupt/wrong-version degrades to an
        empty cache (a miss everywhere — static defaults apply)."""
        with self._lock:
            self._entries = {}
            self._loaded = True
            try:
                with open(self.path) as f:
                    data = json.load(f)
                if (
                    isinstance(data, dict)
                    and data.get("version") == CACHE_VERSION
                    and isinstance(data.get("entries"), dict)
                ):
                    self._entries = {
                        str(k): dict(v)
                        for k, v in data["entries"].items()
                        if isinstance(v, dict)
                    }
            except (OSError, ValueError):
                pass
        return self

    def _ensure_loaded(self):
        if not self._loaded:
            self.load()

    def get(self, key: str) -> Optional[Dict[str, Any]]:
        self._ensure_loaded()
        with self._lock:
            e = self._entries.get(key)
            return dict(e) if e is not None else None

    def put(self, key: str, config: Dict[str, Any]) -> None:
        self._ensure_loaded()
        with self._lock:
            self._entries[str(key)] = dict(config)

    def __len__(self) -> int:
        self._ensure_loaded()
        return len(self._entries)

    def keys(self):
        self._ensure_loaded()
        with self._lock:
            return sorted(self._entries)

    def save(self) -> str:
        """Atomic write (tempfile in the destination dir + ``os.replace``)
        so concurrent readers never observe a torn file."""
        self._ensure_loaded()
        with self._lock:
            payload = {"version": CACHE_VERSION, "entries": self._entries}
            d = os.path.dirname(os.path.abspath(self.path)) or "."
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(prefix=".tune_cache.", dir=d)
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(payload, f, indent=1, sort_keys=True)
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        return self.path


_shared: Optional[TuneCache] = None
_shared_lock = threading.Lock()


def shared_cache() -> TuneCache:
    """Process-wide cache singleton, re-resolved if the env-var path
    changes (tests point it at tmp dirs)."""
    global _shared
    with _shared_lock:
        if _shared is None or _shared.path != cache_path():
            _shared = TuneCache().load()
        return _shared
