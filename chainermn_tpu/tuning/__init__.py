"""Kernel autotuning — searched block configs for the Pallas hot paths.

The hot kernels (``ops.flash_attention``, ``ops.fused_ce``) shipped with
one magic geometry each: ``block_q``/``block_k`` ~ S/16 clamped to
[128, 512] and ``chunk = 512``.  Blockwise TPU kernels are highly
sensitive to tile shape, and the best choice shifts with sequence
length, head dim, dtype and the causal/window band — so, following the
reference framework's own design principle (expose the knob, but pick a
fast default FOR the user: ``allreduce_grad_dtype``,
``double_buffering``), this package measures the best config per shape
once and remembers it:

* :mod:`~chainermn_tpu.tuning.search_space` — per-kernel candidate
  declarations (flash fwd/bwd ``block_q``×``block_k`` within VMEM
  limits, fused-CE ``chunk``), each with the static default included so
  a tuned pick can never lose to it;
* :mod:`~chainermn_tpu.tuning.measure` — compile-and-time harness
  (median-of-k slope timing via ``utils.profiling``; candidates that
  fail to compile or OOM are skipped, not fatal);
* :mod:`~chainermn_tpu.tuning.cache` — persistent JSON cache keyed by
  ``(kernel, device_kind, dtype, shape bucket, causal/window flags)``,
  path overridable via ``CHAINERMN_TPU_TUNE_CACHE`` (default under
  ``/tmp``, never inside the repo);
* :mod:`~chainermn_tpu.tuning.autotune` — the tuners and the runtime
  lookups the ops consult when the caller does not pin blocks.

Determinism guard: lookups and tuning are inert under pytest and on
non-TPU backends — there the ops use their static defaults, bit-identical
to the pre-tuning behavior.  Tuning itself only ever runs explicitly:
``python -m chainermn_tpu.tools.autotune`` or ``bench.py --autotune``.
"""

from chainermn_tpu.tuning.cache import (  # noqa: F401
    DEFAULT_CACHE_PATH,
    ENV_AUTOTUNE,
    ENV_CACHE_PATH,
    TuneCache,
    autotune_enabled,
    bucket_pow2,
    cache_path,
    device_kind,
    runtime_lookup_enabled,
    shared_cache,
)
from chainermn_tpu.tuning.search_space import (  # noqa: F401
    bucket_cache_key,
    bucket_search_space,
    ce_cache_key,
    ce_search_space,
    comm_dtype_cache_key,
    comm_dtype_search_space,
    decode_cache_key,
    decode_search_space,
    draft_cache_key,
    draft_search_space,
    flash_cache_key,
    flash_search_space,
    kv_dtype_cache_key,
    kv_dtype_search_space,
    layout_cache_key,
    layout_search_space,
    overlap_cache_key,
    overlap_schedule_search_space,
    prefill_chunk_cache_key,
    prefill_chunk_search_space,
    serve_group_cache_key,
    serve_group_search_space,
)
from chainermn_tpu.tuning.autotune import (  # noqa: F401
    lookup_bucket_bytes,
    lookup_ce_chunk,
    lookup_comm_dtype,
    lookup_decode_block_ctx,
    lookup_draft,
    lookup_draft_layers,
    lookup_flash_blocks,
    lookup_kv_dtype,
    lookup_layout,
    lookup_overlap_schedule,
    lookup_prefill_chunk,
    tune_allreduce_bucket,
    tune_comm_dtype,
    tune_decode_attention,
    tune_draft,
    tune_flash,
    tune_fused_ce,
    tune_kv_dtype,
    tune_layout,
    tune_lm_shapes,
    tune_overlap_schedule,
    tune_prefill_chunk,
    tune_serve_group,
)
