"""Compile-and-time harness for candidate kernel configs.

One contract: the caller supplies ``build_run(config) -> run`` where
``run(n)`` executes ``n`` chained iterations ending in one hard
:func:`~chainermn_tpu.utils.profiling.sync`, and this module times every
candidate with the same median-of-k slope method ``bench.py`` uses (the
slope between two run lengths cancels the ~100 ms tunneled-readback
constant; the median absorbs run-to-run tunnel noise).

A candidate that fails anywhere — Mosaic compile error, VMEM OOM, a
shape the estimate misjudged — is recorded with its error and skipped,
never fatal: an autotune sweep must survive the edges of its own search
space.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional

from chainermn_tpu.utils.profiling import median_slope


def measure_candidates(
    build_run: Callable[[dict], Callable[[int], float]],
    candidates: Iterable[dict],
    n1: int = 3,
    repeats: int = 3,
    log: Optional[Callable[[str], None]] = None,
) -> List[dict]:
    """Time every candidate; returns one record per candidate:
    ``{"config", "seconds", "error"}`` with ``seconds`` None for skipped
    (failed) configs.  ``run(1)`` is called once first so compile time
    never leaks into the slope samples and compile failures are caught
    per-candidate."""
    results = []
    for cfg in candidates:
        rec = {"config": dict(cfg), "seconds": None, "error": None}
        try:
            run = build_run(dict(cfg))
            run(1)  # compile + warm; candidate-killing errors land here
            t, samples = median_slope(run, n1, repeats=repeats)
            rec["seconds"] = float(t)
            rec["samples"] = [float(s) for s in samples]
        except Exception as e:  # invalid config: skip, keep sweeping
            rec["error"] = f"{type(e).__name__}: {e}"[:300]
        if log is not None:
            log(
                f"  {rec['config']}: "
                + (f"{rec['seconds'] * 1e6:.1f} us/iter"
                   if rec["seconds"] is not None
                   else f"skipped ({rec['error']})")
            )
        results.append(rec)
    return results


def best_config(results: List[dict]) -> Optional[dict]:
    """The measured argmin record, or None when every candidate failed."""
    timed = [r for r in results if r["seconds"] is not None]
    if not timed:
        return None
    return min(timed, key=lambda r: r["seconds"])
