"""Per-kernel search-space declarations.

Each kernel declares (a) its candidate configs, filtered to what can
actually compile — divisibility of the sequence/row count, sublane
alignment, and a VMEM budget per grid program — and (b) its cache-key
schema.  The kernel's own static default is ALWAYS a member of the
space, so the measured argmin can never be slower than shipping the
magic number (the tuner picks the default when nothing beats it).

VMEM model (v4/v5 class chips have ~16 MiB/core): a Pallas grid program
holds its input blocks double-buffered (the pipeline prefetches tile
``i+1`` while computing ``i``), its output blocks double-buffered, and
its scratch once.  The estimate errs conservative — Mosaic pads the lane
(last) dim to a multiple of 128 — and candidates over budget are pruned
before compilation rather than left to die as OOM (they are *also*
skipped-on-error in the measure harness, for the shapes the model
misjudges).
"""

from __future__ import annotations

from typing import List, Optional

from chainermn_tpu.tuning.cache import bucket_pow2, make_key

VMEM_BYTES = 16 * 1024 * 1024
#: fraction of VMEM the estimate may claim — headroom for Mosaic's own
#: temporaries and the iota/mask intermediates inside the kernel body.
VMEM_BUDGET_FRACTION = 0.75

#: candidate tile edges: every multiple-of-sublane power of two between
#: the smallest tile worth scheduling and the largest that a 16 MiB VMEM
#: can double-buffer at common head dims.
BLOCK_CANDIDATES = (64, 128, 256, 512, 1024)

#: fused-CE row-chunk candidates; the static default 512 sits mid-range.
CE_CHUNK_CANDIDATES = (128, 256, 512, 1024, 2048, 4096)

#: cap on the transient (chunk, V) fp32 logit tile the CE scan holds.
CE_TILE_BYTES_MAX = 512 * 1024 * 1024


def _pad_lane(d: int) -> int:
    """Mosaic pads the lane (last) dim to a multiple of 128."""
    return max(128, ((int(d) + 127) // 128) * 128)


def _sublane(dtype) -> int:
    from chainermn_tpu.tuning.cache import dtype_name

    return 16 if dtype_name(dtype) == "bfloat16" else 8


def flash_vmem_bytes(block_q: int, block_k: int, D: int, itemsize: int,
                     which: str = "fwd", segmented: bool = False) -> int:
    """Estimated VMEM bytes for one grid program of the flash kernels.

    ``which``: ``"fwd"`` models the forward kernel; ``"bwd"`` the max of
    the dq and dk/dv kernels (they are separate ``pallas_call``s, so the
    binding constraint is whichever is larger).
    """
    Dp = _pad_lane(D)
    qd = block_q * Dp
    kd = block_k * Dp
    seg = 2 * (block_q + block_k) * 4 if segmented else 0
    if which == "fwd":
        inputs = 2 * (qd + 2 * kd) * itemsize + seg
        outputs = 2 * (qd * itemsize + block_q * 4)
        scratch = qd * 4 + 2 * block_q * 4
        return inputs + outputs + scratch
    # backward: q, k, v, do + lse, delta rows in both kernels
    rows = 2 * 2 * block_q * 4
    dq_in = 2 * (2 * qd + 2 * kd) * itemsize + rows + seg
    dq_total = dq_in + 2 * qd * itemsize + qd * 4
    dkv_in = 2 * (2 * qd + 2 * kd) * itemsize + rows + seg
    dkv_total = dkv_in + 2 * 2 * kd * itemsize + 2 * kd * 4
    return max(dq_total, dkv_total)


def flash_search_space(
    Sq: int,
    Sk: int,
    D: int,
    dtype,
    which: str = "fwd",
    segmented: bool = False,
    vmem_budget: Optional[int] = None,
) -> List[dict]:
    """Valid ``{"block_q", "block_k"}`` candidates for the flash kernels:
    blocks divide their sequence, meet the dtype's sublane alignment, and
    fit the VMEM budget.  The static auto default is inserted if the
    filters somehow excluded it (it compiles today, so it stays
    reachable)."""
    import numpy as np

    from chainermn_tpu.ops.flash_attention import auto_block_size

    if vmem_budget is None:
        vmem_budget = int(VMEM_BYTES * VMEM_BUDGET_FRACTION)
    itemsize = np.dtype(dtype).itemsize
    sub = _sublane(dtype)
    out = []
    for bq in BLOCK_CANDIDATES:
        if bq > Sq or Sq % bq or bq % sub:
            continue
        for bk in BLOCK_CANDIDATES:
            if bk > Sk or Sk % bk or bk % sub:
                continue
            if flash_vmem_bytes(bq, bk, D, itemsize, which,
                                segmented) > vmem_budget:
                continue
            out.append({"block_q": bq, "block_k": bk})
    default = {"block_q": auto_block_size(Sq), "block_k": auto_block_size(Sk)}
    if default not in out:
        out.append(default)
    return out


def flash_default_config(Sq: int, Sk: int) -> dict:
    """The static default geometry (what a cache miss resolves to)."""
    from chainermn_tpu.ops.flash_attention import auto_block_size

    return {"block_q": auto_block_size(Sq), "block_k": auto_block_size(Sk)}


def flash_cache_key(kind: str, dev_kind: str, dtype, Sq: int, Sk: int,
                    D: int, causal: bool, window: Optional[int],
                    segmented: bool = False) -> str:
    """Cache key for the flash kernels.  ``kind``: ``fwd`` or ``bwd`` —
    forward and backward tile economics differ (the backward streams two
    extra operands and runs two kernels), so they tune independently.
    Sequence lengths are pow2-bucketed; head dim, causality, window width
    and segmenting are exact — each changes the kernel's inner loop."""
    if kind not in ("fwd", "bwd"):
        raise ValueError(f"kind must be 'fwd' or 'bwd', got {kind!r}")
    return make_key(
        f"flash_{kind}",
        dev_kind,
        dtype,
        (("q", bucket_pow2(Sq)), ("k", bucket_pow2(Sk)), ("d", D)),
        {
            "causal": bool(causal),
            "window": 0 if window is None else int(window),
            "seg": bool(segmented),
        },
    )


def ce_search_space(N: int, V: int, D: int, dtype=None) -> List[dict]:
    """Valid ``{"chunk"}`` candidates for the fused cross-entropy: chunk
    divides the row count (the scan needs equal tiles; ``_pick_chunk``
    would silently shrink a non-divisor, making it a duplicate config)
    and the transient ``(chunk, V)`` fp32 tile stays bounded.  The static
    default chunk is always included."""
    from chainermn_tpu.ops.fused_ce import DEFAULT_CHUNK, _pick_chunk

    out = []
    for c in CE_CHUNK_CANDIDATES:
        if c > N or N % c:
            continue
        if c * V * 4 > CE_TILE_BYTES_MAX:
            continue
        out.append({"chunk": c})
    default = {"chunk": _pick_chunk(N, DEFAULT_CHUNK)}
    if default not in out:
        out.append(default)
    return out


def ce_cache_key(dev_kind: str, dtype, N: int, V: int, D: int) -> str:
    """Cache key for the fused CE: token count pow2-bucketed (the scan
    length), vocab and model dim exact (they set the tile shape)."""
    return make_key(
        "fused_ce",
        dev_kind,
        dtype,
        (("n", bucket_pow2(N)), ("v", V), ("d", D)),
        {},
    )


#: candidate context-gather chunks (in PAGES) for paged decode attention:
#: how many block-table entries one gather materializes at a time.
DECODE_BLOCK_CTX_CANDIDATES = (4, 8, 16, 32, 64, 128)

#: cap on the transient gathered (batch, ctx, n_kv, D) K/V buffer a decode
#: step may materialize per gather chunk (both K and V, double-buffered).
DECODE_GATHER_BYTES_MAX = 64 * 1024 * 1024


def decode_search_space(
    n_pages: int, page_size: int, n_kv: int, D: int, dtype,
    batch: int = 8,
) -> List[dict]:
    """Valid ``{"block_ctx"}`` candidates for the paged decode-attention
    gather: chunks of at most the table width whose transient gathered
    K+V buffer stays bounded.  ``None`` → one-shot gather is the static
    default and always a member (spelled ``{"block_ctx": 0}``), so a
    tuned pick can never lose to it."""
    import numpy as np

    itemsize = np.dtype(dtype).itemsize
    out = [{"block_ctx": 0}]  # 0 = unchunked (the static default)
    for bc in DECODE_BLOCK_CTX_CANDIDATES:
        if bc >= n_pages:
            break
        per_chunk = 2 * 2 * batch * bc * page_size * n_kv * D * itemsize
        if per_chunk > DECODE_GATHER_BYTES_MAX:
            continue
        out.append({"block_ctx": bc})
    return out


def decode_cache_key(dev_kind: str, dtype, n_pages: int, page_size: int,
                     n_kv: int, D: int) -> str:
    """Cache key for the paged decode-attention gather chunk.  Page count
    is pow2-bucketed (it only scales the table width); page size, kv-head
    count and head dim are exact — they set the gathered tile shape.  The
    decode batch is NOT part of the key: the serving engine rebuckets the
    batch every iteration, and a per-batch key would fragment the cache
    across bucket churn for a knob whose optimum tracks the tile shape."""
    return make_key(
        "paged_decode",
        dev_kind,
        dtype,
        (("p", bucket_pow2(n_pages)), ("s", page_size), ("h", n_kv),
         ("d", D)),
        {},
    )


#: candidate gradient-allreduce bucket caps: the pow2 ladder around the
#: 4 MiB static default (chainermn_tpu.communicators.packing).
BUCKET_BYTES_CANDIDATES = tuple((1 << 20) * m for m in (1, 2, 4, 8, 16, 32))


def bucket_search_space(total_bytes: Optional[int] = None) -> List[dict]:
    """Candidate ``{"bucket_bytes"}`` configs for the fused gradient
    allreduce.  ``0`` (bucketing off — the legacy per-leaf/one-buffer
    lowering) is always a candidate: for small trees one unbucketed
    collective can win.  Caps beyond the first one covering the whole
    tree are pruned (they all produce the same one-bucket-per-dtype
    plan); the static default is always reachable."""
    from chainermn_tpu.communicators.packing import DEFAULT_BUCKET_BYTES

    out = [{"bucket_bytes": 0}]
    for b in BUCKET_BYTES_CANDIDATES:
        out.append({"bucket_bytes": b})
        if total_bytes is not None and b >= total_bytes:
            break
    default = {"bucket_bytes": DEFAULT_BUCKET_BYTES}
    if default not in out:
        out.append(default)
    return out


def bucket_cache_key(dev_kind: str, dtype, total_bytes: int,
                     n_leaves: int, communicator: str) -> str:
    """Cache key for the allreduce bucket cap: total gradient bytes and
    leaf count pow2-bucketed (the economics shift with both), dominant
    dtype and communicator name exact (each variant's collective pattern
    prices buckets differently)."""
    return make_key(
        "allreduce_bucket",
        dev_kind,
        dtype,
        (("b", bucket_pow2(total_bytes)), ("l", bucket_pow2(n_leaves))),
        {"comm": str(communicator)},
    )


#: candidate overlap-schedule stage widths (buckets emitted per stage):
#: 1 is maximal overlap (each bucket's allreduce-start issues the moment
#: its last grad leaf exists), wider stages amortize dispatch overhead
#: when buckets are small.
OVERLAP_GRANULARITY_CANDIDATES = (1, 2, 4)


def overlap_schedule_search_space(
        total_bytes: Optional[int] = None) -> List[dict]:
    """Candidate ``{"granularity", "bucket_bytes"}`` configs for the
    backward-overlapped allreduce schedule — the cross product of stage
    width and the (nonzero) bucket-cap ladder, since the two knobs trade
    against each other: smaller buckets expose more overlap points but
    need wider stages to keep per-collective dispatch cost amortized.
    The static default (granularity 1 × the 4 MiB default cap) is always
    first; ``bucket_bytes=0`` is excluded because the unbucketed path
    has no schedule to stage."""
    from chainermn_tpu.communicators.packing import DEFAULT_BUCKET_BYTES

    caps = [c["bucket_bytes"] for c in bucket_search_space(total_bytes)
            if c["bucket_bytes"] > 0]
    out = [{"granularity": 1, "bucket_bytes": DEFAULT_BUCKET_BYTES}]
    for g in OVERLAP_GRANULARITY_CANDIDATES:
        for b in caps:
            cfg = {"granularity": g, "bucket_bytes": b}
            if cfg not in out:
                out.append(cfg)
    return out


def overlap_cache_key(dev_kind: str, dtype, total_bytes: int,
                      n_leaves: int, communicator: str) -> str:
    """Cache key for the overlap schedule: same family signature as
    :func:`bucket_cache_key` (the schedule is a property of the same
    tree family) under a distinct kernel tag, so the two tuned answers
    coexist and ``bucket_bytes`` tuned alone stays valid."""
    return make_key(
        "overlap_schedule",
        dev_kind,
        dtype,
        (("b", bucket_pow2(total_bytes)), ("l", bucket_pow2(n_leaves))),
        {"comm": str(communicator)},
    )


def comm_dtype_search_space() -> List[dict]:
    """Candidate ``{"comm_dtype"}`` configs for the gradient wire dtype:
    ``"none"`` (full precision — the static default, pinned first so a
    tuned pick can never lose to it) plus every canonical narrow wire
    dtype.  Unlike the other spaces this one trades a little accuracy
    (bounded per dtype, see ``communicators.quant``) for wire bytes, so
    the tuner records the measured quantization error alongside the
    timing for the operator to veto."""
    from chainermn_tpu.communicators.quant import COMM_DTYPE_CHOICES

    return [{"comm_dtype": "none"}] + [
        {"comm_dtype": c} for c in COMM_DTYPE_CHOICES
    ]


def comm_dtype_cache_key(dev_kind: str, dtype, total_bytes: int,
                         n_leaves: int, communicator: str) -> str:
    """Cache key for the gradient wire dtype: same family signature as
    :func:`bucket_cache_key` (the trade-off is a property of the same
    tree family) under its own kernel tag."""
    return make_key(
        "comm_dtype",
        dev_kind,
        dtype,
        (("b", bucket_pow2(total_bytes)), ("l", bucket_pow2(n_leaves))),
        {"comm": str(communicator)},
    )


def kv_dtype_search_space() -> List[dict]:
    """Candidate ``{"kv_dtype"}`` configs for KV page storage: ``"none"``
    (model dtype — the static default) plus every canonical quantized
    page dtype."""
    from chainermn_tpu.communicators.quant import KV_DTYPE_CHOICES

    return [{"kv_dtype": "none"}] + [
        {"kv_dtype": c} for c in KV_DTYPE_CHOICES
    ]


def kv_dtype_cache_key(dev_kind: str, dtype, n_pages: int, page_size: int,
                       n_kv: int, d_head: int) -> str:
    """Cache key for the KV page dtype: same geometry signature as
    :func:`decode_cache_key` (the decision is a property of the same
    page shape) under its own kernel tag."""
    return make_key(
        "kv_dtype",
        dev_kind,
        dtype,
        (("p", bucket_pow2(n_pages)), ("s", page_size), ("h", n_kv),
         ("d", d_head)),
        {},
    )


def draft_search_space(n_layers: int) -> List[dict]:
    """Candidate ``{"draft", "draft_layers"}`` configs for the
    speculative draft source: ``"ngram"`` (model-free — the static
    default) plus the layer-truncated self-draft at a few depths.
    Deeper drafts accept longer but cost more per proposal, so the
    trade lands differently per model family — exactly what the
    measured argmin is for."""
    ks = sorted({max(1, int(n_layers) // 4), max(1, int(n_layers) // 2)})
    return [{"draft": "ngram", "draft_layers": 0}] + [
        {"draft": "model", "draft_layers": k} for k in ks
    ]


def draft_cache_key(dev_kind: str, dtype, vocab: int, d_model: int,
                    n_layers: int, max_len: int) -> str:
    """Cache key for the draft source: a property of the target model
    family (vocab/width/depth) and the serving context budget, under
    its own kernel tag."""
    return make_key(
        "draft",
        dev_kind,
        dtype,
        (("v", bucket_pow2(vocab)), ("d", bucket_pow2(d_model)),
         ("l", int(n_layers)), ("c", bucket_pow2(max_len))),
        {},
    )


def prefill_chunk_search_space(max_len: int,
                               block_size: int) -> List[dict]:
    """Candidate ``{"prefill_chunk"}`` token-slice sizes for chunked
    prefill: 0 (off — monolithic prefill, the static default) plus
    page-aligned slices strictly below the context budget.  Smaller
    slices bound decode p99 tighter but pay more scheduler iterations
    per prompt; the sweet spot is a property of the page geometry."""
    out = [{"prefill_chunk": 0}]
    for mult in (8, 16, 32, 64):
        c = int(block_size) * mult
        if 0 < c < int(max_len):
            out.append({"prefill_chunk": c})
    return out


def prefill_chunk_cache_key(dev_kind: str, max_len: int,
                            block_size: int) -> str:
    """Cache key for the prefill slice size: the page geometry and
    context budget alone (dtype-independent — the chunk program is the
    same jitted step either way)."""
    return make_key(
        "prefill_chunk",
        dev_kind,
        "none",
        (("c", bucket_pow2(max_len)), ("s", int(block_size))),
        {},
    )


def layout_search_space(mesh_axes, params=None, mesh=None) -> List[dict]:
    """Candidate ``{"plan"}`` configs for the parameter-layout search:
    every registry sharding plan whose axes the mesh has — and, when a
    parameter tree (and optionally the mesh, for divisibility) is given,
    that validates clean against it.  The ``dp`` plan (pure data
    parallelism, everything replicated — today's hand-picked layout) is
    pinned first as the static default, so a tuned layout can never
    lose to shipping no plan at all."""
    from chainermn_tpu.sharding import list_plans, validate

    axes = set(mesh_axes)
    out = [{"plan": "dp"}]
    for plan in list_plans():
        if plan.name == "dp" or not set(plan.axes) <= axes:
            continue
        if params is not None and not validate(plan, params, mesh).ok:
            continue
        out.append({"plan": plan.name})
    return out


def serve_group_search_space(n_heads: int, d_ff: int, d_model: int,
                             n_devices: int,
                             max_batch: int) -> List[dict]:
    """Candidate ``{"group_size", "pp_stages"}`` shard-group shapes for
    the serving cluster: how many tensor-parallel shards one replica
    spans (the registry ``tp`` plan over that many devices) crossed
    with how many pipeline microbatch stages the decode batch splits
    into.  ``{1, 1}`` (today's single-shard replica) is pinned first as
    the static default; group sizes must divide the model's heads, FFN
    and width AND fit the local device count, pipeline depths must
    leave each microbatch at least one row.  Bit-exactness makes every
    candidate produce identical streams — wall time per workload is the
    whole trade."""
    out = [{"group_size": 1, "pp_stages": 1}]
    groups = [1] + [
        k for k in (2, 4)
        if k <= int(n_devices)
        and n_heads % k == 0 and d_ff % k == 0 and d_model % k == 0
    ]
    stages = [1] + [s for s in (2, 4) if s <= int(max_batch)]
    for k in groups:
        for s in stages:
            cfg = {"group_size": k, "pp_stages": s}
            if cfg not in out:
                out.append(cfg)
    return out


def serve_group_cache_key(dev_kind: str, dtype, vocab: int, d_model: int,
                          n_layers: int, max_len: int, n_devices: int,
                          max_batch: int) -> str:
    """Cache key for the shard-group shape: model family (pow2-bucketed
    like the draft key), the serving context budget, and — unlike the
    single-engine tuners — the local device count and decode batch
    ceiling, since they bound the candidate set itself."""
    return make_key(
        "serve_group",
        dev_kind,
        dtype,
        (("v", bucket_pow2(vocab)), ("d", bucket_pow2(d_model)),
         ("l", int(n_layers)), ("c", bucket_pow2(max_len))),
        {"dev": str(int(n_devices)), "b": str(int(max_batch))},
    )


def layout_cache_key(dev_kind: str, dtype, n_params: int, n_leaves: int,
                     mesh_shape, model: str = "transformer_lm") -> str:
    """Cache key for the layout search: parameter count and leaf count
    pow2-bucketed (layout economics shift with model scale, not exact
    width), mesh shape and model family exact — the same plan table
    prices completely differently on a (8,) ring vs a (4, 2) torus, and
    across model families with different shardable structure."""
    return make_key(
        "layout",
        dev_kind,
        dtype,
        (("p", bucket_pow2(max(1, n_params))),
         ("l", bucket_pow2(max(1, n_leaves)))),
        {"mesh": "x".join(str(int(s)) for s in mesh_shape),
         "model": str(model)},
    )
