"""Tuners (measure → pick → persist) and the runtime lookups the ops
consult.

The tuners only ever run explicitly (CLI / ``bench.py --autotune``) —
never from inside an op.  The lookups are trace-time reads of the
persistent cache, validated against the *actual* call shape (pow2
bucketing means a 3072-long call can hit a 4096-bucket entry whose
blocks do not divide it — such an entry is ignored, not an error), and
return None whenever tuning is disabled, off-TPU, or on a miss; the ops
then use their static defaults.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple

import jax

from chainermn_tpu.tuning.cache import (
    TuneCache,
    autotune_enabled,
    device_kind,
    dtype_name,
    runtime_lookup_enabled,
    shared_cache,
)
from chainermn_tpu.tuning.measure import best_config, measure_candidates
from chainermn_tpu.tuning.search_space import (
    bucket_cache_key,
    bucket_search_space,
    ce_cache_key,
    ce_search_space,
    comm_dtype_cache_key,
    comm_dtype_search_space,
    decode_cache_key,
    decode_search_space,
    draft_cache_key,
    draft_search_space,
    flash_cache_key,
    flash_default_config,
    flash_search_space,
    kv_dtype_cache_key,
    kv_dtype_search_space,
    layout_cache_key,
    layout_search_space,
    overlap_cache_key,
    overlap_schedule_search_space,
    prefill_chunk_cache_key,
    prefill_chunk_search_space,
    serve_group_cache_key,
    serve_group_search_space,
)


def _blocks_valid(bq: int, bk: int, Sq: int, Sk: int, dtype) -> bool:
    """Mirror of ``flash_attention``'s compiled-path gate: blocks divide
    their sequences and meet the dtype's sublane alignment."""
    sub = 16 if dtype_name(dtype) == "bfloat16" else 8
    return (
        bq >= 1 and bk >= 1
        and Sq % bq == 0 and Sk % bk == 0
        and bq % sub == 0 and bk % sub == 0
    )


# --------------------------------------------------------------------------
# Runtime lookups — what flash_attention / fused_cross_entropy call when the
# caller does not pin a geometry.
# --------------------------------------------------------------------------


def lookup_flash_blocks(
    kind: str,
    *,
    Sq: int,
    Sk: int,
    D: int,
    dtype,
    causal: bool,
    window: Optional[int] = None,
    segmented: bool = False,
) -> Optional[Tuple[int, int]]:
    """Tuned ``(block_q, block_k)`` for the flash ``kind`` (``fwd`` /
    ``bwd``) or None (miss, invalid entry, or lookups disabled)."""
    if not runtime_lookup_enabled():
        return None
    try:
        key = flash_cache_key(
            kind, device_kind(), dtype, Sq, Sk, D, causal, window, segmented
        )
        entry = shared_cache().get(key)
        if not entry:
            return None
        bq, bk = int(entry["block_q"]), int(entry["block_k"])
    except Exception:
        return None
    if not _blocks_valid(bq, bk, Sq, Sk, dtype):
        return None
    return bq, bk


def lookup_ce_chunk(*, N: int, V: int, D: int, dtype) -> Optional[int]:
    """Tuned fused-CE row chunk or None (miss / disabled)."""
    if not runtime_lookup_enabled():
        return None
    try:
        entry = shared_cache().get(
            ce_cache_key(device_kind(), dtype, N, V, D)
        )
        if not entry:
            return None
        chunk = int(entry["chunk"])
    except Exception:
        return None
    return chunk if chunk >= 1 else None


def lookup_bucket_bytes(*, total_bytes: int, n_leaves: int, dtype,
                        communicator: str) -> Optional[int]:
    """Tuned gradient-allreduce bucket cap for one (tree size, leaf
    count, dominant dtype, communicator) family, or None (miss /
    disabled).  ``0`` is a valid tuned answer: the measured winner was
    the unbucketed path."""
    if not runtime_lookup_enabled():
        return None
    try:
        entry = shared_cache().get(bucket_cache_key(
            device_kind(), dtype, total_bytes, n_leaves, communicator
        ))
        if not entry:
            return None
        bb = int(entry["bucket_bytes"])
    except Exception:
        return None
    return bb if bb >= 0 else None


def lookup_overlap_schedule(*, total_bytes: int, n_leaves: int, dtype,
                            communicator: str) -> Optional[dict]:
    """Tuned overlap schedule (``{"granularity", "bucket_bytes"}``) for
    one (tree size, leaf count, dominant dtype, communicator) family, or
    None (miss / disabled).  Consulted by the communicators'
    ``resolve_overlap_granularity`` at trace time, after the ctor and
    ``CHAINERMN_TPU_OVERLAP_GRANULARITY`` env overrides."""
    if not runtime_lookup_enabled():
        return None
    try:
        entry = shared_cache().get(overlap_cache_key(
            device_kind(), dtype, total_bytes, n_leaves, communicator
        ))
        if not entry:
            return None
        g = int(entry["granularity"])
        bb = int(entry.get("bucket_bytes", -1))
    except Exception:
        return None
    if g < 1:
        return None
    return {"granularity": g, "bucket_bytes": bb if bb > 0 else None}


def lookup_decode_block_ctx(*, n_pages: int, page_size: int, n_kv: int,
                            d_head: int, dtype) -> Optional[int]:
    """Tuned context-gather chunk (in pages) for paged decode attention,
    or None (one-shot gather) on a miss / off-TPU / under pytest.  The
    inert-off-TPU guard doubles as the serving engine's determinism
    guard: CPU decode numerics never depend on the tune cache."""
    if not runtime_lookup_enabled():
        return None
    try:
        entry = shared_cache().get(decode_cache_key(
            device_kind(), dtype, n_pages, page_size, n_kv, d_head
        ))
        if not entry:
            return None
        bc = int(entry["block_ctx"])
    except Exception:
        return None
    return bc if bc >= 1 else None


def lookup_comm_dtype(*, total_bytes: int, n_leaves: int, dtype,
                      communicator: str) -> Optional[str]:
    """Tuned gradient wire dtype (canonical ``"int8"``/``"fp8"``) for
    one (tree size, leaf count, dominant dtype, communicator) family, or
    None (full precision) on a miss / off-TPU / under pytest.  Consulted
    by ``CommunicatorBase.resolve_comm_dtype`` after the ctor and
    ``CHAINERMN_TPU_COMM_DTYPE`` overrides — and like every lookup it is
    inert under pytest, so tier-1 gradients never quantize by surprise."""
    if not runtime_lookup_enabled():
        return None
    try:
        entry = shared_cache().get(comm_dtype_cache_key(
            device_kind(), dtype, total_bytes, n_leaves, communicator
        ))
        if not entry:
            return None
        from chainermn_tpu.communicators.quant import canonical_comm_dtype

        cd = canonical_comm_dtype(str(entry["comm_dtype"]))
    except Exception:
        return None
    return None if cd in (None, "none") else cd


def lookup_kv_dtype(*, n_pages: int, page_size: int, n_kv: int,
                    d_head: int, dtype) -> Optional[str]:
    """Tuned KV page storage dtype (canonical ``"int8"``) for one page
    geometry, or None (model dtype) on a miss / off-TPU / under pytest.
    Consulted by the serving engine's ``kv_dtype`` resolution after the
    config and ``CHAINERMN_TPU_KV_DTYPE`` overrides."""
    if not runtime_lookup_enabled():
        return None
    try:
        entry = shared_cache().get(kv_dtype_cache_key(
            device_kind(), dtype, n_pages, page_size, n_kv, d_head
        ))
        if not entry:
            return None
        from chainermn_tpu.communicators.quant import canonical_kv_dtype

        return canonical_kv_dtype(str(entry["kv_dtype"]))
    except Exception:
        return None


def lookup_draft(*, vocab: int, d_model: int, n_layers: int,
                 max_len: int, dtype) -> Optional[str]:
    """Tuned speculative draft source (``"ngram"``/``"model"``) for one
    target model family, or None (n-gram) on a miss / off-TPU / under
    pytest.  Consulted by the serving engine's ``draft`` resolution
    after the config and ``CHAINERMN_TPU_DRAFT`` overrides — inert
    under pytest like every lookup, so tier-1 never builds a draft
    model by surprise."""
    if not runtime_lookup_enabled():
        return None
    try:
        entry = shared_cache().get(draft_cache_key(
            device_kind(), dtype, vocab, d_model, n_layers, max_len
        ))
        if not entry:
            return None
        src = str(entry["draft"])
    except Exception:
        return None
    return src if src in ("ngram", "model") else None


def lookup_draft_layers(*, vocab: int, d_model: int, n_layers: int,
                        max_len: int, dtype) -> Optional[int]:
    """Companion to :func:`lookup_draft`: the tuned draft depth for the
    same key, or None (the engine's ``n_layers // 2`` default)."""
    if not runtime_lookup_enabled():
        return None
    try:
        entry = shared_cache().get(draft_cache_key(
            device_kind(), dtype, vocab, d_model, n_layers, max_len
        ))
        if not entry or entry.get("draft") != "model":
            return None
        k = int(entry["draft_layers"])
    except Exception:
        return None
    return k if k >= 1 else None


def lookup_prefill_chunk(*, max_len: int,
                         block_size: int) -> Optional[int]:
    """Tuned chunked-prefill slice size (tokens) for one page geometry,
    or None (0 — monolithic prefill) on a miss / off-TPU / under
    pytest.  Consulted by the serving engine's ``prefill_chunk``
    resolution after the config and ``CHAINERMN_TPU_PREFILL_CHUNK``
    overrides."""
    if not runtime_lookup_enabled():
        return None
    try:
        entry = shared_cache().get(prefill_chunk_cache_key(
            device_kind(), max_len, block_size
        ))
        if not entry:
            return None
        c = int(entry["prefill_chunk"])
    except Exception:
        return None
    return c if c > 0 else None


def lookup_layout(*, mesh, n_params: int, n_leaves: int, dtype,
                  model: str = "transformer_lm") -> Optional[str]:
    """Tuned registry-plan name for one (model family, scale, mesh
    shape) — or None (miss / disabled / the cached plan no longer fits
    the mesh).  Callers resolve the name via
    ``chainermn_tpu.sharding.get_plan``."""
    if not runtime_lookup_enabled():
        return None
    try:
        entry = shared_cache().get(layout_cache_key(
            device_kind(), dtype, n_params, n_leaves,
            tuple(mesh.devices.shape), model,
        ))
        if not entry:
            return None
        name = str(entry["plan"])
        from chainermn_tpu.sharding import get_plan

        plan = get_plan(name)
    except Exception:
        return None
    if not set(plan.axes) <= set(mesh.axis_names):
        return None
    return name


# --------------------------------------------------------------------------
# Tuners.
# --------------------------------------------------------------------------


def _require_tuning_allowed(what: str):
    if not autotune_enabled():
        raise RuntimeError(
            f"autotuning ({what}) is disabled in this context — under "
            "pytest the tuner is inert by design (tier-1 determinism "
            "guard), and CHAINERMN_TPU_AUTOTUNE=0 disables it explicitly"
        )


def _finish(key, results, default_cfg, cache, extra):
    """Pick the winner, fold in provenance, persist."""
    best = best_config(results)
    if best is None:
        return {"key": key, "chosen": None, "results": results,
                "error": "every candidate failed"}
    default_secs = next(
        (r["seconds"] for r in results if r["config"] == default_cfg),
        None,
    )
    entry = dict(best["config"])
    entry.update(
        seconds=best["seconds"],
        default_config=default_cfg,
        default_seconds=default_secs,
        speedup_vs_default=(
            round(default_secs / best["seconds"], 4)
            if default_secs else None
        ),
        candidates_timed=sum(1 for r in results if r["seconds"] is not None),
        candidates_skipped=sum(1 for r in results if r["seconds"] is None),
        device_kind=device_kind(),
        jax_version=jax.__version__,
        tuned_at=time.strftime("%Y-%m-%dT%H:%M:%S"),
        source="chainermn_tpu.tuning.autotune",
        **extra,
    )
    cache.put(key, entry)
    cache.save()
    return {"key": key, "chosen": dict(best["config"]),
            "seconds": best["seconds"], "default_seconds": default_secs,
            "speedup_vs_default": entry["speedup_vs_default"],
            "results": results, "cache_path": cache.path}


def tune_flash(
    *,
    Sq: int,
    Sk: int,
    D: int,
    dtype="bfloat16",
    causal: bool = True,
    window: Optional[int] = None,
    batch_heads: int = 8,
    cache: Optional[TuneCache] = None,
    n1: int = 3,
    repeats: int = 3,
    force: bool = False,
    dry_run: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune the flash attention forward AND backward block geometry for
    one shape family; returns ``{"fwd": record, "bwd": record}``.

    The backward sweep pins the forward blocks to the forward winner and
    varies only the backward geometry (``jax.grad`` re-runs the forward,
    so holding it constant isolates the backward's contribution to the
    argmin).  ``dry_run`` enumerates candidates without compiling or
    timing anything.
    """
    import numpy as np

    fwd_space = flash_search_space(Sq, Sk, D, dtype, which="fwd")
    bwd_space = flash_search_space(Sq, Sk, D, dtype, which="bwd")
    default_cfg = flash_default_config(Sq, Sk)
    dev = device_kind()
    fwd_key = flash_cache_key("fwd", dev, dtype, Sq, Sk, D, causal, window)
    bwd_key = flash_cache_key("bwd", dev, dtype, Sq, Sk, D, causal, window)
    if dry_run:
        return {
            "kernel": "flash", "dry_run": True,
            "fwd": {"key": fwd_key, "candidates": fwd_space,
                    "default": default_cfg},
            "bwd": {"key": bwd_key, "candidates": bwd_space,
                    "default": default_cfg},
        }
    _require_tuning_allowed("flash attention")
    cache = cache or shared_cache()

    from chainermn_tpu.ops.flash_attention import _flash_bh, _flash_bh_fwd
    from chainermn_tpu.utils.profiling import sync

    scale = 1.0 / (D ** 0.5)
    rng = np.random.RandomState(0)
    q = jax.numpy.asarray(
        rng.randn(batch_heads, Sq, D), dtype_name(dtype)
    )
    k = jax.numpy.asarray(
        rng.randn(batch_heads, Sk, D), dtype_name(dtype)
    )
    v = jax.numpy.asarray(
        rng.randn(batch_heads, Sk, D), dtype_name(dtype)
    )

    out = {"kernel": "flash"}

    cached = cache.get(fwd_key) if not force else None
    if cached and _blocks_valid(
        int(cached.get("block_q", 0)), int(cached.get("block_k", 0)),
        Sq, Sk, dtype,
    ):
        out["fwd"] = {
            "key": fwd_key, "cached": True,
            "chosen": {"block_q": int(cached["block_q"]),
                       "block_k": int(cached["block_k"])},
        }
    else:
        if log:
            log(f"flash fwd {fwd_key}: {len(fwd_space)} candidates")

        def build_fwd(cfg):
            f = jax.jit(
                lambda q, k, v: _flash_bh_fwd(
                    q, k, v, scale=scale, causal=causal,
                    block_q=cfg["block_q"], block_k=cfg["block_k"],
                    interpret=False, window=window,
                )[0]
            )

            def run(n):
                t0 = time.perf_counter()
                for _ in range(n):
                    o = f(q, k, v)
                sync(o)
                return time.perf_counter() - t0

            return run

        results = measure_candidates(
            build_fwd, fwd_space, n1=n1, repeats=repeats, log=log
        )
        out["fwd"] = _finish(
            fwd_key, results, default_cfg, cache,
            {"kernel": "flash_fwd", "dtype": dtype_name(dtype),
             "Sq": Sq, "Sk": Sk, "D": D, "causal": causal,
             "window": window, "batch_heads": batch_heads},
        )

    fq = out["fwd"]["chosen"] or default_cfg
    cached = cache.get(bwd_key) if not force else None
    if cached and _blocks_valid(
        int(cached.get("block_q", 0)), int(cached.get("block_k", 0)),
        Sq, Sk, dtype,
    ):
        out["bwd"] = {
            "key": bwd_key, "cached": True,
            "chosen": {"block_q": int(cached["block_q"]),
                       "block_k": int(cached["block_k"])},
        }
        return out
    if log:
        log(f"flash bwd {bwd_key}: {len(bwd_space)} candidates")

    def build_bwd(cfg):
        def loss(q, k, v):
            return _flash_bh(
                q, k, v, scale, causal, fq["block_q"], fq["block_k"],
                False, window, cfg["block_q"], cfg["block_k"],
            ).sum()

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        def run(n):
            t0 = time.perf_counter()
            for _ in range(n):
                dq, dk, dv = g(q, k, v)
            sync(dq)
            return time.perf_counter() - t0

        return run

    results = measure_candidates(
        build_bwd, bwd_space, n1=n1, repeats=repeats, log=log
    )
    out["bwd"] = _finish(
        bwd_key, results, default_cfg, cache,
        {"kernel": "flash_bwd", "dtype": dtype_name(dtype),
         "Sq": Sq, "Sk": Sk, "D": D, "causal": causal,
         "window": window, "batch_heads": batch_heads,
         "fwd_blocks": fq},
    )
    return out


def tune_fused_ce(
    *,
    N: int,
    V: int,
    D: int,
    dtype="bfloat16",
    cache: Optional[TuneCache] = None,
    n1: int = 3,
    repeats: int = 3,
    force: bool = False,
    dry_run: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune the fused cross-entropy row chunk for an ``(N, V, D)`` loss
    head; times the full fwd+bwd (``value_and_grad``), which is what the
    training step pays."""
    import numpy as np

    from chainermn_tpu.ops.fused_ce import DEFAULT_CHUNK, _pick_chunk

    space = ce_search_space(N, V, D, dtype)
    default_cfg = {"chunk": _pick_chunk(N, DEFAULT_CHUNK)}
    key = ce_cache_key(device_kind(), dtype, N, V, D)
    if dry_run:
        return {"kernel": "fused_ce", "dry_run": True, "key": key,
                "candidates": space, "default": default_cfg}
    _require_tuning_allowed("fused cross-entropy")
    cache = cache or shared_cache()
    cached = cache.get(key) if not force else None
    if cached and int(cached.get("chunk", 0)) >= 1:
        return {"kernel": "fused_ce", "key": key, "cached": True,
                "chosen": {"chunk": int(cached["chunk"])}}

    from chainermn_tpu.ops.fused_ce import fused_cross_entropy
    from chainermn_tpu.utils.profiling import sync

    rng = np.random.RandomState(0)
    h = jax.numpy.asarray(rng.randn(N, D), dtype_name(dtype))
    emb = jax.numpy.asarray(rng.randn(V, D), dtype_name(dtype))
    labels = jax.numpy.asarray(
        rng.randint(0, V, size=(N,)), "int32"
    )
    if log:
        log(f"fused_ce {key}: {len(space)} candidates")

    def build(cfg):
        g = jax.jit(jax.value_and_grad(
            lambda h, emb: fused_cross_entropy(
                h, emb, labels, chunk=cfg["chunk"]
            ),
            argnums=(0, 1),
        ))

        def run(n):
            t0 = time.perf_counter()
            for _ in range(n):
                _loss, (dh, _demb) = g(h, emb)
            sync(dh)
            return time.perf_counter() - t0

        return run

    results = measure_candidates(build, space, n1=n1, repeats=repeats,
                                 log=log)
    rec = _finish(
        key, results, default_cfg, cache,
        {"kernel": "fused_ce", "dtype": dtype_name(dtype),
         "N": N, "V": V, "D": D},
    )
    rec["kernel"] = "fused_ce"
    return rec


def tune_allreduce_bucket(
    *,
    communicator: str = "xla_ici",
    total_mb: float = 64.0,
    n_leaves: int = 64,
    dtype="float32",
    mesh=None,
    cache: Optional[TuneCache] = None,
    n1: int = 3,
    repeats: int = 3,
    force: bool = False,
    dry_run: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune the gradient-allreduce ``bucket_bytes`` for one tree family.

    Times ``eager_allreduce_grad`` over the shared synthetic mixed-shape
    tree (``packing.synthetic_grad_tree``) at each candidate cap —
    including 0, the unbucketed path — and persists the argmin under a
    key the communicators' trace-time ``resolve_bucket_bytes`` lookup
    reads back on TPU."""
    import numpy as np

    from chainermn_tpu.communicators.packing import (
        DEFAULT_BUCKET_BYTES,
        synthetic_grad_tree,
    )

    total_bytes = int(total_mb * 1024 * 1024)
    tree = synthetic_grad_tree(n_leaves, total_bytes, dtypes=(dtype,))
    total_bytes = sum(
        l.size * l.dtype.itemsize for l in jax.tree_util.tree_leaves(tree)
    )
    space = bucket_search_space(total_bytes)
    default_cfg = {"bucket_bytes": DEFAULT_BUCKET_BYTES}
    key = bucket_cache_key(
        device_kind(), dtype, total_bytes, n_leaves, communicator
    )
    if dry_run:
        return {"kernel": "allreduce_bucket", "dry_run": True, "key": key,
                "candidates": space, "default": default_cfg}
    _require_tuning_allowed("allreduce bucketing")
    cache = cache or shared_cache()
    cached = cache.get(key) if not force else None
    if cached and int(cached.get("bucket_bytes", -1)) >= 0:
        return {"kernel": "allreduce_bucket", "key": key, "cached": True,
                "chosen": {"bucket_bytes": int(cached["bucket_bytes"])}}

    from chainermn_tpu.communicators import create_communicator
    from chainermn_tpu.utils.profiling import sync

    n = None  # filled by the first build
    if log:
        log(f"allreduce_bucket {key}: {len(space)} candidates")

    def build(cfg):
        nonlocal n
        comm = create_communicator(
            communicator, mesh=mesh, bucket_bytes=cfg["bucket_bytes"]
        )
        n = comm.device_size
        stacked = jax.tree_util.tree_map(
            lambda l: jax.numpy.stack([jax.numpy.asarray(l)] * n), tree
        )

        def run(k):
            t0 = time.perf_counter()
            out = stacked
            for _ in range(k):
                out = comm.eager_allreduce_grad(out)
            sync(jax.tree_util.tree_leaves(out)[0])
            return time.perf_counter() - t0

        return run

    results = measure_candidates(build, space, n1=n1, repeats=repeats,
                                 log=log)
    rec = _finish(
        key, results, default_cfg, cache,
        {"kernel": "allreduce_bucket", "dtype": dtype_name(dtype),
         "communicator": communicator, "total_bytes": total_bytes,
         "n_leaves": n_leaves, "device_size": n},
    )
    rec["kernel"] = "allreduce_bucket"
    return rec


def tune_overlap_schedule(
    *,
    communicator: str = "xla_ici",
    total_mb: float = 64.0,
    n_leaves: int = 64,
    dtype="float32",
    mesh=None,
    cache: Optional[TuneCache] = None,
    n1: int = 3,
    repeats: int = 3,
    force: bool = False,
    dry_run: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune the backward-overlap schedule (stage granularity ×
    ``bucket_bytes``) for one tree family.

    Times the overlapped ``eager_allreduce_grad`` at each candidate —
    the schedule's win is how well ``all-reduce-start`` pairs hide under
    the backward compute the latency-hiding scheduler interleaves, so
    this tuner is only meaningful on TPU (the shared
    ``_require_tuning_allowed`` gate already refuses under pytest).
    Persists the argmin under a key the communicators' trace-time
    ``resolve_overlap_granularity`` lookup reads back."""
    from chainermn_tpu.communicators.packing import synthetic_grad_tree

    total_bytes = int(total_mb * 1024 * 1024)
    tree = synthetic_grad_tree(n_leaves, total_bytes, dtypes=(dtype,))
    total_bytes = sum(
        l.size * l.dtype.itemsize for l in jax.tree_util.tree_leaves(tree)
    )
    space = overlap_schedule_search_space(total_bytes)
    default_cfg = space[0]  # granularity 1 × the static default cap
    key = overlap_cache_key(
        device_kind(), dtype, total_bytes, n_leaves, communicator
    )
    if dry_run:
        return {"kernel": "overlap_schedule", "dry_run": True, "key": key,
                "candidates": space, "default": default_cfg}
    _require_tuning_allowed("overlap schedule")
    cache = cache or shared_cache()
    cached = cache.get(key) if not force else None
    if cached and int(cached.get("granularity", 0)) >= 1:
        return {"kernel": "overlap_schedule", "key": key, "cached": True,
                "chosen": {
                    "granularity": int(cached["granularity"]),
                    "bucket_bytes": int(cached["bucket_bytes"]),
                }}

    from chainermn_tpu.communicators import create_communicator
    from chainermn_tpu.utils.profiling import sync

    n = None
    if log:
        log(f"overlap_schedule {key}: {len(space)} candidates")

    def build(cfg):
        nonlocal n
        comm = create_communicator(
            communicator, mesh=mesh,
            bucket_bytes=cfg["bucket_bytes"],
            overlap=True, overlap_granularity=cfg["granularity"],
        )
        n = comm.device_size
        stacked = jax.tree_util.tree_map(
            lambda l: jax.numpy.stack([jax.numpy.asarray(l)] * n), tree
        )

        def run(k):
            t0 = time.perf_counter()
            out = stacked
            for _ in range(k):
                out = comm.eager_allreduce_grad(out)
            sync(jax.tree_util.tree_leaves(out)[0])
            return time.perf_counter() - t0

        return run

    results = measure_candidates(build, space, n1=n1, repeats=repeats,
                                 log=log)
    rec = _finish(
        key, results, default_cfg, cache,
        {"kernel": "overlap_schedule", "dtype": dtype_name(dtype),
         "communicator": communicator, "total_bytes": total_bytes,
         "n_leaves": n_leaves, "device_size": n},
    )
    rec["kernel"] = "overlap_schedule"
    return rec


def tune_decode_attention(
    *,
    n_pages: int,
    page_size: int,
    n_kv: int,
    d_head: int,
    n_heads: Optional[int] = None,
    batch: int = 8,
    dtype="bfloat16",
    cache: Optional[TuneCache] = None,
    n1: int = 3,
    repeats: int = 3,
    force: bool = False,
    dry_run: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune the paged decode-attention context-gather chunk for one page
    geometry.  Times :func:`~chainermn_tpu.ops.paged_attention_decode`
    over a full table (the worst-case context) at each candidate
    ``block_ctx`` — including 0, the one-shot gather — and persists the
    argmin under the key the serving engine's trace-time lookup
    (:func:`lookup_decode_block_ctx`) reads back on TPU.  Chunking is
    data movement only, so the tuned pick is bit-identical to the
    default; only the transient-buffer footprint and gather schedule
    move."""
    import numpy as np

    space = decode_search_space(n_pages, page_size, n_kv, d_head, dtype,
                                batch=batch)
    default_cfg = {"block_ctx": 0}
    key = decode_cache_key(
        device_kind(), dtype, n_pages, page_size, n_kv, d_head
    )
    if dry_run:
        return {"kernel": "paged_decode", "dry_run": True, "key": key,
                "candidates": space, "default": default_cfg}
    _require_tuning_allowed("paged decode attention")
    cache = cache or shared_cache()
    cached = cache.get(key) if not force else None
    if cached and int(cached.get("block_ctx", -1)) >= 0:
        return {"kernel": "paged_decode", "key": key, "cached": True,
                "chosen": {"block_ctx": int(cached["block_ctx"])}}

    from chainermn_tpu.ops.decode_attention import paged_attention_decode
    from chainermn_tpu.utils.profiling import sync

    H = n_heads or n_kv
    W = n_pages // max(1, batch)  # pages per sequence, full occupancy
    rng = np.random.RandomState(0)
    dt = dtype_name(dtype)
    q = jax.numpy.asarray(rng.randn(batch, 1, H, d_head), dt)
    kp = jax.numpy.asarray(
        rng.randn(n_pages, page_size, n_kv, d_head), dt
    )
    vp = jax.numpy.asarray(
        rng.randn(n_pages, page_size, n_kv, d_head), dt
    )
    tables = jax.numpy.asarray(
        rng.permutation(n_pages)[: batch * W].reshape(batch, W), "int32"
    )
    lens = jax.numpy.full((batch,), W * page_size, "int32")
    if log:
        log(f"paged_decode {key}: {len(space)} candidates")

    def build(cfg):
        bc = cfg["block_ctx"] or None
        f = jax.jit(
            lambda q, kp, vp, t, sl: paged_attention_decode(
                q, kp, vp, t, sl, block_ctx=bc
            )
        )

        def run(n):
            t0 = time.perf_counter()
            for _ in range(n):
                o = f(q, kp, vp, tables, lens)
            sync(o)
            return time.perf_counter() - t0

        return run

    results = measure_candidates(build, space, n1=n1, repeats=repeats,
                                 log=log)
    rec = _finish(
        key, results, default_cfg, cache,
        {"kernel": "paged_decode", "dtype": dt, "n_pages": n_pages,
         "page_size": page_size, "n_kv": n_kv, "d_head": d_head,
         "batch": batch},
    )
    rec["kernel"] = "paged_decode"
    return rec


def tune_comm_dtype(
    *,
    communicator: str = "xla_ici",
    total_mb: float = 64.0,
    n_leaves: int = 64,
    dtype="float32",
    mesh=None,
    cache: Optional[TuneCache] = None,
    n1: int = 3,
    repeats: int = 3,
    force: bool = False,
    dry_run: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune the gradient wire dtype (``comm_dtype``) for one tree family.

    Times ``eager_allreduce_grad`` over the shared synthetic tree at
    full precision and at each narrow wire dtype, persisting the argmin
    under the key ``resolve_comm_dtype`` reads back on TPU.  Every
    candidate's measured max-abs error vs the fp32 path is recorded in
    the result (and the winner's in the cache entry) so an operator can
    audit the accuracy cost of the picked wire — the per-dtype bounds in
    ``communicators.quant`` hold regardless of what is picked."""
    from chainermn_tpu.communicators.packing import synthetic_grad_tree
    from chainermn_tpu.communicators.quant import measure_comm_quant_error

    total_bytes = int(total_mb * 1024 * 1024)
    tree = synthetic_grad_tree(n_leaves, total_bytes, dtypes=(dtype,))
    total_bytes = sum(
        l.size * l.dtype.itemsize for l in jax.tree_util.tree_leaves(tree)
    )
    space = comm_dtype_search_space()
    default_cfg = {"comm_dtype": "none"}
    key = comm_dtype_cache_key(
        device_kind(), dtype, total_bytes, n_leaves, communicator
    )
    if dry_run:
        return {"kernel": "comm_dtype", "dry_run": True, "key": key,
                "candidates": space, "default": default_cfg}
    _require_tuning_allowed("gradient wire dtype")
    cache = cache or shared_cache()
    cached = cache.get(key) if not force else None
    if cached and cached.get("comm_dtype"):
        return {"kernel": "comm_dtype", "key": key, "cached": True,
                "chosen": {"comm_dtype": str(cached["comm_dtype"])}}

    from chainermn_tpu.communicators import create_communicator
    from chainermn_tpu.utils.profiling import sync

    n = None
    errs: dict = {}
    if log:
        log(f"comm_dtype {key}: {len(space)} candidates")

    def build(cfg):
        nonlocal n
        comm = create_communicator(
            communicator, mesh=mesh, comm_dtype=cfg["comm_dtype"]
        )
        n = comm.device_size
        if cfg["comm_dtype"] != "none":
            errs[cfg["comm_dtype"]] = measure_comm_quant_error(
                comm, tree, publish=False
            )
        stacked = jax.tree_util.tree_map(
            lambda l: jax.numpy.stack([jax.numpy.asarray(l)] * n), tree
        )

        def run(k):
            t0 = time.perf_counter()
            out = stacked
            for _ in range(k):
                out = comm.eager_allreduce_grad(out)
            sync(jax.tree_util.tree_leaves(out)[0])
            return time.perf_counter() - t0

        return run

    results = measure_candidates(build, space, n1=n1, repeats=repeats,
                                 log=log)
    rec = _finish(
        key, results, default_cfg, cache,
        {"kernel": "comm_dtype", "dtype": dtype_name(dtype),
         "communicator": communicator, "total_bytes": total_bytes,
         "n_leaves": n_leaves, "device_size": n,
         "max_abs_err": errs},
    )
    rec["kernel"] = "comm_dtype"
    rec["max_abs_err"] = errs
    return rec


def tune_kv_dtype(
    *,
    n_pages: int,
    page_size: int,
    n_kv: int,
    d_head: int,
    n_heads: Optional[int] = None,
    batch: int = 8,
    dtype="bfloat16",
    cache: Optional[TuneCache] = None,
    n1: int = 3,
    repeats: int = 3,
    force: bool = False,
    dry_run: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune the KV page storage dtype for one page geometry.

    Times :func:`~chainermn_tpu.ops.paged_attention_decode` over a full
    table at the model dtype and at each quantized page dtype (int8
    pages + fp32 scale gather + in-kernel dequant), persisting the
    argmin under the key the serving engine's ``kv_dtype`` resolution
    reads back on TPU.  Note the timing captures the dequant overhead
    but not the capacity win — int8 pages halve pool bytes per token
    (docs/serving.md), which is why an operator may pin ``int8`` even
    when the step time ties."""
    import numpy as np

    space = kv_dtype_search_space()
    default_cfg = {"kv_dtype": "none"}
    key = kv_dtype_cache_key(
        device_kind(), dtype, n_pages, page_size, n_kv, d_head
    )
    if dry_run:
        return {"kernel": "kv_dtype", "dry_run": True, "key": key,
                "candidates": space, "default": default_cfg}
    _require_tuning_allowed("KV page dtype")
    cache = cache or shared_cache()
    cached = cache.get(key) if not force else None
    if cached and cached.get("kv_dtype"):
        return {"kernel": "kv_dtype", "key": key, "cached": True,
                "chosen": {"kv_dtype": str(cached["kv_dtype"])}}

    from chainermn_tpu.communicators.quant import quantize_kv
    from chainermn_tpu.ops.decode_attention import paged_attention_decode
    from chainermn_tpu.utils.profiling import sync

    H = n_heads or n_kv
    W = n_pages // max(1, batch)
    rng = np.random.RandomState(0)
    dt = dtype_name(dtype)
    q = jax.numpy.asarray(rng.randn(batch, 1, H, d_head), dt)
    kv_f = jax.numpy.asarray(rng.randn(n_pages, page_size, n_kv, d_head), dt)
    vv_f = jax.numpy.asarray(rng.randn(n_pages, page_size, n_kv, d_head), dt)
    kv_q, kv_s = quantize_kv(kv_f)
    vv_q, vv_s = quantize_kv(vv_f)
    tables = jax.numpy.asarray(
        rng.permutation(n_pages)[: batch * W].reshape(batch, W), "int32"
    )
    lens = jax.numpy.full((batch,), W * page_size, "int32")
    if log:
        log(f"kv_dtype {key}: {len(space)} candidates")

    def build(cfg):
        quantized = cfg["kv_dtype"] != "none"
        kp, vp = (kv_q, vv_q) if quantized else (kv_f, vv_f)
        ks, vs = (kv_s, vv_s) if quantized else (None, None)
        f = jax.jit(
            lambda q, kp, vp, t, sl: paged_attention_decode(
                q, kp, vp, t, sl, k_scales=ks, v_scales=vs
            )
        )

        def run(n):
            t0 = time.perf_counter()
            for _ in range(n):
                o = f(q, kp, vp, tables, lens)
            sync(o)
            return time.perf_counter() - t0

        return run

    results = measure_candidates(build, space, n1=n1, repeats=repeats,
                                 log=log)
    rec = _finish(
        key, results, default_cfg, cache,
        {"kernel": "kv_dtype", "dtype": dt, "n_pages": n_pages,
         "page_size": page_size, "n_kv": n_kv, "d_head": d_head,
         "batch": batch},
    )
    rec["kernel"] = "kv_dtype"
    return rec


def _serve_model_and_engine_factory(vocab, d_model, n_heads, d_ff,
                                    n_layers, max_len, dtype,
                                    block_size, n_blocks, max_batch):
    """One target LM + init params, and a factory building a fresh
    serving engine over them per candidate config — shared scaffolding
    for the serving-loop tuners."""
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving.engine import EngineConfig, InferenceEngine

    dt = getattr(jnp, dtype_name(dtype))
    lm = TransformerLM(vocab=vocab, d_model=d_model, n_heads=n_heads,
                       d_ff=d_ff, n_layers=n_layers, max_len=max_len,
                       dtype=dt)
    params = lm.init(jax.random.PRNGKey(0),
                     jnp.zeros((1, 8), jnp.int32))

    def make_engine(**cfg_overrides):
        cfg = EngineConfig(block_size=block_size, n_blocks=n_blocks,
                           max_len=max_len, max_batch=max_batch,
                           **cfg_overrides)
        return InferenceEngine(lm, params, cfg)

    return lm, np.random.RandomState(0), make_engine


def tune_draft(
    *,
    vocab: int = 8192,
    d_model: int = 1024,
    n_heads: int = 8,
    d_ff: int = 4096,
    n_layers: int = 8,
    max_len: int = 512,
    block_size: int = 16,
    n_blocks: int = 256,
    batch: int = 4,
    prompt_len: int = 64,
    max_new: int = 32,
    spec_tokens: int = 4,
    dtype="bfloat16",
    cache: Optional[TuneCache] = None,
    n1: int = 1,
    repeats: int = 3,
    force: bool = False,
    dry_run: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune the speculative draft source for one target model family.

    Times a fixed continuous-batching workload (``batch`` requests,
    ``spec_tokens``-deep speculation) to completion under each draft
    config — n-gram prompt lookup versus the layer-truncated self-draft
    at each candidate depth — and persists the fastest.  Stream content
    is identical across candidates by the exact-match acceptance
    invariant, so wall time per workload is the whole story: the draft
    choice trades proposal cost against accepted tokens per verify."""
    from chainermn_tpu.serving.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )

    space = draft_search_space(n_layers)
    default_cfg = dict(space[0])
    key = draft_cache_key(
        device_kind(), dtype, vocab, d_model, n_layers, max_len
    )
    if dry_run:
        return {"kernel": "draft", "dry_run": True, "key": key,
                "candidates": space, "default": default_cfg}
    _require_tuning_allowed("speculative draft source")
    cache = cache or shared_cache()
    cached = cache.get(key) if not force else None
    if cached and cached.get("draft"):
        return {"kernel": "draft", "key": key, "cached": True,
                "chosen": {"draft": str(cached["draft"]),
                           "draft_layers": int(cached.get(
                               "draft_layers", 0))}}

    lm, rng, make_engine = _serve_model_and_engine_factory(
        vocab, d_model, n_heads, d_ff, n_layers, max_len, dtype,
        block_size, n_blocks, batch,
    )
    prompts = [
        list(rng.randint(1, vocab, size=prompt_len).astype(int))
        for _ in range(batch)
    ]
    if log:
        log(f"draft {key}: {len(space)} candidates")

    def build(cfg):
        engine = make_engine(
            draft=cfg["draft"],
            draft_layers=(cfg["draft_layers"]
                          if cfg["draft"] == "model" else None),
        )

        def run(n):
            t0 = time.perf_counter()
            for _ in range(n):
                sched = ContinuousBatchingScheduler(
                    engine, spec_tokens=spec_tokens)
                for i, p in enumerate(prompts):
                    sched.add_request(Request(
                        request_id=i, prompt=list(p),
                        max_new_tokens=max_new))
                sched.run_to_completion()
            return time.perf_counter() - t0

        return run

    results = measure_candidates(build, space, n1=n1, repeats=repeats,
                                 log=log)
    rec = _finish(
        key, results, default_cfg, cache,
        {"kernel": "draft", "dtype": dtype_name(dtype), "vocab": vocab,
         "d_model": d_model, "n_layers": n_layers, "max_len": max_len,
         "batch": batch, "prompt_len": prompt_len, "max_new": max_new,
         "spec_tokens": spec_tokens},
    )
    rec["kernel"] = "draft"
    return rec


def tune_prefill_chunk(
    *,
    max_len: int = 512,
    block_size: int = 16,
    vocab: int = 8192,
    d_model: int = 1024,
    n_heads: int = 8,
    d_ff: int = 4096,
    n_layers: int = 8,
    n_blocks: int = 256,
    decode_batch: int = 3,
    max_new: int = 24,
    long_context: bool = False,
    dtype="bfloat16",
    cache: Optional[TuneCache] = None,
    n1: int = 1,
    repeats: int = 3,
    force: bool = False,
    dry_run: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune the chunked-prefill slice size for one page geometry.

    Unlike the throughput tuners, the metric here is the workload's
    *worst decode stall*: ``decode_batch`` short requests stream while
    one near-budget prompt arrives mid-flight, and ``run(n)`` returns
    the summed maximum scheduler-step wall time across ``n`` workload
    repetitions.  Monolithic prefill (0) charges the whole long prompt
    to one step — the decode p99 spike chunked prefill exists to bound
    — so the argmin lands on the slice size whose per-step cost hides
    best behind the decode cadence.  Throughput is deliberately NOT the
    objective: chunking always costs a little of it.

    With ``long_context`` the same objective reruns at the long-context
    bucket: the context budget doubles, the engine's seed ladder stops
    at the BASE budget, and the long arrival crosses it via lazy bucket
    growth — so the argmin reflects per-step cost at the GROWN bucket,
    where a slice that hid fine at the base budget can stall decode
    (attention over the longer context makes every slice step dearer).
    The growth recompiles themselves are one-time and warmed out by the
    measurement harness; the leg has its own cache key, so base and
    long-context slice sizes tune independently."""
    from chainermn_tpu.serving.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )

    ctx = int(max_len) * 2 if long_context else int(max_len)
    space = prefill_chunk_search_space(max_len, block_size)
    default_cfg = dict(space[0])
    key = prefill_chunk_cache_key(device_kind(), ctx, block_size)
    if dry_run:
        return {"kernel": "prefill_chunk", "dry_run": True, "key": key,
                "candidates": space, "default": default_cfg}
    _require_tuning_allowed("chunked-prefill slice size")
    cache = cache or shared_cache()
    cached = cache.get(key) if not force else None
    if cached and cached.get("prefill_chunk") is not None:
        return {"kernel": "prefill_chunk", "key": key, "cached": True,
                "chosen": {"prefill_chunk": int(
                    cached["prefill_chunk"])}}

    lm, rng, make_engine = _serve_model_and_engine_factory(
        vocab, d_model, n_heads, d_ff, n_layers, ctx, dtype,
        block_size, n_blocks, decode_batch + 1,
    )
    short_len = max(block_size, max_len // 16)
    long_len = ctx - max_new - 1
    shorts = [
        list(rng.randint(1, vocab, size=short_len).astype(int))
        for _ in range(decode_batch)
    ]
    long_prompt = list(rng.randint(1, vocab, size=long_len).astype(int))
    if log:
        log(f"prefill_chunk {key}: {len(space)} candidates "
            f"(long prompt {long_len} tok"
            + (", crosses the seed ladder" if long_context else "")
            + ")")

    def build(cfg):
        over = {"prefill_chunk": int(cfg["prefill_chunk"])}
        if long_context:
            # Seed ladder stops at the BASE budget; the long arrival
            # must grow past it, so measured stalls are at the grown
            # bucket (run(1) warms the growth compiles away).
            over["prefill_buckets"] = (int(max_len),)
        engine = make_engine(**over)

        def run(n):
            total = 0.0
            for _ in range(n):
                sched = ContinuousBatchingScheduler(engine)
                for i, p in enumerate(shorts):
                    sched.add_request(Request(
                        request_id=i, prompt=list(p),
                        max_new_tokens=max_new))
                # warm the decode cadence before the long arrival
                for _ in range(2):
                    sched.step()
                sched.add_request(Request(
                    request_id=len(shorts), prompt=list(long_prompt),
                    max_new_tokens=4))
                worst = 0.0
                while sched.has_work:
                    t0 = time.perf_counter()
                    sched.step()
                    worst = max(worst, time.perf_counter() - t0)
                total += worst
            return total

        return run

    results = measure_candidates(build, space, n1=n1, repeats=repeats,
                                 log=log)
    rec = _finish(
        key, results, default_cfg, cache,
        {"kernel": "prefill_chunk", "dtype": dtype_name(dtype),
         "max_len": ctx, "block_size": block_size,
         "decode_batch": decode_batch, "long_len": long_len,
         "long_context": bool(long_context),
         "metric": "sum of worst per-step wall time per workload"},
    )
    rec["kernel"] = "prefill_chunk"
    return rec


def tune_serve_group(
    *,
    vocab: int = 8192,
    d_model: int = 1024,
    n_heads: int = 8,
    d_ff: int = 4096,
    n_layers: int = 8,
    max_len: int = 512,
    block_size: int = 16,
    n_blocks: int = 256,
    batch: int = 4,
    prompt_len: int = 64,
    max_new: int = 24,
    dtype="bfloat16",
    cache: Optional[TuneCache] = None,
    n1: int = 1,
    repeats: int = 3,
    force: bool = False,
    dry_run: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune the serving shard-group SHAPE for one target model family:
    tensor-parallel group size (registry ``tp`` plan over that many
    local devices) crossed with pipeline microbatch depth for the
    decode step.  A fixed continuous-batching workload runs to
    completion under each candidate; streams are bit-identical across
    the whole space (per-sequence attention + counter-based sampling +
    contiguous microbatch splits), so wall time per workload is the
    entire objective — group shape is a pure throughput decision, like
    the draft source.  The persisted argmin is what ``tools.serve`` and
    the router would spend a whole shard group of processes on, priced
    here on one process's local devices before committing the fleet."""
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.serving.engine import EngineConfig, InferenceEngine
    from chainermn_tpu.serving.scheduler import (
        ContinuousBatchingScheduler,
        Request,
    )

    n_devices = len(jax.devices())
    space = serve_group_search_space(n_heads, d_ff, d_model,
                                     n_devices, batch)
    default_cfg = dict(space[0])
    key = serve_group_cache_key(
        device_kind(), dtype, vocab, d_model, n_layers, max_len,
        n_devices, batch,
    )
    if dry_run:
        return {"kernel": "serve_group", "dry_run": True, "key": key,
                "candidates": space, "default": default_cfg}
    _require_tuning_allowed("serving shard-group shape")
    cache = cache or shared_cache()
    cached = cache.get(key) if not force else None
    if cached and cached.get("group_size"):
        return {"kernel": "serve_group", "key": key, "cached": True,
                "chosen": {"group_size": int(cached["group_size"]),
                           "pp_stages": int(cached.get(
                               "pp_stages", 1))}}

    dt = getattr(jnp, dtype_name(dtype))
    lm = TransformerLM(vocab=vocab, d_model=d_model, n_heads=n_heads,
                       d_ff=d_ff, n_layers=n_layers, max_len=max_len,
                       dtype=dt)
    params = lm.init(jax.random.PRNGKey(0),
                     jnp.zeros((1, 8), jnp.int32))
    rng = np.random.RandomState(0)
    prompts = [
        list(rng.randint(1, vocab, size=prompt_len).astype(int))
        for _ in range(batch)
    ]
    if log:
        log(f"serve_group {key}: {len(space)} candidates "
            f"({n_devices} local devices)")

    def build(cfg):
        plan = mesh = None
        if cfg["group_size"] > 1:
            from jax.sharding import Mesh

            plan = "tp"
            mesh = Mesh(
                np.asarray(jax.devices()[: cfg["group_size"]]),
                ("model",),
            )
        ecfg = EngineConfig(block_size=block_size, n_blocks=n_blocks,
                            max_len=max_len, max_batch=batch)
        engine = InferenceEngine(lm, params, ecfg, plan=plan, mesh=mesh)
        engine.pp_stages = int(cfg["pp_stages"])

        def run(n):
            t0 = time.perf_counter()
            for _ in range(n):
                sched = ContinuousBatchingScheduler(engine)
                for i, p in enumerate(prompts):
                    sched.add_request(Request(
                        request_id=i, prompt=list(p),
                        max_new_tokens=max_new))
                while sched.has_work:
                    sched.step()
            return time.perf_counter() - t0

        return run

    results = measure_candidates(build, space, n1=n1, repeats=repeats,
                                 log=log)
    rec = _finish(
        key, results, default_cfg, cache,
        {"kernel": "serve_group", "dtype": dtype_name(dtype),
         "vocab": vocab, "d_model": d_model, "n_layers": n_layers,
         "max_len": max_len, "batch": batch,
         "n_devices": n_devices},
    )
    rec["kernel"] = "serve_group"
    return rec


def tune_layout(
    *,
    mesh,
    batch: int = 8,
    seq: int = 64,
    vocab: int = 256,
    d_model: int = 64,
    n_heads: int = 4,
    d_ff: int = 256,
    n_layers: int = 2,
    dtype="bfloat16",
    data_axis: str = "data",
    model: str = "transformer_lm",
    cache: Optional[TuneCache] = None,
    n1: int = 2,
    repeats: int = 3,
    force: bool = False,
    dry_run: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune the parameter LAYOUT itself: time one gspmd train step per
    registry sharding plan valid for ``mesh`` (dp replicate vs tp vs
    fsdp vs zero vs dp_tp — whatever validates against the model) and
    persist the argmin plan name.  The search space is the plan
    registry, so a plan added by user code is automatically a candidate
    the next tuning run; ``dp`` (today's hand-picked layout) is the
    default the winner must beat.  ``mesh`` must carry ``data_axis``
    (the batch always shards over it)."""
    import jax.numpy as jnp
    import numpy as np
    import optax

    from chainermn_tpu.models.transformer import TransformerLM
    from chainermn_tpu.parallel.sharding import make_gspmd_train_step
    from chainermn_tpu.sharding import get_plan

    if data_axis not in mesh.axis_names:
        raise ValueError(
            f"mesh has no {data_axis!r} axis (axes: "
            f"{tuple(mesh.axis_names)}) — the layout tuner's batch "
            "always shards over the data axis"
        )
    dt = jnp.bfloat16 if dtype_name(dtype) == "bfloat16" else jnp.float32
    lm = TransformerLM(
        vocab=vocab, d_model=d_model, n_heads=n_heads, d_ff=d_ff,
        n_layers=n_layers, max_len=seq, dtype=dt,
    )
    tokens = jax.numpy.asarray(
        np.random.RandomState(0).randint(0, vocab, (batch, seq)), "int32"
    )
    params = lm.init(jax.random.PRNGKey(0), tokens)["params"]
    # Host copies: the plan-driven step donates its param/moment buffers,
    # and device_put may alias an on-device input's buffer into the
    # placed tree — numpy leaves guarantee every candidate starts from
    # fresh device arrays no earlier candidate could have donated away.
    params = jax.tree.map(np.asarray, params)
    leaves = jax.tree_util.tree_leaves(params)
    n_params = int(sum(leaf.size for leaf in leaves))

    space = layout_search_space(mesh.axis_names, params, mesh)
    default_cfg = {"plan": "dp"}
    key = layout_cache_key(
        device_kind(), dtype, n_params, len(leaves),
        tuple(mesh.devices.shape), model,
    )
    if dry_run:
        return {"kernel": "layout", "dry_run": True, "key": key,
                "candidates": space, "default": default_cfg}
    _require_tuning_allowed("sharding-plan layout")
    cache = cache or shared_cache()
    cached = cache.get(key) if not force else None
    if cached and cached.get("plan"):
        return {"kernel": "layout", "key": key, "cached": True,
                "chosen": {"plan": str(cached["plan"])}}

    from chainermn_tpu.utils.profiling import sync

    opt = optax.adam(1e-3)

    def loss_fn(p, batch_tokens):
        logits = lm.apply({"params": p}, batch_tokens)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        tgt = jnp.roll(batch_tokens, -1, axis=1)
        return -jnp.mean(
            jnp.take_along_axis(logp, tgt[..., None], axis=-1)
        )

    if log:
        log(f"layout {key}: {len(space)} candidate plan(s): "
            f"{[c['plan'] for c in space]}")

    def build(cfg):
        plan = get_plan(cfg["plan"])
        step, shard_fn = make_gspmd_train_step(
            loss_fn, opt, mesh, plan, data_axis=data_axis
        )
        p, s = shard_fn(params, opt.init(params))
        holder = {"p": p, "s": s}

        def run(n):
            t0 = time.perf_counter()
            for _ in range(n):
                holder["p"], holder["s"], loss = step(
                    holder["p"], holder["s"], tokens
                )
            sync(loss)
            return time.perf_counter() - t0

        return run

    results = measure_candidates(build, space, n1=n1, repeats=repeats,
                                 log=log)
    rec = _finish(
        key, results, default_cfg, cache,
        {"kernel": "layout", "dtype": dtype_name(dtype), "model": model,
         "mesh_shape": list(int(s) for s in mesh.devices.shape),
         "mesh_axes": list(mesh.axis_names), "n_params": n_params,
         "n_leaves": len(leaves), "batch": batch, "seq": seq},
    )
    rec["kernel"] = "layout"
    return rec


def tune_lm_shapes(
    *,
    batch: int,
    seq: int,
    n_heads: int,
    d_model: int,
    vocab: int,
    window: Optional[int] = None,
    dtype="bfloat16",
    cache: Optional[TuneCache] = None,
    force: bool = False,
    dry_run: bool = False,
    n1: int = 3,
    repeats: int = 3,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Tune every searched kernel the LM bench step hits — the flash
    fwd/bwd geometry at the step's (batch*heads, S, head_dim) and the CE
    chunk at its (batch*S, vocab, d_model).  This is what
    ``bench.py --autotune`` and the CLI's default mode call."""
    if d_model % n_heads:
        raise ValueError(f"d_model {d_model} not divisible by heads {n_heads}")
    flash = tune_flash(
        Sq=seq, Sk=seq, D=d_model // n_heads, dtype=dtype, causal=True,
        window=window, batch_heads=batch * n_heads, cache=cache,
        force=force, dry_run=dry_run, n1=n1, repeats=repeats, log=log,
    )
    ce = tune_fused_ce(
        N=batch * seq, V=vocab, D=d_model, dtype=dtype, cache=cache,
        force=force, dry_run=dry_run, n1=n1, repeats=repeats, log=log,
    )
    return {"flash": flash, "fused_ce": ce}
