"""Global exception hook — failure containment.

Reference: REF:chainermn/global_except_hook.py — monkey-patches
``sys.excepthook`` so an uncaught exception on any rank flushes stderr and
calls ``MPI_Abort(MPI_COMM_WORLD)``, killing the whole job loudly instead
of leaving peers deadlocked in a collective (SURVEY §5.3).

TPU-native translation: there is no MPI_Abort; the job-wide kill comes from
the fact that a vanished process stalls its peers' next DCN collective
until the coordinator's missed-heartbeat timeout tears the job down.  The
hook's value is (a) making the *failing* host exit immediately and loudly
with its process index in the banner (so the culprit is identifiable in a
pile of timeout logs), and (b) using ``os._exit`` so no atexit/finalizer
can hang the teardown — the same "die loudly, never deadlock" contract.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

_hook_installed = False
_EXIT_CODE = 13  # distinct from interpreter default 1: "killed by crash barrier"
_current_step = None


def set_current_step(step) -> None:
    """Best-effort step bookmark for the crash postmortem row (the
    elastic runtime calls this from ``ElasticContext.beat``)."""
    global _current_step
    _current_step = int(step)


def _safe_rank():
    """Process rank WITHOUT initializing a backend: the barrier must never
    block (backend init can wait on a device claim — the exact hang this
    hook exists to prevent). Reports -1/-1 unless jax is already live."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return -1, -1
    try:
        from jax._src import xla_bridge as xb

        if not getattr(xb, "_backends", None):
            return -1, -1
        return jax.process_index(), jax.process_count()
    except Exception:
        return -1, -1


def _write_postmortem(rank, size, exc_type, exc_value, exc_traceback):
    """Append one crash row — who, which step, what traceback — before
    the process vanishes, so supervisor postmortems can name the
    culprit.  Two sinks, each best-effort and each using the
    torn-tail-tolerant O_APPEND JSONL contract of the step log:

    * the process's installed :class:`StepRecorder`, when one is live;
    * the file ``CHAINERMN_TPU_POSTMORTEM_FILE`` points at (the elastic
      supervisor sets it for every rank it spawns).

    Never raises: a failing postmortem must not mask the crash exit."""
    if rank < 0:
        # Backend not live — the elastic env still names us.
        rank = int(os.environ.get("CHAINERMN_TPU_ELASTIC_RANK", -1))
    tb = "".join(
        traceback.format_exception(exc_type, exc_value, exc_traceback)
    )[-8000:]
    row = {
        "event": "crash", "rank": rank, "size": size,
        "step": _current_step, "t": time.time(),
        "exc": f"{exc_type.__name__}: {exc_value}", "traceback": tb,
    }
    try:
        from chainermn_tpu.observability.step_log import current_recorder

        rec = current_recorder()
        if rec is not None:
            rec.record("crash", rank=rank, size=size, step=_current_step,
                       exc=row["exc"], traceback=tb)
    except Exception:
        pass
    try:
        path = os.environ.get("CHAINERMN_TPU_POSTMORTEM_FILE")
        if path:
            line = (json.dumps(row) + "\n").encode("utf-8")
            fd = os.open(
                path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644
            )
            try:
                os.write(fd, line)
            finally:
                os.close(fd)
    except Exception:
        pass


def _handle_uncaught(exc_type, exc_value, exc_traceback):
    rank, size = _safe_rank()
    sys.stderr.write(
        "\n*****************************************************\n"
        f"chainermn_tpu: uncaught exception on process {rank}/{size};\n"
        "aborting this host so peers fail fast instead of hanging\n"
        "in a collective.\n"
        "*****************************************************\n"
    )
    traceback.print_exception(exc_type, exc_value, exc_traceback)
    try:
        _write_postmortem(rank, size, exc_type, exc_value, exc_traceback)
    except Exception:
        pass
    sys.stderr.flush()
    sys.stdout.flush()
    os._exit(_EXIT_CODE)


def add_hook():
    """Install the crash barrier (reference: ``_add_hook_if_enabled``;
    idempotent)."""
    global _hook_installed
    if not _hook_installed:
        sys.excepthook = _handle_uncaught
        _hook_installed = True


def remove_hook():
    global _hook_installed
    sys.excepthook = sys.__excepthook__
    _hook_installed = False
