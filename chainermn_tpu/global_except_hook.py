"""Global exception hook — failure containment.

Reference: REF:chainermn/global_except_hook.py — monkey-patches
``sys.excepthook`` so an uncaught exception on any rank flushes stderr and
calls ``MPI_Abort(MPI_COMM_WORLD)``, killing the whole job loudly instead
of leaving peers deadlocked in a collective (SURVEY §5.3).

TPU-native translation: there is no MPI_Abort; the job-wide kill comes from
the fact that a vanished process stalls its peers' next DCN collective
until the coordinator's missed-heartbeat timeout tears the job down.  The
hook's value is (a) making the *failing* host exit immediately and loudly
with its process index in the banner (so the culprit is identifiable in a
pile of timeout logs), and (b) using ``os._exit`` so no atexit/finalizer
can hang the teardown — the same "die loudly, never deadlock" contract.
"""

from __future__ import annotations

import os
import sys
import traceback

_hook_installed = False
_EXIT_CODE = 13  # distinct from interpreter default 1: "killed by crash barrier"


def _safe_rank():
    """Process rank WITHOUT initializing a backend: the barrier must never
    block (backend init can wait on a device claim — the exact hang this
    hook exists to prevent). Reports -1/-1 unless jax is already live."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return -1, -1
    try:
        from jax._src import xla_bridge as xb

        if not getattr(xb, "_backends", None):
            return -1, -1
        return jax.process_index(), jax.process_count()
    except Exception:
        return -1, -1


def _handle_uncaught(exc_type, exc_value, exc_traceback):
    rank, size = _safe_rank()
    sys.stderr.write(
        "\n*****************************************************\n"
        f"chainermn_tpu: uncaught exception on process {rank}/{size};\n"
        "aborting this host so peers fail fast instead of hanging\n"
        "in a collective.\n"
        "*****************************************************\n"
    )
    traceback.print_exception(exc_type, exc_value, exc_traceback)
    sys.stderr.flush()
    sys.stdout.flush()
    os._exit(_EXIT_CODE)


def add_hook():
    """Install the crash barrier (reference: ``_add_hook_if_enabled``;
    idempotent)."""
    global _hook_installed
    if not _hook_installed:
        sys.excepthook = _handle_uncaught
        _hook_installed = True


def remove_hook():
    global _hook_installed
    sys.excepthook = sys.__excepthook__
    _hook_installed = False
