"""Fabric arbiter: the actuator that moves chips between planes.

The arbiter owns a :class:`~chainermn_tpu.fabric.ledger.ChipLedger`
and drives the two planes through surfaces they already expose:

* **training** — a trainer handle (the elastic supervisor, or any
  duck-typed stand-in) with ``world``/``active`` and
  ``yield_ranks``/``grant_ranks``.  Shrinking rides the EXISTING
  preemption path end-to-end: the supervisor SIGTERMs live ranks, each
  worker's ``check_preemption`` agrees host-plane, saves a blocking
  checkpoint, and exits 75; the supervisor classifies the wave as a
  preemption (never against ``max_restarts``) and respawns at the new
  world size, where ``maybe_load`` resumes bit-exactly.
* **serving** — the :class:`~chainermn_tpu.serving.cluster.autoscaler.
  Autoscaler`'s granted-capacity surface (``grant_capacity`` /
  ``yield_capacity`` / ``on_retire``) plus ``force_drain`` for the
  graceful drain → migrate → retire sequence that drops zero streams.

Transitions are asynchronous — a preemption takes a full
checkpoint/respawn round-trip — so the arbiter runs one transition at
a time as a small pending-state machine, re-cutting ledger leases only
when the plane has actually reached its target shape.  Chips are
therefore never double-counted: they stay on the old lease until the
old holder is provably gone.

Dead replicas are reconciled before anything else each step: a leased
replica that vanished (SIGKILL) hands its lease to the autoscaler's
backfill twin if one is up, else the chips return to the free pool.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from chainermn_tpu.fabric.ledger import ChipLedger
from chainermn_tpu.fabric.policy import FabricPolicy, FabricPolicyConfig
from chainermn_tpu.serving.cluster.health import scale_signals


class TrainerHandle:
    """Adapter giving the arbiter its duck-typed view of a training
    plane: ``world`` (current rank count), ``active`` (still running),
    ``yield_ranks(k)`` / ``grant_ranks(k)``.  Wraps an
    ``ElasticSupervisor``; tests pass any object with the same four
    names directly instead."""

    def __init__(self, supervisor):
        self._sup = supervisor

    @property
    def world(self) -> int:
        return self._sup.world

    @property
    def active(self) -> bool:
        return bool(self._sup.running)

    def yield_ranks(self, k: int) -> bool:
        return self._sup.yield_ranks(k)

    def grant_ranks(self, k: int) -> bool:
        return self._sup.grant_ranks(k)


class FabricArbiter:
    """One control loop brokering chips between training and serving.

    Call :meth:`bootstrap` once after both planes are up, then
    :meth:`step` from the same pump that steps the router and the
    autoscaler.  Decisions land in :attr:`events`; transition counts in
    :attr:`transitions`; gauges under ``fabric/*``.
    """

    def __init__(
        self,
        ledger: ChipLedger,
        trainer,
        autoscaler,
        policy: Optional[FabricPolicy] = None,
        reporter=None,
        anomaly=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.ledger = ledger
        self.trainer = trainer
        self.autoscaler = autoscaler
        self.router = autoscaler.router
        self.policy = policy or FabricPolicy(FabricPolicyConfig(),
                                             clock=clock)
        self.reporter = reporter
        self.anomaly = anomaly
        self.clock = clock
        self._train_lease: Optional[str] = None
        self._replica_leases: Dict[Any, str] = {}
        self._pending: Optional[Dict[str, Any]] = None
        self.events: List[dict] = []
        self.transitions = {
            "grant_free": 0,
            "preempt_for_serving": 0,
            "return_to_training": 0,
        }

    # -- wiring --------------------------------------------------------

    def bootstrap(self) -> None:
        """Grant the initial leases covering the planes as they stand
        and take over the autoscaler's growth ceiling."""
        cfg = self.policy.config
        if self.trainer.active and self.trainer.world > 0:
            lease = self.ledger.grant(
                "train", self.trainer.world * cfg.chips_per_rank,
                reason="bootstrap",
            )
            self._train_lease = lease.lease_id
        alive = [
            rid for rid in sorted(self.router.replicas, key=repr)
            if self.router.replicas[rid].alive
        ]
        for rid in alive:
            lease = self.ledger.grant(
                "serve", cfg.chips_per_replica,
                reason="bootstrap:%s" % rid,
            )
            self._replica_leases[rid] = lease.lease_id
        self.autoscaler.set_capacity(len(alive))
        self.autoscaler.on_retire = self._note_retire
        self._event("bootstrap", self.clock(),
                    train_ranks=self.trainer.world, replicas=len(alive))

    def _note_retire(self, rid) -> None:
        """Autoscaler callback: a drained replica fully retired — its
        chips go back to the free pool and the ceiling drops."""
        lease_id = self._replica_leases.pop(rid, None)
        if lease_id is not None:
            self.ledger.release(lease_id, reason="retire:%s" % rid)
        self.autoscaler.yield_capacity(1)

    # -- bookkeeping ---------------------------------------------------

    def _event(self, action: str, now: float, **extra) -> dict:
        ev = {"action": action, "t": now, **extra}
        self.events.append(ev)
        if self.reporter is not None:
            self.reporter.count("fabric/%s" % action, 1)
        return ev

    def _alive_replicas(self) -> List[Any]:
        return [
            rid for rid in sorted(self.router.replicas, key=repr)
            if self.router.replicas[rid].alive
        ]

    def _reconcile_dead(self, now: float) -> None:
        """A leased replica that vanished (chaos SIGKILL) must not
        strand chips.  Prefer moving the lease onto an unleased alive
        replica — the autoscaler's emergency backfill twin — so custody
        follows capacity; otherwise the chips return to free and the
        ceiling drops."""
        alive = self._alive_replicas()
        unleased = [r for r in alive if r not in self._replica_leases]
        for rid in sorted(self._replica_leases, key=repr):
            if rid in alive:
                continue
            lease_id = self._replica_leases.pop(rid)
            if unleased:
                twin = unleased.pop(0)
                self._replica_leases[twin] = lease_id
                self._event("lease_transfer", now,
                            lease=lease_id, dead=rid, to=twin)
            else:
                self.ledger.release(lease_id,
                                    reason="replica_dead:%s" % rid)
                self.autoscaler.yield_capacity(1)
                self._event("lease_reclaim", now,
                            lease=lease_id, dead=rid)

    def _recut_train_lease(self, reason: str) -> None:
        """Re-issue the training lease at the trainer's current world
        size (or release it entirely when training finished)."""
        cfg = self.policy.config
        if self._train_lease is not None:
            self.ledger.release(self._train_lease, reason=reason)
            self._train_lease = None
        if self.trainer.active and self.trainer.world > 0:
            lease = self.ledger.grant(
                "train", self.trainer.world * cfg.chips_per_rank,
                reason=reason,
            )
            self._train_lease = lease.lease_id

    def _grant_serve_replicas(self, n: int, now: float,
                              reason: str) -> List[Any]:
        cfg = self.policy.config
        n = min(int(n), self.ledger.free // max(1, cfg.chips_per_replica))
        if n <= 0:
            return []
        rids = self.autoscaler.grant_capacity(n, now=now, reason=reason)
        for rid in rids:
            lease = self.ledger.grant(
                "serve", cfg.chips_per_replica,
                reason="%s:%s" % (reason, rid),
            )
            self._replica_leases[rid] = lease.lease_id
        return rids

    # -- control loop --------------------------------------------------

    def step(self, now: Optional[float] = None) -> Optional[dict]:
        """One arbitration iteration; returns the event emitted this
        call (None when both planes are left alone)."""
        now = self.clock() if now is None else now
        self._reconcile_dead(now)

        # Training finished on its own: its lease becomes free pool.
        if (not self.trainer.active and self._train_lease is not None
                and self._pending is None):
            self.ledger.release(self._train_lease, reason="train_done")
            self._train_lease = None
            self._event("train_done", now)

        self._publish_gauges()

        if self._pending is not None:
            return self._progress_pending(now)
        return self._observe_and_decide(now)

    def _publish_gauges(self) -> None:
        if self.reporter is None:
            return
        self.reporter.gauge("fabric/free_chips", self.ledger.free)
        self.reporter.gauge("fabric/train_chips",
                            self.ledger.held("train"))
        self.reporter.gauge("fabric/serve_chips",
                            self.ledger.held("serve"))
        self.reporter.gauge("fabric/pending",
                            int(self._pending is not None))

    def _progress_pending(self, now: float) -> Optional[dict]:
        p = self._pending
        assert p is not None
        if p["action"] == "preempt_for_serving":
            # Wait for the supervisor to respawn at the shrunk world —
            # chips stay on the old training lease until the old ranks
            # are provably gone (checkpointed + exited 75).
            if self.trainer.active and self.trainer.world != p["target_world"]:
                return None
            self._recut_train_lease("preempt_for_serving")
            rids = self._grant_serve_replicas(
                p["replicas"], now, reason="backfill")
            self._pending = None
            self.transitions["preempt_for_serving"] += 1
            return self._event(
                "preempt_for_serving_done", now,
                train_ranks=self.trainer.world,
                backfill=list(rids),
            )
        if p["action"] == "return_to_training":
            rid = p["replica"]
            if p["stage"] == "drain":
                if rid in self._replica_leases:
                    return None  # still draining/migrating; retire pends
                # Retire (or death-reconcile) returned the chips; now
                # grow training with them.
                cfg = self.policy.config
                k = p["ranks"]
                if (not self.trainer.active
                        or self.ledger.free < k * cfg.chips_per_rank
                        or not self.trainer.grant_ranks(k)):
                    self._pending = None
                    return self._event("return_abandoned", now,
                                       replica=rid)
                p["stage"] = "regrow"
                p["target_world"] = self.trainer.world + k
                return self._event("regrow_start", now,
                                   target_world=p["target_world"])
            # stage == "regrow": wait for the respawn at the grown
            # world, then move the chips onto the training lease.
            if self.trainer.active and self.trainer.world != p["target_world"]:
                return None
            self._recut_train_lease("return_to_training")
            self._pending = None
            self.transitions["return_to_training"] += 1
            return self._event("return_to_training_done", now,
                               train_ranks=self.trainer.world)
        raise AssertionError("unknown pending action %r" % p["action"])

    def _observe_and_decide(self, now: float) -> Optional[dict]:
        c = self.autoscaler.config
        signals = scale_signals(
            self.router.loads(now),
            low_free_frac=c.low_free_frac,
            high_free_frac=c.high_free_frac,
            queue_pressure_frac=c.queue_pressure_frac,
        )
        burn = self.autoscaler._max_burn_rate()
        anomalous = self.anomaly is not None and self.anomaly.alarming()
        action = self.policy.decide(
            signals=signals,
            burn=burn,
            anomalous=anomalous,
            train_ranks=self.trainer.world if self.trainer.active else 0,
            serve_replicas=len(self._alive_replicas()),
            free_chips=self.ledger.free,
            train_active=self.trainer.active,
            now=now,
        )
        if action is None:
            return None
        if action["action"] == "grant_free":
            rids = self._grant_serve_replicas(
                action["replicas"], now, reason="fabric_free")
            if not rids:
                return None
            self.transitions["grant_free"] += 1
            return self._event("grant_free", now,
                               backfill=list(rids))
        if action["action"] == "preempt_for_serving":
            k = action["ranks"]
            target = self.trainer.world - k
            if not self.trainer.yield_ranks(k):
                return None
            cfg = self.policy.config
            self._pending = {
                "action": "preempt_for_serving",
                "target_world": target,
                "replicas": max(1,
                                (k * cfg.chips_per_rank)
                                // max(1, cfg.chips_per_replica)),
            }
            return self._event("preempt_start", now, ranks=k,
                               target_world=target)
        if action["action"] == "return_to_training":
            rid = action["replica"]
            if not self.autoscaler.force_drain(rid, now=now):
                return None
            self._pending = {
                "action": "return_to_training",
                "replica": rid,
                "ranks": action["ranks"],
                "stage": "drain",
            }
            return self._event("drain_start", now, replica=rid,
                               ranks=action["ranks"])
        raise AssertionError("unknown action %r" % action["action"])

    # -- reporting -----------------------------------------------------

    def as_report(self) -> Dict[str, Any]:
        return {
            "transitions": dict(self.transitions),
            "events": list(self.events),
            "pending": dict(self._pending) if self._pending else None,
            "ledger": self.ledger.as_report(),
        }
