"""One resource fabric: training and serving trade TPUs under SLO
pressure.

The fabric sits ABOVE the two planes this repo already grew — the
elastic training supervisor (``chainermn_tpu.elastic``) and the
SLO-guarded serving fleet (``chainermn_tpu.serving.cluster``) — and
brokers chips between them:

* :class:`~chainermn_tpu.fabric.ledger.ChipLedger` — the single source
  of truth for who holds which chips.  Conservation
  (``granted + free == total``) is checked at every event.
* :class:`~chainermn_tpu.fabric.policy.FabricPolicy` — when to move
  chips: debounced serving-pressure votes (reusing the autoscaler's
  ``ScaleSignalFilter`` hysteresis) against per-plane floors/ceilings.
* :class:`~chainermn_tpu.fabric.arbiter.FabricArbiter` — the actuator:
  preempts trainer ranks through the EXISTING SIGTERM-grace-checkpoint
  path and hands the freed chips to the autoscaler as backfill
  replicas; on traffic troughs it drains replicas (drain → migrate →
  retire, zero dropped streams) and returns the chips to training.

Drive both planes in one process tree with
``python -m chainermn_tpu.tools.fabric``; methodology and the lease
lifecycle are in ``docs/fabric.md``.
"""

from chainermn_tpu.fabric.arbiter import FabricArbiter, TrainerHandle
from chainermn_tpu.fabric.ledger import ChipLedger, Lease, LedgerError
from chainermn_tpu.fabric.policy import FabricPolicy, FabricPolicyConfig

__all__ = [
    "ChipLedger",
    "FabricArbiter",
    "FabricPolicy",
    "FabricPolicyConfig",
    "Lease",
    "LedgerError",
    "TrainerHandle",
]
