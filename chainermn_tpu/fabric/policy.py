"""Fabric rebalance policy: when to move chips between planes.

The policy turns the same raw signals the autoscaler already consumes
(``scale_signals`` watermarks, ``slo/burn_rate/*`` gauges, the
``AnomalyDetector`` vote) into *fabric* actions — "take k trainer
ranks for serving" or "give a drained replica's chips back to
training" — debounced through the exact ``ScaleSignalFilter``
hysteresis the autoscaler uses, so the two layers cannot disagree
about what constitutes sustained pressure.

Floors protect each plane from being starved by the other:
``min_train_ranks`` bounds preemption, ``min_serve_replicas`` bounds
drains.  Ceilings (``max_*``, 0 = uncapped) bound growth.  All
decisions are pure functions of the inputs plus filter state — no
wall-clock, no RNG (H005); callers inject ``now``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

from chainermn_tpu.serving.cluster.health import ScaleSignalFilter


@dataclass
class FabricPolicyConfig:
    """Knobs for the chip-rebalance policy.

    ``chips_per_rank`` / ``chips_per_replica`` translate between plane
    units and ledger chips (a TP-group replica spans several chips).
    ``k_spike`` / ``k_trough`` / ``cooldown_s`` feed the shared
    ``ScaleSignalFilter``: a spike vote must persist ``k_spike``
    consecutive polls before chips move toward serving, a trough vote
    (same drain candidate) ``k_trough`` polls before chips move back.
    """

    chips_per_rank: int = 1
    chips_per_replica: int = 1
    min_train_ranks: int = 1
    min_serve_replicas: int = 1
    ranks_per_move: int = 1
    replicas_per_move: int = 1
    k_spike: int = 3
    k_trough: int = 5
    cooldown_s: float = 2.0
    burn_limit: float = 1.0
    max_serve_replicas: int = 0  # 0 = uncapped
    max_train_ranks: int = 0  # 0 = uncapped


class FabricPolicy:
    """Debounced two-plane rebalance decisions.

    :meth:`decide` returns ``None`` (hold) or one action dict:

    * ``{"action": "grant_free", "replicas": r, "chips": c}`` — serving
      pressure and the free pool already covers the growth; no
      preemption needed.
    * ``{"action": "preempt_for_serving", "ranks": k, "chips": c}`` —
      shrink training by ``k`` ranks and move their chips to serving.
    * ``{"action": "return_to_training", "replica": rid, "ranks": k,
      "chips": c}`` — drain replica ``rid`` and grow training.
    """

    def __init__(
        self,
        config: Optional[FabricPolicyConfig] = None,
        clock=time.monotonic,
    ):
        self.config = config or FabricPolicyConfig()
        c = self.config
        self._filter = ScaleSignalFilter(
            k_up=c.k_spike,
            k_down=c.k_trough,
            cooldown_s=c.cooldown_s,
            clock=clock,
        )

    def decide(
        self,
        *,
        signals: Dict[str, Any],
        burn: float,
        anomalous: bool,
        train_ranks: int,
        serve_replicas: int,
        free_chips: int,
        train_active: bool,
        now: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        c = self.config
        pressure = bool(
            signals.get("scale_up") or burn >= c.burn_limit or anomalous
        )
        cand = signals.get("drain_candidate")
        if cand is not None and not signals.get("scale_up"):
            # The watermarks see a provably idle fleet (a drain
            # candidate is only nominated with empty queues, an idle
            # replica, and ample free pages).  Burn-rate gauges freeze
            # at their last value once traffic stops, so a stale peak
            # reading must not pin chips on serving through the trough:
            # live idleness outranks a frozen burn.
            pressure = False
        vote = {
            "scale_up": pressure,
            "drain_candidate": cand,
        }
        decision = self._filter.update(vote, now=now)

        if decision["scale_up"]:
            if (
                c.max_serve_replicas
                and serve_replicas >= c.max_serve_replicas
            ):
                return None
            r = c.replicas_per_move
            need = r * c.chips_per_replica
            if free_chips >= need:
                return {"action": "grant_free", "replicas": r, "chips": need}
            if not train_active:
                return None
            k = c.ranks_per_move
            if train_ranks - k < c.min_train_ranks:
                k = train_ranks - c.min_train_ranks
            if k <= 0:
                return None
            return {
                "action": "preempt_for_serving",
                "ranks": k,
                "chips": k * c.chips_per_rank,
            }

        cand = decision["drain"]
        if cand is not None:
            if serve_replicas - 1 < c.min_serve_replicas:
                return None
            if not train_active:
                # Nothing to return chips to; let the autoscaler's own
                # drain hysteresis handle pure-serving shrink instead.
                return None
            chips = c.chips_per_replica
            k = max(1, chips // max(1, c.chips_per_rank))
            if c.max_train_ranks and train_ranks + k > c.max_train_ranks:
                return None
            return {
                "action": "return_to_training",
                "replica": cand,
                "ranks": k,
                "chips": chips,
            }
        return None
