"""Chip ledger: the fabric's single source of truth for chip custody.

Every chip in the fabric is at all times either *free* or covered by
exactly one :class:`Lease` held by a plane (``"train"`` or
``"serve"``).  The ledger enforces conservation —

    ``granted + free == total``

— after every mutation, and records every grant/yield as a wire frame
so the invariant can be audited post-hoc (:meth:`ChipLedger.conserved`)
and asserted by the multi-process soak even when the arbiter crashed
mid-transition.

The ledger is deliberately passive: it never decides anything and never
talks to the planes.  The arbiter (``fabric/arbiter.py``) is the only
writer.  No wall-clock or RNG enters this file — event ordering is a
monotonically increasing sequence number, which keeps replays
deterministic (H005).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple


class LedgerError(RuntimeError):
    """Raised when an operation would violate chip conservation."""


@dataclass(frozen=True)
class Lease:
    """An exclusive claim on ``chips`` chips by one plane.

    Trailing fields are defaulted so older readers of the wire frame
    keep decoding newer grants (same wire-compat rule as
    ``ReplicaLoad``).
    """

    lease_id: str
    plane: str
    chips: int
    reason: str = ""
    granted_seq: int = 0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "lease_id": self.lease_id,
            "plane": self.plane,
            "chips": self.chips,
            "reason": self.reason,
            "granted_seq": self.granted_seq,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Lease":
        return Lease(
            lease_id=str(d["lease_id"]),
            plane=str(d["plane"]),
            chips=int(d["chips"]),
            reason=str(d.get("reason", "")),
            granted_seq=int(d.get("granted_seq", 0)),
        )


class ChipLedger:
    """Tracks chip custody with conservation checked at every event.

    ``free`` is tracked explicitly (not derived) so that
    ``granted + free == total`` is a real invariant that a bug in
    either bookkeeping path would break loudly, rather than a
    tautology.
    """

    def __init__(self, total_chips: int):
        if total_chips <= 0:
            raise ValueError("total_chips must be positive")
        self._total = int(total_chips)
        self._free = int(total_chips)
        self._leases: Dict[str, Lease] = {}
        self._seq = 0
        self._events: List[Dict[str, Any]] = []
        self._check("init")

    # -- read surface -------------------------------------------------

    @property
    def total(self) -> int:
        return self._total

    @property
    def free(self) -> int:
        return self._free

    @property
    def granted(self) -> int:
        return sum(l.chips for l in self._leases.values())

    def held(self, plane: str) -> int:
        """Chips currently leased to ``plane``."""
        return sum(l.chips for l in self._leases.values() if l.plane == plane)

    def leases(self, plane: Optional[str] = None) -> Tuple[Lease, ...]:
        out = [
            self._leases[k]
            for k in sorted(self._leases)
            if plane is None or self._leases[k].plane == plane
        ]
        return tuple(out)

    def get(self, lease_id: str) -> Optional[Lease]:
        return self._leases.get(lease_id)

    @property
    def events(self) -> List[Dict[str, Any]]:
        return list(self._events)

    # -- mutation -----------------------------------------------------

    def grant(self, plane: str, chips: int, reason: str = "") -> Lease:
        """Move ``chips`` chips from the free pool to a new lease."""
        chips = int(chips)
        if chips <= 0:
            raise LedgerError("grant of %d chips (must be positive)" % chips)
        if chips > self._free:
            raise LedgerError(
                "grant of %d chips to %r exceeds free pool (%d free of %d)"
                % (chips, plane, self._free, self._total)
            )
        self._seq += 1
        lease = Lease(
            lease_id="ls%d" % self._seq,
            plane=plane,
            chips=chips,
            reason=reason,
            granted_seq=self._seq,
        )
        self._free -= chips
        self._leases[lease.lease_id] = lease
        frame = {
            "op": "lease_grant",
            "seq": self._seq,
            "lease": lease.lease_id,
            "plane": plane,
            "chips": chips,
            "reason": reason,
            "granted": self.granted,
            "free": self._free,
            "total": self._total,
        }
        self._events.append(frame)
        self._check("grant %s" % lease.lease_id)
        return lease

    def release(self, lease_id: str, reason: str = "") -> Lease:
        """Return a lease's chips to the free pool."""
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            raise LedgerError("release of unknown lease %r" % lease_id)
        self._free += lease.chips
        self._seq += 1
        frame = {
            "op": "lease_yield",
            "seq": self._seq,
            "lease": lease.lease_id,
            "plane": lease.plane,
            "chips": lease.chips,
            "reason": reason,
            "granted": self.granted,
            "free": self._free,
            "total": self._total,
        }
        self._events.append(frame)
        self._check("release %s" % lease_id)
        return lease

    # -- invariants ---------------------------------------------------

    def _check(self, where: str) -> None:
        if self.granted + self._free != self._total:
            raise LedgerError(
                "conservation violated at %s: granted=%d free=%d total=%d"
                % (where, self.granted, self._free, self._total)
            )
        if self._free < 0:
            raise LedgerError("negative free pool at %s" % where)

    def conserved(self) -> bool:
        """True iff every recorded event satisfied conservation.

        The live ``_check`` already raises on violation; this re-audits
        the recorded frames so a consumer holding only the event log
        (e.g. the MP soak parsing ``FABRIC_REPORT``) can re-verify.
        """
        for ev in self._events:
            if ev["granted"] + ev["free"] != ev["total"]:
                return False
        return self.granted + self._free == self._total

    def as_report(self) -> Dict[str, Any]:
        return {
            "total": self._total,
            "free": self._free,
            "granted": self.granted,
            "held_train": self.held("train"),
            "held_serve": self.held("serve"),
            "leases": [l.as_dict() for l in self.leases()],
            "events": self.events,
            "conserved": self.conserved(),
        }
