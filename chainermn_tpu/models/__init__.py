from chainermn_tpu.models.mlp import MLP  # noqa: F401


def __getattr__(name):
    if name in ("ResNet50", "ResNet18", "ResNet101", "ResNet"):
        from chainermn_tpu.models import resnet

        return getattr(resnet, name)
    if name in ("AlexNet", "NiN", "GoogLeNet"):
        from chainermn_tpu.models import convnets

        return getattr(convnets, name)
    if name in ("Seq2seq", "Seq2Seq"):
        from chainermn_tpu.models import seq2seq

        # The class is spelled Seq2seq; accept the CamelCase alias the
        # lazy table historically advertised (which never resolved).
        return seq2seq.Seq2seq
    if name in ("Transformer", "TransformerLM"):
        from chainermn_tpu.models import transformer

        return getattr(transformer, name)
    if name in ("ViT",):
        from chainermn_tpu.models import vit

        return getattr(vit, name)
    raise AttributeError(name)
