"""Seq2seq encoder/decoder — the reference's model-parallel acceptance test.

Reference: REF:examples/seq2seq/seq2seq.py — an NMT model whose encoder and
decoder live on different ranks, wired through ``MultiNodeChainList`` with
``send``/``recv`` (BASELINE config #3).

TPU-first: GRU recurrences via ``flax.linen.RNN`` (lax.scan under jit —
compiler-friendly sequential control flow), bf16-ready embeddings, and a
clean encoder/decoder split so the pair drops into ``MultiNodeChainList``
(encoder rank → decoder rank, hidden state as the transferred payload).
"""

from __future__ import annotations


import flax.linen as nn
import jax.numpy as jnp

PAD, BOS, EOS = 0, 1, 2


class Encoder(nn.Module):
    vocab: int
    d_model: int = 256
    n_layers: int = 2

    @nn.compact
    def __call__(self, src):
        """(B, S) int tokens → (n_layers, B, H) final hidden states."""
        x = nn.Embed(self.vocab, self.d_model, name="embed")(src)
        carries = []
        for i in range(self.n_layers):
            rnn = nn.RNN(nn.GRUCell(self.d_model), name=f"gru_{i}")
            x = rnn(x)
            carries.append(x[:, -1])  # final state per layer
        return jnp.stack(carries)


class Decoder(nn.Module):
    vocab: int
    d_model: int = 256
    n_layers: int = 2

    @nn.compact
    def __call__(self, hidden, tgt_in):
        """Teacher-forced decode: ``hidden`` (n_layers, B, H) from the
        encoder, ``tgt_in`` (B, T) shifted-right targets → (B, T, vocab)."""
        x = nn.Embed(self.vocab, self.d_model, name="embed")(tgt_in)
        for i in range(self.n_layers):
            cell = nn.GRUCell(self.d_model)
            rnn = nn.RNN(cell, name=f"gru_{i}")
            x = rnn(x, initial_carry=hidden[i])
        return nn.Dense(self.vocab, dtype=jnp.float32, name="proj")(x)


class Seq2seq(nn.Module):
    """Single-device composition (the oracle the split model must match)."""

    vocab: int
    d_model: int = 256
    n_layers: int = 2

    @nn.compact
    def __call__(self, src, tgt_in):
        h = Encoder(self.vocab, self.d_model, self.n_layers, name="encoder")(src)
        return Decoder(self.vocab, self.d_model, self.n_layers, name="decoder")(
            h, tgt_in
        )


def shift_right(tgt):
    """Prepend BOS, drop last — the teacher-forcing input."""
    return jnp.concatenate(
        [jnp.full((tgt.shape[0], 1), BOS, tgt.dtype), tgt[:, :-1]], axis=1
    )
