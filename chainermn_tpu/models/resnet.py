"""ResNet family — the reference's ImageNet workhorse.

Reference: REF:examples/imagenet/models/resnet50.py (a Chainer ResNet-50
used for the headline scaling benchmarks; BASELINE.md's
``images/sec/chip ResNet-50 ImageNet`` metric).

TPU-first choices: NHWC layout (XLA's native conv layout on TPU), bf16
compute with fp32 parameters/statistics (MXU-friendly), and BatchNorm whose
statistics the training step synchronizes across replicas — cross-replica
BN is a strict improvement over the reference's per-GPU statistics at the
same API.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(
            self.filters, (3, 3), strides=(self.strides, self.strides), use_bias=False
        )(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)

        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4,
                (1, 1),
                strides=(self.strides, self.strides),
                use_bias=False,
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(
            self.filters, (3, 3), strides=(self.strides, self.strides), use_bias=False
        )(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters,
                (1, 1),
                strides=(self.strides, self.strides),
                use_bias=False,
                name="conv_proj",
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """NHWC ResNet. ``dtype=bfloat16`` keeps matmul/conv inputs on the MXU's
    native format while parameters stay fp32."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), strides=(2, 2), use_bias=False, name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
