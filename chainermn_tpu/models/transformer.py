"""Transformer encoder-decoder and LM.

Reference anchor: BASELINE config #4 ("Transformer enc-dec WMT,
hierarchical 2D allreduce on multi-host v4 pod") — the reference repo
itself had no transformer (it predates them); this is the net-new model
family the baseline configs demand, built TPU-first: bf16 activations,
einsum attention that XLA tiles onto the MXU, static shapes, and
``lax.scan``-free dense blocks (depth unrolled at trace time).

Tensor-parallel note: head and MLP-hidden dimensions are the natural
``model``-axis shardings; ``chainermn_tpu.parallel.sharding`` carries the
PartitionSpec rules, and the attention layer can run sequence-parallel via
``chainermn_tpu.parallel.ring_attention`` / ``ulysses``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


def sinusoidal_positions(max_len: int, d_model: int) -> np.ndarray:
    pos = np.arange(max_len)[:, None]
    div = np.exp(np.arange(0, d_model, 2) * (-np.log(10000.0) / d_model))
    pe = np.zeros((max_len, d_model), np.float32)
    pe[:, 0::2] = np.sin(pos * div)
    pe[:, 1::2] = np.cos(pos * div)
    return pe


class MultiHeadAttention(nn.Module):
    d_model: int
    n_heads: int
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None  # pluggable (ring/ulysses SP)

    @nn.compact
    def __call__(self, q_in, kv_in, mask=None):
        d_head = self.d_model // self.n_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.n_heads, d_head), dtype=self.dtype, name=name, use_bias=False
        )
        q = dense("query")(q_in)
        k = dense("key")(kv_in)
        v = dense("value")(kv_in)

        if self.attention_fn is not None:
            out = self.attention_fn(q, k, v, mask)
        else:
            scale = 1.0 / np.sqrt(d_head)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            if mask is not None:
                logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
            weights = nn.softmax(logits.astype(jnp.float32)).astype(self.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        return nn.DenseGeneral(
            self.d_model, axis=(-2, -1), dtype=self.dtype, name="out", use_bias=False
        )(out)


class FeedForward(nn.Module):
    d_model: int
    d_ff: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.d_ff, dtype=self.dtype, use_bias=False, name="wi")(x)
        h = nn.gelu(h)
        return nn.Dense(self.d_model, dtype=self.dtype, use_bias=False, name="wo")(h)


class EncoderLayer(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, mask=None):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MultiHeadAttention(
            self.d_model, self.n_heads, self.dtype, self.attention_fn
        )(h, h, mask)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        return x + FeedForward(self.d_model, self.d_ff, self.dtype)(h)


class DecoderLayer(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, y, enc, self_mask=None, cross_mask=None):
        h = nn.LayerNorm(dtype=self.dtype)(y)
        y = y + MultiHeadAttention(self.d_model, self.n_heads, self.dtype, name="self_attn")(
            h, h, self_mask
        )
        h = nn.LayerNorm(dtype=self.dtype)(y)
        y = y + MultiHeadAttention(self.d_model, self.n_heads, self.dtype, name="cross_attn")(
            h, enc, cross_mask
        )
        h = nn.LayerNorm(dtype=self.dtype)(y)
        return y + FeedForward(self.d_model, self.d_ff, self.dtype)(h)


def causal_mask(length: int):
    return jnp.tril(jnp.ones((1, 1, length, length), bool))


class Transformer(nn.Module):
    """Encoder-decoder transformer (WMT-shape, BASELINE config #4)."""

    vocab: int
    d_model: int = 512
    n_heads: int = 8
    d_ff: int = 2048
    n_enc_layers: int = 6
    n_dec_layers: int = 6
    max_len: int = 512
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, src, tgt):
        """``src``: (B, S) int tokens; ``tgt``: (B, T) int tokens (shifted
        right by the caller). Returns (B, T, vocab) fp32 logits."""
        embed = nn.Embed(self.vocab, self.d_model, dtype=self.dtype, name="embed")
        pe = jnp.asarray(sinusoidal_positions(self.max_len, self.d_model))

        x = embed(src) + pe[None, : src.shape[1]].astype(self.dtype)
        src_mask = (src != 0)[:, None, None, :]
        for i in range(self.n_enc_layers):
            x = EncoderLayer(
                self.d_model, self.n_heads, self.d_ff, self.dtype,
                self.attention_fn, name=f"enc_{i}",
            )(x, src_mask)
        x = nn.LayerNorm(dtype=self.dtype, name="enc_norm")(x)

        y = embed(tgt) + pe[None, : tgt.shape[1]].astype(self.dtype)
        self_mask = causal_mask(tgt.shape[1]) & (tgt != 0)[:, None, None, :]
        for i in range(self.n_dec_layers):
            y = DecoderLayer(
                self.d_model, self.n_heads, self.d_ff, self.dtype, name=f"dec_{i}"
            )(y, x, self_mask, src_mask)
        y = nn.LayerNorm(dtype=self.dtype, name="dec_norm")(y)
        logits = embed.attend(y.astype(jnp.float32))
        return logits


class TransformerLM(nn.Module):
    """Decoder-only LM — the long-context workhorse for the
    sequence-parallel (ring attention / Ulysses) layers."""

    vocab: int
    d_model: int = 512
    n_heads: int = 8
    d_ff: int = 2048
    n_layers: int = 6
    max_len: int = 2048
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, tokens, position_offset=None):
        """``position_offset``: global position of this shard's first token —
        pass ``axis_index * S_local`` when the sequence dimension is sharded
        (sequence parallelism); requires a sequence-aware ``attention_fn``
        (ring/Ulysses), since the dense path's causal mask is local.
        Alternatively a ``(S_local,)`` int array of explicit global
        positions, for non-contiguous shard layouts (zigzag ring)."""
        import jax.lax as _lax

        embed = nn.Embed(self.vocab, self.d_model, dtype=self.dtype, name="embed")
        pe = jnp.asarray(sinusoidal_positions(self.max_len, self.d_model))
        S = tokens.shape[1]
        if position_offset is None:
            pos = pe[:S]
        elif getattr(position_offset, "ndim", 0):
            pos = pe[position_offset]      # explicit per-token positions
        else:
            pos = _lax.dynamic_slice_in_dim(pe, position_offset, S, axis=0)
        x = embed(tokens) + pos[None].astype(self.dtype)
        mask = causal_mask(S)
        for i in range(self.n_layers):
            x = EncoderLayer(
                self.d_model, self.n_heads, self.d_ff, self.dtype,
                self.attention_fn, name=f"layer_{i}",
            )(x, mask)
        x = nn.LayerNorm(dtype=self.dtype, name="final_norm")(x)
        return embed.attend(x.astype(jnp.float32))
