"""Transformer encoder-decoder and LM.

Reference anchor: BASELINE config #4 ("Transformer enc-dec WMT,
hierarchical 2D allreduce on multi-host v4 pod") — the reference repo
itself had no transformer (it predates them); this is the net-new model
family the baseline configs demand, built TPU-first: bf16 activations,
einsum attention that XLA tiles onto the MXU, static shapes, and
``lax.scan``-free dense blocks (depth unrolled at trace time).

Tensor-parallel note: head and MLP-hidden dimensions are the natural
``model``-axis shardings; ``chainermn_tpu.parallel.sharding`` carries the
PartitionSpec rules, and the attention layer can run sequence-parallel via
``chainermn_tpu.parallel.ring_attention`` / ``ulysses``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


def _tuned_block_ctx(page_count, page_size, n_kv, d_head, dtype):
    """Tuned context-gather chunk (in pages) for paged decode attention.
    ``None`` (one-shot gather) when the tune cache has no entry — and
    always off-TPU / under pytest, where tuning lookups are inert, so CPU
    decode numerics never depend on the cache."""
    from chainermn_tpu.tuning import lookup_decode_block_ctx

    return lookup_decode_block_ctx(
        n_pages=page_count, page_size=page_size, n_kv=n_kv,
        d_head=d_head, dtype=dtype,
    )


def sinusoidal_positions(max_len: int, d_model: int) -> np.ndarray:
    pos = np.arange(max_len)[:, None]
    div = np.exp(np.arange(0, d_model, 2) * (-np.log(10000.0) / d_model))
    pe = np.zeros((max_len, d_model), np.float32)
    pe[:, 0::2] = np.sin(pos * div)
    pe[:, 1::2] = np.cos(pos * div)
    return pe


class MultiHeadAttention(nn.Module):
    d_model: int
    n_heads: int
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None  # pluggable (ring/ulysses SP)
    decode: bool = False        # incremental decoding with a KV cache
    cache_len: int = 0          # cache capacity (max sequence length)
    n_kv_heads: Optional[int] = None  # GQA/MQA: fewer K/V heads (divides
                                      # n_heads; None = MHA)
    paged: Optional[str] = None  # paged KV cache (serving): None |
                                 # "prefill" (write whole prompt, dense
                                 # causal attention) | "decode" (write one
                                 # token, paged single-query attention)
    page_count: int = 0          # number of cache pages (paged modes)
    page_size: int = 0           # tokens per page (paged modes)
    kv_dtype: Optional[str] = None  # quantized pages: "int8" stores K/V
                                    # pages as int8 with per-token-per-head
                                    # fp32 scales ("k_scales"/"v_scales"
                                    # cache leaves); None = pages in the
                                    # compute dtype
    sp_axis: Optional[str] = None   # sequence-parallel chunk prefill: the
                                    # token axis is sharded over this mesh
                                    # axis (shard_map); K/V all-gather to
                                    # the full slice before the page write
                                    # (paged="chunk" only)

    @nn.compact
    def __call__(self, q_in, kv_in, mask=None, *, block_tables=None,
                 seq_lens=None):
        d_head = self.d_model // self.n_heads
        n_kv = self.n_kv_heads or self.n_heads
        if self.n_heads % n_kv:
            raise ValueError(
                f"n_kv_heads ({n_kv}) must divide n_heads ({self.n_heads})"
            )
        dense = lambda name, h: nn.DenseGeneral(  # noqa: E731
            (h, d_head), dtype=self.dtype, name=name, use_bias=False
        )
        q = dense("query", self.n_heads)(q_in)
        k = dense("key", n_kv)(kv_in)
        v = dense("value", n_kv)(kv_in)

        if self.paged is not None:
            # Paged KV cache (serving, docs/serving.md): K/V live in
            # fixed-size pages indexed by a per-sequence block table, so
            # sequences of different lengths share one physical cache and
            # grow in O(page_size) quanta.  Same "cache" collection idiom
            # (and the same param structure) as the dense decode path
            # below, so trained params drop in unchanged.
            from chainermn_tpu.ops.decode_attention import (
                paged_attention_chunk,
                paged_attention_decode,
                write_chunk_pages,
                write_prompt_pages,
                write_token_pages,
            )

            if self.decode:
                raise ValueError(
                    "paged and decode are mutually exclusive KV cache "
                    "modes: the dense cache keeps one scalar index for "
                    "the whole batch, pages keep per-sequence lengths"
                )
            if self.attention_fn is not None:
                raise ValueError(
                    "paged modes are incompatible with attention_fn: the "
                    "pluggable adapters ignore the cache mask and would "
                    "attend to the wrong page slots"
                )
            if self.paged not in ("prefill", "decode", "chunk"):
                raise ValueError(
                    f"paged must be 'prefill', 'decode' or 'chunk', got "
                    f"{self.paged!r}"
                )
            if self.sp_axis is not None and self.paged != "chunk":
                raise ValueError(
                    "sp_axis shards the multi-token chunk step only; "
                    "decode is per-token (nothing to shard) and whole-"
                    "prompt prefill should use the chunk path when "
                    "sequence-sharded"
                )
            if self.page_count <= 0 or self.page_size <= 0:
                raise ValueError("paged modes require page_count > 0 and "
                                 "page_size > 0")
            if block_tables is None or seq_lens is None:
                raise ValueError(
                    "paged modes require block_tables and seq_lens"
                )
            if self.kv_dtype not in (None, "int8"):
                raise ValueError(
                    f"kv_dtype must be None or 'int8', got "
                    f"{self.kv_dtype!r}"
                )
            from chainermn_tpu.communicators.quant import (
                dequantize_kv,
                quantize_kv,
            )

            # Quantized pages (kv_dtype="int8", docs/serving.md): K/V
            # pages store int8 payloads with a per-token-per-head fp32
            # scale leaf alongside — the scale pages share the page
            # geometry's leading (page, slot) axes, so the SAME scatter
            # writes and the same block-table gather route them.
            page_dt = jnp.int8 if self.kv_dtype else k.dtype
            pages = (self.page_count, self.page_size, n_kv, d_head)
            pk = self.variable(
                "cache", "k_pages", lambda: jnp.zeros(pages, page_dt)
            )
            pv = self.variable(
                "cache", "v_pages", lambda: jnp.zeros(pages, page_dt)
            )
            sk = sv = None
            if self.kv_dtype:
                sshape = (self.page_count, self.page_size, n_kv)
                sk = self.variable(
                    "cache", "k_scales",
                    lambda: jnp.zeros(sshape, jnp.float32),
                )
                sv = self.variable(
                    "cache", "v_scales",
                    lambda: jnp.zeros(sshape, jnp.float32),
                )

            def write_kv(writer, lens):
                # One write path for all three paged modes: quantize the
                # fresh K/V (when kv_dtype is on) and scatter payloads
                # and scales through the same (page, slot) routing.
                if not self.kv_dtype:
                    pk.value = writer(pk.value, k, block_tables, lens)
                    pv.value = writer(pv.value, v, block_tables, lens)
                    return
                qk, k_sc = quantize_kv(k)
                qv, v_sc = quantize_kv(v)
                pk.value = writer(pk.value, qk, block_tables, lens)
                pv.value = writer(pv.value, qv, block_tables, lens)
                sk.value = writer(sk.value, k_sc, block_tables, lens)
                sv.value = writer(sv.value, v_sc, block_tables, lens)
                # Round-trip quantization error of this write — the
                # ``serve/kv_quant_err`` gauge's source (engine pulls the
                # "intermediates" collection when kv_dtype is on).
                err = jnp.maximum(
                    jnp.max(jnp.abs(dequantize_kv(qk, k_sc, jnp.float32)
                                    - k.astype(jnp.float32))),
                    jnp.max(jnp.abs(dequantize_kv(qv, v_sc, jnp.float32)
                                    - v.astype(jnp.float32))),
                )
                self.sow("intermediates", "kv_quant_err", err)

            def scales():
                # Read AFTER write_kv, so the freshly-written slots carry
                # this step's scales, not the pre-write zeros.
                return dict(
                    k_scales=sk.value if self.kv_dtype else None,
                    v_scales=sv.value if self.kv_dtype else None,
                )

            if self.paged == "prefill":
                # Write the whole prompt's K/V (padding positions beyond
                # seq_lens route to the invalid page and are dropped);
                # the attention itself is the ordinary dense causal path
                # over the local K/V — the prompt IS the whole context,
                # and it is still local in full precision (quantization
                # error enters only when pages are READ back: decode,
                # chunk, and prefix-cached suffix prefill).
                write_kv(write_prompt_pages, seq_lens)
            elif self.paged == "chunk":
                # Verify/suffix-prefill mode: T consecutive tokens per
                # sequence starting at position ``seq_lens[b]`` (here the
                # context length BEFORE the chunk).  All T tokens' K/V are
                # written first, then each query attends with its own
                # causal bound — exactly what T sequential decode steps
                # would have seen, in one lowering.
                attn_start = seq_lens
                if self.sp_axis is not None:
                    # Sequence-sharded slice (Ulysses-style): this shard
                    # holds C consecutive tokens starting at global
                    # position seq_lens + r*C.  Gather the FULL slice's
                    # K/V (pure concatenation — no cross-shard
                    # reduction, so pages are byte-identical to the
                    # unsharded chunk's), write it whole on every shard
                    # (identical values -> the cache stays replicated),
                    # and attend only the local queries at their global
                    # causal bounds.  Quantization (kv_dtype) runs
                    # after the gather, on the full slice, inside
                    # write_kv.
                    from jax import lax as _splax

                    from chainermn_tpu.parallel.ring_attention import (
                        gather_sequence_kv,
                    )

                    C = q.shape[1]
                    k, v = gather_sequence_kv(k, v, self.sp_axis)
                    r = _splax.axis_index(self.sp_axis)
                    # Padding rows (seq_lens < 0) must stay fully
                    # masked on every shard, not just rank 0.
                    attn_start = jnp.where(
                        seq_lens >= 0, seq_lens + r * C, seq_lens
                    )
                write_kv(write_chunk_pages, seq_lens)
                out = paged_attention_chunk(
                    q, pk.value, pv.value, block_tables, attn_start,
                    block_ctx=_tuned_block_ctx(
                        self.page_count, self.page_size, n_kv, d_head,
                        q.dtype,
                    ),
                    **scales(),
                )
                return nn.DenseGeneral(
                    self.d_model, axis=(-2, -1), dtype=self.dtype,
                    name="out", use_bias=False,
                )(out)
            else:
                if q.shape[1] != 1:
                    raise ValueError(
                        f"paged decode consumes exactly one token per "
                        f"call, got a length-{q.shape[1]} chunk"
                    )
                write_kv(write_token_pages, seq_lens)
                out = paged_attention_decode(
                    q, pk.value, pv.value, block_tables, seq_lens + 1,
                    block_ctx=_tuned_block_ctx(
                        self.page_count, self.page_size, n_kv, d_head,
                        q.dtype,
                    ),
                    **scales(),
                )
                return nn.DenseGeneral(
                    self.d_model, axis=(-2, -1), dtype=self.dtype,
                    name="out", use_bias=False,
                )(out)

        if self.decode:
            # KV cache (flax "cache" collection): one new token per call is
            # written at the running index; attention runs over the whole
            # cache with positions beyond the index masked.  Same param
            # structure as the training path, so trained params drop in.
            if self.attention_fn is not None:
                raise ValueError(
                    "decode=True is incompatible with attention_fn: the "
                    "pluggable adapters (flash/ring/ulysses) impose their "
                    "own causality with the query at local position 0 and "
                    "ignore the cache mask, so they would silently attend "
                    "to the wrong cache slots; build the decode twin "
                    "without attention_fn (generate() does this)"
                )
            if self.cache_len <= 0:
                raise ValueError("decode=True requires cache_len > 0")
            if q.shape[1] != 1:
                raise ValueError(
                    f"decode mode consumes exactly one token per call, got "
                    f"a length-{q.shape[1]} chunk (the single-position "
                    f"cache mask would silently hide the chunk's own "
                    f"tokens); feed tokens one at a time, as generate() does"
                )
            B = q.shape[0]
            ck = self.variable(
                "cache", "cached_key",
                lambda: jnp.zeros((B, self.cache_len, n_kv, d_head),
                                  k.dtype),
            )
            cv = self.variable(
                "cache", "cached_value",
                lambda: jnp.zeros((B, self.cache_len, n_kv, d_head),
                                  v.dtype),
            )
            cidx = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
            )
            i = cidx.value
            import jax.lax as _lax

            ck.value = _lax.dynamic_update_slice(ck.value, k, (0, i, 0, 0))
            cv.value = _lax.dynamic_update_slice(cv.value, v, (0, i, 0, 0))
            cidx.value = i + q.shape[1]
            k, v = ck.value, cv.value
            mask = (jnp.arange(self.cache_len) <= i)[None, None, None, :]

        if self.attention_fn is not None:
            # GQA-aware adapters (flash and its SP compositions) consume
            # the reduced kv head count directly.
            out = self.attention_fn(q, k, v, mask)
        else:
            if n_kv != self.n_heads:
                # Dense-softmax path: broadcast kv heads (the grads sum
                # back over the group through repeat's transpose).
                k = jnp.repeat(k, self.n_heads // n_kv, axis=2)
                v = jnp.repeat(v, self.n_heads // n_kv, axis=2)
            scale = 1.0 / np.sqrt(d_head)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            if mask is not None:
                logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
            weights = nn.softmax(logits.astype(jnp.float32)).astype(self.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        return nn.DenseGeneral(
            self.d_model, axis=(-2, -1), dtype=self.dtype, name="out", use_bias=False
        )(out)


class FeedForward(nn.Module):
    d_model: int
    d_ff: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.d_ff, dtype=self.dtype, use_bias=False, name="wi")(x)
        h = nn.gelu(h)
        return nn.Dense(self.d_model, dtype=self.dtype, use_bias=False, name="wo")(h)


class EncoderLayer(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    decode: bool = False
    cache_len: int = 0
    n_kv_heads: Optional[int] = None
    paged: Optional[str] = None
    page_count: int = 0
    page_size: int = 0
    kv_dtype: Optional[str] = None
    sp_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, mask=None, *, block_tables=None, seq_lens=None):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MultiHeadAttention(
            self.d_model, self.n_heads, self.dtype, self.attention_fn,
            decode=self.decode, cache_len=self.cache_len,
            n_kv_heads=self.n_kv_heads, paged=self.paged,
            page_count=self.page_count, page_size=self.page_size,
            kv_dtype=self.kv_dtype, sp_axis=self.sp_axis,
        )(h, h, mask, block_tables=block_tables, seq_lens=seq_lens)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        return x + FeedForward(self.d_model, self.d_ff, self.dtype)(h)


class DecoderLayer(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, y, enc, self_mask=None, cross_mask=None):
        h = nn.LayerNorm(dtype=self.dtype)(y)
        y = y + MultiHeadAttention(self.d_model, self.n_heads, self.dtype, name="self_attn")(
            h, h, self_mask
        )
        h = nn.LayerNorm(dtype=self.dtype)(y)
        y = y + MultiHeadAttention(self.d_model, self.n_heads, self.dtype, name="cross_attn")(
            h, enc, cross_mask
        )
        h = nn.LayerNorm(dtype=self.dtype)(y)
        return y + FeedForward(self.d_model, self.d_ff, self.dtype)(h)


def causal_mask(length: int):
    return jnp.tril(jnp.ones((1, 1, length, length), bool))


class Transformer(nn.Module):
    """Encoder-decoder transformer (WMT-shape, BASELINE config #4)."""

    vocab: int
    d_model: int = 512
    n_heads: int = 8
    d_ff: int = 2048
    n_enc_layers: int = 6
    n_dec_layers: int = 6
    max_len: int = 512
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, src, tgt):
        """``src``: (B, S) int tokens; ``tgt``: (B, T) int tokens (shifted
        right by the caller). Returns (B, T, vocab) fp32 logits."""
        embed = nn.Embed(self.vocab, self.d_model, dtype=self.dtype, name="embed")
        pe = jnp.asarray(sinusoidal_positions(self.max_len, self.d_model))

        x = embed(src) + pe[None, : src.shape[1]].astype(self.dtype)
        src_mask = (src != 0)[:, None, None, :]
        for i in range(self.n_enc_layers):
            x = EncoderLayer(
                self.d_model, self.n_heads, self.d_ff, self.dtype,
                self.attention_fn, name=f"enc_{i}",
            )(x, src_mask)
        x = nn.LayerNorm(dtype=self.dtype, name="enc_norm")(x)

        y = embed(tgt) + pe[None, : tgt.shape[1]].astype(self.dtype)
        self_mask = causal_mask(tgt.shape[1]) & (tgt != 0)[:, None, None, :]
        for i in range(self.n_dec_layers):
            y = DecoderLayer(
                self.d_model, self.n_heads, self.d_ff, self.dtype, name=f"dec_{i}"
            )(y, x, self_mask, src_mask)
        y = nn.LayerNorm(dtype=self.dtype, name="dec_norm")(y)
        logits = embed.attend(y.astype(jnp.float32))
        return logits


class TransformerLM(nn.Module):
    """Decoder-only LM — the long-context workhorse for the
    sequence-parallel (ring attention / Ulysses) layers."""

    vocab: int
    d_model: int = 512
    n_heads: int = 8
    d_ff: int = 2048
    n_layers: int = 6
    max_len: int = 2048
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    decode: bool = False        # KV-cache incremental decoding (generate())
    remat: bool = False         # rematerialize each layer in backward
    n_kv_heads: Optional[int] = None  # GQA/MQA (divides n_heads)
    paged: Optional[str] = None  # paged KV cache (serving engine):
                                 # "prefill" | "decode" — see
                                 # MultiHeadAttention.paged
    page_count: int = 0
    page_size: int = 0
    kv_dtype: Optional[str] = None  # quantized pages ("int8") — see
                                    # MultiHeadAttention.kv_dtype
    sp_axis: Optional[str] = None   # sequence-parallel chunk prefill —
                                    # see MultiHeadAttention.sp_axis

    @nn.compact
    def __call__(self, tokens, position_offset=None, return_hidden=False,
                 inputs_embeds=None, block_tables=None, seq_lens=None):
        """``position_offset``: global position of this shard's first token —
        pass ``axis_index * S_local`` when the sequence dimension is sharded
        (sequence parallelism); requires a sequence-aware ``attention_fn``
        (ring/Ulysses), since the dense path's causal mask is local.
        Alternatively a ``(S_local,)`` int array of explicit global
        positions, for non-contiguous shard layouts (zigzag ring) — or a
        ``(B, S)`` int array of PER-SEQUENCE positions, which is how the
        serving engine's paged decode step places each sequence's next
        token at its own context length.

        ``block_tables``/``seq_lens``: the paged-KV-cache routing inputs,
        required (and only meaningful) when ``paged`` is set — see
        :class:`MultiHeadAttention` and docs/serving.md.

        ``return_hidden=True`` returns the final-norm hidden states
        ``(B, S, d_model)`` instead of logits — the input for
        :func:`chainermn_tpu.ops.fused_cross_entropy`, which never
        materializes the ``(B*S, vocab)`` logits the default
        ``embed.attend`` path does.

        ``inputs_embeds``: optional pre-computed ``(B, S, d_model)`` token
        embeddings replacing the internal table lookup (positions are
        still added here) — the entry point for a VOCAB-SHARDED embedding
        (``parallel.sharding.vocab_parallel_embed``), whose table lives
        outside this module's replicated parameters.  Combine with
        ``return_hidden=True`` so the (equally vocab-sharded) LM head
        runs outside too.

        ``remat=True`` wraps every layer in ``jax.checkpoint``: backward
        recomputes layer activations instead of storing ~6 per-layer
        tensors — the standard long-context memory/FLOP trade."""
        import jax.lax as _lax

        pe = jnp.asarray(sinusoidal_positions(self.max_len, self.d_model))
        S = tokens.shape[1]
        if position_offset is None:
            pos = pe[:S]
        elif getattr(position_offset, "ndim", 0) == 2:
            pos = pe[position_offset]      # (B, S) per-sequence positions
        elif getattr(position_offset, "ndim", 0):
            pos = pe[position_offset]      # explicit per-token positions
        else:
            pos = _lax.dynamic_slice_in_dim(pe, position_offset, S, axis=0)
        if inputs_embeds is None:
            embed = nn.Embed(
                self.vocab, self.d_model, dtype=self.dtype, name="embed"
            )
            x = embed(tokens)
        else:
            if not return_hidden:
                raise ValueError(
                    "inputs_embeds requires return_hidden=True: the tied "
                    "embed.attend head has no table when the lookup is "
                    "external (vocab-sharded) — compute the head with "
                    "the same external table"
                )
            embed = None
            x = inputs_embeds.astype(self.dtype)
        if pos.ndim == 3:                  # (B, S, d): already per-batch
            x = x + pos.astype(self.dtype)
        else:
            x = x + pos[None].astype(self.dtype)
        # Pluggable attention (flash/ring/ulysses) imposes its own
        # causality and ignores the mask argument — skip materializing
        # the (S, S) mask, which at long context is the largest host
        # constant in the program (S=16k: 256 MiB as bool).
        mask = None if self.attention_fn is not None else causal_mask(S)
        layer_cls = (
            nn.remat(EncoderLayer, static_argnums=())
            if self.remat else EncoderLayer
        )
        for i in range(self.n_layers):
            x = layer_cls(
                self.d_model, self.n_heads, self.d_ff, self.dtype,
                self.attention_fn, name=f"layer_{i}",
                decode=self.decode, cache_len=self.max_len if self.decode else 0,
                n_kv_heads=self.n_kv_heads, paged=self.paged,
                page_count=self.page_count, page_size=self.page_size,
                kv_dtype=self.kv_dtype, sp_axis=self.sp_axis,
            )(x, mask, block_tables=block_tables, seq_lens=seq_lens)
        x = nn.LayerNorm(dtype=self.dtype, name="final_norm")(x)
        if return_hidden:
            return x
        return embed.attend(x.astype(jnp.float32))


def generate(
    lm: "TransformerLM",
    params,
    prompt,
    max_new_tokens: int,
    rng=None,
    temperature: float = 0.0,
):
    """Autoregressive generation with a KV cache — O(T·max_len) attention
    instead of the O(T²·max_len) of re-running the prefix per token.

    ``lm``: the TransformerLM the ``params`` were trained with (any
    ``decode`` value — a decode twin is constructed here).
    ``prompt``: (B, T) int32.  Greedy at ``temperature=0`` (default),
    otherwise softmax sampling with ``rng``.
    Returns (B, T + max_new_tokens) — prompt with the continuation.
    """
    import jax
    from jax import lax

    B, T = prompt.shape
    total = T + max_new_tokens
    if total > lm.max_len:
        raise ValueError(
            f"prompt + new tokens ({total}) exceed max_len {lm.max_len}"
        )
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 requires rng")

    dec = TransformerLM(
        vocab=lm.vocab, d_model=lm.d_model, n_heads=lm.n_heads,
        d_ff=lm.d_ff, n_layers=lm.n_layers, max_len=lm.max_len,
        dtype=lm.dtype, decode=True,
    )
    # eval_shape: cache geometry without allocating (and then discarding)
    # a second full parameter set; zeros ARE the empty cache (index 0).
    cache_shapes = jax.eval_shape(
        lambda: dec.init(
            jax.random.PRNGKey(0), jnp.zeros((B, 1), jnp.int32),
            position_offset=0,
        )["cache"]
    )
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)

    pad = jnp.zeros((B, max_new_tokens), prompt.dtype)
    prompt_padded = jnp.concatenate([prompt, pad], axis=1)

    def step(carry, t):
        cache, prev = carry
        # Feed the prompt while it lasts, then the previous sample.
        tok = jnp.where(t < T, prompt_padded[:, t], prev)
        logits, upd = dec.apply(
            {"params": params["params"] if "params" in params else params,
             "cache": cache},
            tok[:, None], position_offset=t, mutable=["cache"],
        )
        logits = logits[:, 0]                       # (B, vocab)
        if temperature > 0.0:
            key = jax.random.fold_in(rng, t)
            nxt = jax.random.categorical(key, logits / temperature, axis=-1)
        else:
            nxt = logits.argmax(-1)
        return (upd["cache"], nxt.astype(prompt.dtype)), nxt.astype(prompt.dtype)

    (_, _), ys = lax.scan(
        step, (cache, jnp.zeros((B,), prompt.dtype)), jnp.arange(total - 1)
    )
    # ys[t] is the model's prediction AFTER consuming token t; the
    # continuation is ys[T-1 : T-1+max_new_tokens].
    gen = ys[T - 1 :].T                              # (B, max_new_tokens)
    return jnp.concatenate([prompt, gen], axis=1)
