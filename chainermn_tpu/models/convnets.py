"""Classic ImageNet convnets — the reference's example-model zoo.

Reference: REF:examples/imagenet/models/ — ``alex.py``, ``nin.py``,
``googlenet.py`` alongside resnet50 (SURVEY §2.4).  Rebuilt NHWC/bf16 for
the MXU; architectural intent preserved (AlexNet's big-kernel stem, NiN's
1×1 mlpconv stacks + global average pooling, GoogLeNet's Inception
branches) rather than any line-level translation.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class AlexNet(nn.Module):
    """AlexNet (REF:examples/imagenet/models/alex.py)."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.relu(conv(96, (11, 11), strides=(4, 4))(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(256, (5, 5), padding="SAME")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(384, (3, 3), padding="SAME")(x))
        x = nn.relu(conv(384, (3, 3), padding="SAME")(x))
        x = nn.relu(conv(256, (3, 3), padding="SAME")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


class NiN(nn.Module):
    """Network-in-Network (REF:examples/imagenet/models/nin.py): mlpconv
    stacks (conv + two 1×1 convs) and global average pooling."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    def _mlpconv(self, x, features, kernel, strides, name):
        conv = partial(nn.Conv, dtype=self.dtype)
        x = nn.relu(conv(features, kernel, strides=strides, name=f"{name}_0")(x))
        x = nn.relu(conv(features, (1, 1), name=f"{name}_1")(x))
        x = nn.relu(conv(features, (1, 1), name=f"{name}_2")(x))
        return x

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = self._mlpconv(x, 96, (11, 11), (4, 4), "mlp1")
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = self._mlpconv(x, 256, (5, 5), (1, 1), "mlp2")
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = self._mlpconv(x, 384, (3, 3), (1, 1), "mlp3")
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = self._mlpconv(x, self.num_classes, (3, 3), (1, 1), "mlp4")
        x = jnp.mean(x, axis=(1, 2))
        return x.astype(jnp.float32)


class _Inception(nn.Module):
    n1: int
    n3r: int
    n3: int
    n5r: int
    n5: int
    pool_proj: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, dtype=self.dtype)
        b1 = nn.relu(conv(self.n1, (1, 1), name="b1")(x))
        b3 = nn.relu(conv(self.n3r, (1, 1), name="b3r")(x))
        b3 = nn.relu(conv(self.n3, (3, 3), padding="SAME", name="b3")(b3))
        b5 = nn.relu(conv(self.n5r, (1, 1), name="b5r")(x))
        b5 = nn.relu(conv(self.n5, (5, 5), padding="SAME", name="b5")(b5))
        bp = nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = nn.relu(conv(self.pool_proj, (1, 1), name="bp")(bp))
        return jnp.concatenate([b1, b3, b5, bp], axis=-1)


class GoogLeNet(nn.Module):
    """GoogLeNet/Inception-v1 (REF:examples/imagenet/models/googlenet.py),
    sans auxiliary classifiers (a training-era trick superseded by better
    normalization)."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.relu(conv(64, (7, 7), strides=(2, 2), padding="SAME")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.relu(conv(64, (1, 1))(x))
        x = nn.relu(conv(192, (3, 3), padding="SAME")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = _Inception(64, 96, 128, 16, 32, 32, self.dtype, name="i3a")(x)
        x = _Inception(128, 128, 192, 32, 96, 64, self.dtype, name="i3b")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = _Inception(192, 96, 208, 16, 48, 64, self.dtype, name="i4a")(x)
        x = _Inception(160, 112, 224, 24, 64, 64, self.dtype, name="i4b")(x)
        x = _Inception(128, 128, 256, 24, 64, 64, self.dtype, name="i4c")(x)
        x = _Inception(112, 144, 288, 32, 64, 64, self.dtype, name="i4d")(x)
        x = _Inception(256, 160, 320, 32, 128, 128, self.dtype, name="i4e")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = _Inception(256, 160, 320, 32, 128, 128, self.dtype, name="i5a")(x)
        x = _Inception(384, 192, 384, 48, 128, 128, self.dtype, name="i5b")(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.4, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
