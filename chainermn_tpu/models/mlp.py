"""MLP — the model of the reference's MNIST example
(REF:examples/mnist/train_mnist.py: a 784→1000→1000→10 tanh/relu MLP).

Defined with flax.linen; all chainermn_tpu wrappers are pytree-generic so
any parameter container works, flax being the idiomatic choice on TPU.
"""

from __future__ import annotations

import flax.linen as nn


class MLP(nn.Module):
    n_units: int = 1000
    n_out: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.n_units)(x))
        x = nn.relu(nn.Dense(self.n_units)(x))
        return nn.Dense(self.n_out)(x)
