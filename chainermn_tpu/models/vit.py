"""Vision Transformer — BASELINE config #5 (ViT-B/16 mixed data+pipeline
parallel with double-buffered allreduce).

Net-new model family (the reference predates ViTs); TPU-first: patchify as
a single strided conv, bf16 einsum attention on the MXU, fp32 head.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from chainermn_tpu.models.transformer import EncoderLayer


class ViT(nn.Module):
    num_classes: int = 1000
    patch: int = 16
    d_model: int = 768
    n_heads: int = 12
    d_ff: int = 3072
    n_layers: int = 12
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        B = x.shape[0]
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.d_model,
            (self.patch, self.patch),
            strides=(self.patch, self.patch),
            dtype=self.dtype,
            name="patchify",
        )(x)
        x = x.reshape(B, -1, self.d_model)

        cls = self.param(
            "cls", nn.initializers.zeros, (1, 1, self.d_model), jnp.float32
        )
        x = jnp.concatenate([jnp.tile(cls.astype(self.dtype), (B, 1, 1)), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, x.shape[1], self.d_model),
            jnp.float32,
        )
        x = x + pos.astype(self.dtype)

        for i in range(self.n_layers):
            x = EncoderLayer(
                self.d_model, self.n_heads, self.d_ff, self.dtype, name=f"block_{i}"
            )(x)
        x = nn.LayerNorm(dtype=self.dtype, name="final_norm")(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x[:, 0])


ViT_B16 = ViT  # defaults are the B/16 configuration
