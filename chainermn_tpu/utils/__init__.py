def __getattr__(name):
    import importlib

    if name in ("native", "profiling", "debug"):
        return importlib.import_module(f"chainermn_tpu.utils.{name}")
    raise AttributeError(name)
