"""Evaluation metrics.

The reference's seq2seq example reported BLEU on WMT validation data
(REF:examples/seq2seq/seq2seq.py); this module provides an in-repo corpus
BLEU (Papineni et al., 2002) so the framework stays self-contained — no
NLTK dependency.  Host-side numpy: metrics run on decoded token lists, not
in the jitted path.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Iterable, Sequence


def _ngrams(tokens: Sequence, n: int) -> Counter:
    return Counter(
        tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1)
    )


def corpus_bleu(
    references: Iterable[Sequence],
    hypotheses: Iterable[Sequence],
    max_n: int = 4,
    smooth: bool = True,
) -> float:
    """Corpus-level BLEU-``max_n`` with brevity penalty.

    ``references``/``hypotheses``: parallel iterables of token sequences
    (ints or strings — anything hashable).  One reference per hypothesis
    (the common NMT-validation setup).  ``smooth`` adds +1 smoothing to
    higher-order precisions (Lin & Och 2004), keeping short-corpus scores
    finite; exact corpus BLEU with ``smooth=False``.
    """
    refs = [list(r) for r in references]
    hyps = [list(h) for h in hypotheses]
    if len(refs) != len(hyps):
        raise ValueError(f"{len(refs)} references vs {len(hyps)} hypotheses")
    if not refs:
        return 0.0

    match = [0] * max_n
    total = [0] * max_n
    ref_len = hyp_len = 0
    for ref, hyp in zip(refs, hyps):
        ref_len += len(ref)
        hyp_len += len(hyp)
        for n in range(1, max_n + 1):
            h = _ngrams(hyp, n)
            r = _ngrams(ref, n)
            match[n - 1] += sum((h & r).values())
            total[n - 1] += max(len(hyp) - n + 1, 0)

    log_prec = 0.0
    for n in range(max_n):
        m, t = match[n], total[n]
        if smooth and n > 0:
            m, t = m + 1, t + 1
        if m == 0 or t == 0:
            return 0.0
        log_prec += math.log(m / t)
    log_prec /= max_n

    bp = 1.0 if hyp_len > ref_len else math.exp(1.0 - ref_len / max(hyp_len, 1))
    return bp * math.exp(log_prec)


def strip_special(tokens: Sequence[int], eos: int = 2, pad: int = 0):
    """Cut a decoded sequence at EOS and drop padding — the usual
    post-processing before BLEU."""
    out = []
    for t in tokens:
        if t == eos:
            break
        if t != pad:
            out.append(int(t))
    return out
