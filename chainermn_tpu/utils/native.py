"""ctypes binding for the in-tree C++ host-buffer library (csrc/hostbuf.cpp).

The native seam of this framework (see csrc/hostbuf.cpp for the design
rationale vs the reference's NCCL binding + pinned-memory staging).  The
library is compiled on demand with g++ and cached next to the sources;
every entry point has a numpy fallback so the framework degrades gracefully
where no toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import zlib
from typing import Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "csrc", "hostbuf.cpp")
_LIB = os.path.join(_REPO_ROOT, "csrc", "libhostbuf.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        "-o", _LIB, _SRC, "-lpthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_LIB) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        ):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _load_failed = True
            return None
        lib.hostbuf_crc32c.restype = ctypes.c_uint32
        lib.hostbuf_crc32c.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
        ]
        lib.hostbuf_parallel_gather.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.hostbuf_queue_new.restype = ctypes.c_void_p
        lib.hostbuf_queue_new.argtypes = [ctypes.c_uint64]
        lib.hostbuf_queue_push.restype = ctypes.c_int
        lib.hostbuf_queue_push.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.hostbuf_queue_pop.restype = ctypes.c_uint64
        lib.hostbuf_queue_pop.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.hostbuf_queue_size.restype = ctypes.c_uint64
        lib.hostbuf_queue_size.argtypes = [ctypes.c_void_p]
        lib.hostbuf_queue_close.argtypes = [ctypes.c_void_p]
        lib.hostbuf_queue_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def crc32c(data: bytes, seed: int = 0) -> int:
    """CRC32C checksum (native; zlib.crc32 fallback keeps determinism per
    process, flagged by a different polynomial)."""
    lib = get_lib()
    if lib is None:
        return zlib.crc32(data, seed) & 0xFFFFFFFF
    return int(lib.hostbuf_crc32c(data, len(data), seed))


def parallel_gather(items: Sequence[np.ndarray], n_threads: int = 0) -> np.ndarray:
    """Stack equal-shaped C-contiguous arrays into one batch array with a
    native multithreaded memcpy — the pack_params idea where it still pays
    on TPU hosts (np.stack is GIL-bound)."""
    items = [np.ascontiguousarray(a) for a in items]
    first = items[0]
    out = np.empty((len(items),) + first.shape, first.dtype)
    lib = get_lib()
    if lib is None:
        for i, a in enumerate(items):
            out[i] = a
        return out
    item_size = first.nbytes
    ptrs = (ctypes.c_void_p * len(items))(
        *[a.ctypes.data_as(ctypes.c_void_p) for a in items]
    )
    if n_threads <= 0:
        n_threads = min(8, os.cpu_count() or 1)
    lib.hostbuf_parallel_gather(
        out.ctypes.data_as(ctypes.c_void_p), ptrs,
        len(items), item_size, n_threads,
    )
    return out


class NativeQueue:
    """Bounded byte-buffer queue backed by the C++ ring queue (threading.Queue
    fallback) — a host-side staging structure for byte-level pipelines (raw
    record readers, serialized checkpoint chunks).  Note
    ``iterators.create_prefetch_iterator`` stages ``jax.Array`` batches
    through a plain ``queue.Queue`` with its own stop-event shutdown; this
    class is for payloads that live as bytes on the host side."""

    def __init__(self, capacity: int = 4):
        self._lib = get_lib()
        if self._lib is not None:
            self._h = self._lib.hostbuf_queue_new(capacity)
        else:
            import queue
            import threading

            self._q = queue.Queue(maxsize=capacity)
            self._closed = threading.Event()

    def push(self, data: bytes) -> bool:
        if self._lib is not None:
            return self._lib.hostbuf_queue_push(self._h, data, len(data)) == 0
        # Fallback mirrors the C++ contract: push blocks while full, fails
        # once closed.
        while not self._closed.is_set():
            try:
                self._q.put(data, timeout=0.05)
                return True
            except Exception:
                continue
        return False

    def pop(self, max_len: int) -> bytes:
        if self._lib is not None:
            buf = ctypes.create_string_buffer(max_len)
            n = self._lib.hostbuf_queue_pop(self._h, buf, max_len)
            return buf.raw[:n]
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except Exception:
                if self._closed.is_set():
                    return b""
                continue
            return item[:max_len]

    def size(self) -> int:
        if self._lib is not None:
            return int(self._lib.hostbuf_queue_size(self._h))
        return self._q.qsize()

    def close(self):
        if self._lib is not None:
            self._lib.hostbuf_queue_close(self._h)
        else:
            self._closed.set()

    def __del__(self):
        try:
            if self._lib is not None:
                self._lib.hostbuf_queue_free(self._h)
        except Exception:
            pass
