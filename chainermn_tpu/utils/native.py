"""ctypes binding for the in-tree C++ host-buffer library (csrc/hostbuf.cpp).

The native seam of this framework (see csrc/hostbuf.cpp for the design
rationale vs the reference's NCCL binding + pinned-memory staging).  The
library is compiled on demand with g++ and cached next to the sources;
every entry point has a numpy fallback so the framework degrades gracefully
where no toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "csrc", "hostbuf.cpp")
_LIB = os.path.join(_REPO_ROOT, "csrc", "libhostbuf.so")
# Installed trees: setup.py's build hook compiles the library into the
# package itself (chainermn_tpu/_native/libhostbuf.so) — no toolchain
# needed at import time.  Preferred when present.
_PACKAGED_LIB = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "_native", "libhostbuf.so",
)

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False
_loaded_from: Optional[str] = None   # "packaged" | "csrc" | None


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        "-o", _LIB, _SRC, "-lpthread",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def native_impl() -> Optional[str]:
    """Which native library is active: ``"packaged"`` (wheel-built
    ``_native/libhostbuf.so``), ``"csrc"`` (on-demand g++ build in a
    source checkout), or ``None`` (pure-Python fallbacks)."""
    get_lib()
    return _loaded_from


def _try_load(path: str):
    try:
        return _bind_symbols(ctypes.CDLL(path))
    except (OSError, AttributeError):
        # Missing/foreign-arch lib, or a stale .so without the expected
        # symbols — fall through to the next source in the chain.
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """Load the native library — packaged first, then the on-demand csrc
    build; None if unavailable (callers use the Python fallbacks)."""
    global _lib, _load_failed, _loaded_from
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.path.exists(_PACKAGED_LIB):
            lib = _try_load(_PACKAGED_LIB)
            if lib is not None:
                _loaded_from = "packaged"
                return lib
        # Source checkout: (re)build when the source is newer; a prebuilt
        # csrc/libhostbuf.so with the SOURCE stripped still loads (the
        # symbol check in _try_load guards against stale/foreign .so).
        if os.path.exists(_SRC) and (
            not os.path.exists(_LIB)
            or os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        ):
            if not _build():
                _load_failed = True
                return None
        if os.path.exists(_LIB):
            lib = _try_load(_LIB)
            if lib is not None:
                _loaded_from = "csrc"
                return lib
        _load_failed = True
        return None


def _bind_symbols(lib: ctypes.CDLL) -> ctypes.CDLL:
    global _lib
    lib.hostbuf_crc32c.restype = ctypes.c_uint32
    lib.hostbuf_crc32c.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
    ]
    lib.hostbuf_crc32c_impl.restype = ctypes.c_int
    for name in ("hostbuf_gatherv", "hostbuf_scatterv"):
        fn = getattr(lib, name)
        fn.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64, ctypes.c_int,
        ]
    lib.hostbuf_queue_new.restype = ctypes.c_void_p
    lib.hostbuf_queue_new.argtypes = [ctypes.c_uint64]
    lib.hostbuf_queue_push.restype = ctypes.c_int
    lib.hostbuf_queue_push.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
    ]
    lib.hostbuf_queue_pop.restype = ctypes.c_uint64
    lib.hostbuf_queue_pop.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.hostbuf_queue_size.restype = ctypes.c_uint64
    lib.hostbuf_queue_size.argtypes = [ctypes.c_void_p]
    lib.hostbuf_queue_close.argtypes = [ctypes.c_void_p]
    lib.hostbuf_queue_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


_CRC32C_TABLES: Optional[list] = None


def _crc32c_tables() -> list:
    """Slicing-by-8 table set for the pure-Python CRC32C fallback."""
    global _CRC32C_TABLES
    if _CRC32C_TABLES is None:
        poly = 0x82F63B78
        t0 = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (poly if crc & 1 else 0)
            t0.append(crc)
        tables = [t0]
        for k in range(1, 8):
            prev = tables[k - 1]
            tables.append([(prev[i] >> 8) ^ t0[prev[i] & 0xFF] for i in range(256)])
        _CRC32C_TABLES = tables
    return _CRC32C_TABLES


def _crc32c_py(data, seed: int) -> int:
    """Pure-Python CRC32C (Castagnoli), bit-identical to the native one —
    the checksum is load-bearing (checkpoint accept/reject, cross-host
    collective fingerprints), so the fallback must match the native
    polynomial exactly, not substitute zlib's.  Slicing-by-8 keeps the
    no-toolchain path within shouting distance of usable."""
    t = _crc32c_tables()
    t0, t1, t2, t3, t4, t5, t6, t7 = t
    mv = memoryview(data).cast("B")
    crc = ~seed & 0xFFFFFFFF
    n = len(mv)
    i = 0
    for i in range(0, n - 7, 8):
        crc ^= mv[i] | (mv[i + 1] << 8) | (mv[i + 2] << 16) | (mv[i + 3] << 24)
        crc = (
            t7[crc & 0xFF] ^ t6[(crc >> 8) & 0xFF]
            ^ t5[(crc >> 16) & 0xFF] ^ t4[(crc >> 24) & 0xFF]
            ^ t3[mv[i + 4]] ^ t2[mv[i + 5]] ^ t1[mv[i + 6]] ^ t0[mv[i + 7]]
        )
    for j in range(n - (n % 8), n):
        crc = (crc >> 8) ^ t0[(crc ^ mv[j]) & 0xFF]
    return ~crc & 0xFFFFFFFF


_accel_crc = None


def _accel_crc32c():
    """An accelerated installed crc32c, if any — the middle tier of the
    fallback chain (native lib → installed module → pure Python), because
    the pure-Python tail runs at ~MB/s and the checksum sits on the
    checkpoint save/load path.  Both candidate modules implement
    Castagnoli with the same ~x ~seed convention as ours, but only for
    seed=0-style chaining of our API; they are used only for seed == 0."""
    global _accel_crc
    if _accel_crc is None:
        _accel_crc = False
        for mod in ("google_crc32c", "crc32c"):
            try:
                m = __import__(mod)
                fn = m.value if hasattr(m, "value") else m.crc32c
                if fn(b"123456789") == 0xE3069283:  # known vector check
                    _accel_crc = fn
                    break
            except Exception:
                continue
    return _accel_crc or None


def crc32c_impl() -> str:
    """Which implementation :func:`crc32c` dispatches to — 'hw' (native
    SSE4.2 instruction), 'sw' (native slicing-by-8), 'module' (installed
    accelerated package), or 'python' (pure-Python slicing-by-8)."""
    lib = get_lib()
    if lib is not None:
        return "hw" if lib.hostbuf_crc32c_impl() else "sw"
    if _accel_crc32c() is not None:
        return "module"
    return "python"


def crc32c(data, seed: int = 0) -> int:
    """CRC32C checksum over ``bytes`` or a C-contiguous ``np.ndarray``
    (arrays are checksummed in place via their buffer pointer — no copy).
    Native implementation (hardware SSE4.2 when the CPU supports it,
    slicing-by-8 otherwise) with an installed-module middle tier and a
    bit-identical pure-Python tail for toolchain-less hosts."""
    lib = get_lib()
    if isinstance(data, np.ndarray):
        if not data.flags["C_CONTIGUOUS"]:
            data = np.ascontiguousarray(data)
        if lib is None:
            return _crc32c_fallback(_byte_view(data), seed)
        return int(
            lib.hostbuf_crc32c(
                data.ctypes.data_as(ctypes.c_char_p), data.nbytes, seed
            )
        )
    if lib is None:
        return _crc32c_fallback(data, seed)
    return int(lib.hostbuf_crc32c(data, len(data), seed))


def tree_digest(tree) -> int:
    """Deterministic crc32c fingerprint of every array leaf of a pytree,
    folded in ``jax.tree.leaves`` order.  Two runs producing bit-identical
    parameters produce equal digests — the fault-tolerance examples print
    it so the kill-and-resume test can assert exact resume."""
    import jax

    digest = 0
    for leaf in jax.tree.leaves(tree):
        a = np.ascontiguousarray(np.asarray(leaf))
        digest = crc32c(_byte_view(a), seed=digest)
    return digest


def _crc32c_fallback(data, seed: int) -> int:
    if seed == 0:
        accel = _accel_crc32c()
        if accel is not None:
            return int(accel(bytes(data)))
    return _crc32c_py(data, seed)


def _byte_view(a: np.ndarray) -> np.ndarray:
    """Flat uint8 view of a C-contiguous array's buffer.  reshape(-1)
    BEFORE the dtype view: ``view(np.uint8)`` is illegal on 0-d arrays."""
    return a.reshape(-1).view(np.uint8)


def _default_threads(n_threads: int) -> int:
    if n_threads <= 0:
        return min(8, os.cpu_count() or 1)
    return n_threads


def _as_u64_array(vals) -> "ctypes.Array":
    return (ctypes.c_uint64 * len(vals))(*vals)


def _ptr_array(arrays: Sequence[np.ndarray]) -> "ctypes.Array":
    return (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data_as(ctypes.c_void_p) for a in arrays]
    )


def pack_buffers(
    arrays: Sequence[np.ndarray],
    out: Optional[np.ndarray] = None,
    n_threads: int = 0,
) -> np.ndarray:
    """Concatenate the raw bytes of C-contiguous arrays (any shapes/dtypes)
    into one uint8 buffer with a native multithreaded memcpy — pack_params
    for the host side.  Used by the checkpoint writer to assemble payload
    chunks."""
    # np.asarray(..., order="C") rather than ascontiguousarray: the latter
    # silently promotes 0-d arrays to shape (1,).
    arrays = [np.asarray(a, order="C") for a in arrays]
    sizes = [a.nbytes for a in arrays]
    total = sum(sizes)
    if out is None:
        out = np.empty(total, np.uint8)
    elif out.nbytes < total:
        raise ValueError(f"pack_buffers out ({out.nbytes}) < total ({total})")
    lib = get_lib()
    offsets = np.cumsum([0] + sizes[:-1]).tolist()
    if lib is None:
        view = _byte_view(out)
        for a, off, sz in zip(arrays, offsets, sizes):
            view[off : off + sz] = _byte_view(a)
        return out
    lib.hostbuf_gatherv(
        out.ctypes.data_as(ctypes.c_void_p), _ptr_array(arrays),
        _as_u64_array(sizes), _as_u64_array(offsets),
        len(arrays), _default_threads(n_threads),
    )
    return out


def unpack_buffers(
    buf: np.ndarray, arrays: Sequence[np.ndarray], n_threads: int = 0
) -> None:
    """Scatter a contiguous uint8 buffer back into preallocated
    C-contiguous arrays (unpack_params) — the checkpoint loader's inverse
    of :func:`pack_buffers`."""
    sizes = [a.nbytes for a in arrays]
    total = sum(sizes)
    if buf.nbytes < total:
        raise ValueError(f"unpack_buffers buf ({buf.nbytes}) < total ({total})")
    for a in arrays:
        if not a.flags["C_CONTIGUOUS"]:
            raise ValueError("unpack_buffers targets must be C-contiguous")
    offsets = np.cumsum([0] + sizes[:-1]).tolist()
    lib = get_lib()
    if lib is None:
        view = _byte_view(buf)
        for a, off, sz in zip(arrays, offsets, sizes):
            _byte_view(a)[:] = view[off : off + sz]
        return
    lib.hostbuf_scatterv(
        buf.ctypes.data_as(ctypes.c_void_p), _ptr_array(arrays),
        _as_u64_array(sizes), _as_u64_array(offsets),
        len(arrays), _default_threads(n_threads),
    )


def parallel_gather(items: Sequence[np.ndarray], n_threads: int = 0) -> np.ndarray:
    """Stack equal-shaped C-contiguous arrays into one batch array with a
    native multithreaded memcpy — the pack_params idea where it still pays
    on TPU hosts (np.stack is GIL-bound).  The batch-assembly path of
    ``datasets.toy.batch_iterator`` (all examples feed through it)."""
    first = np.asarray(items[0], order="C")
    if any(
        np.shape(a) != first.shape or np.asarray(a).dtype != first.dtype
        for a in items[1:]
    ):
        raise ValueError("parallel_gather needs equal-shaped same-dtype items")
    out = np.empty((len(items),) + first.shape, first.dtype)
    pack_buffers(items, out=out.reshape(-1).view(np.uint8),
                 n_threads=n_threads)
    return out


class NativeQueue:
    """Bounded byte-buffer queue backed by the C++ ring queue (threading.Queue
    fallback) — a host-side staging structure for byte-level pipelines (raw
    record readers, serialized checkpoint chunks).  Note
    ``iterators.create_prefetch_iterator`` stages ``jax.Array`` batches
    through a plain ``queue.Queue`` with its own stop-event shutdown; this
    class is for payloads that live as bytes on the host side."""

    def __init__(self, capacity: int = 4):
        self._lib = get_lib()
        if self._lib is not None:
            self._h = self._lib.hostbuf_queue_new(capacity)
        else:
            import queue
            import threading

            self._q = queue.Queue(maxsize=capacity)
            self._closed = threading.Event()

    def push(self, data: bytes) -> bool:
        if self._lib is not None:
            return self._lib.hostbuf_queue_push(self._h, data, len(data)) == 0
        # Fallback mirrors the C++ contract: push blocks while full, fails
        # once closed.
        while not self._closed.is_set():
            try:
                self._q.put(data, timeout=0.05)
                return True
            except Exception:
                continue
        return False

    def pop(self, max_len: int) -> bytes:
        if self._lib is not None:
            buf = ctypes.create_string_buffer(max_len)
            n = self._lib.hostbuf_queue_pop(self._h, buf, max_len)
            return buf.raw[:n]
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except Exception:
                if self._closed.is_set():
                    return b""
                continue
            return item[:max_len]

    def size(self) -> int:
        if self._lib is not None:
            return int(self._lib.hostbuf_queue_size(self._h))
        return self._q.qsize()

    def close(self):
        if self._lib is not None:
            self._lib.hostbuf_queue_close(self._h)
        else:
            self._closed.set()

    def __del__(self):
        try:
            if self._lib is not None:
                self._lib.hostbuf_queue_free(self._h)
        except Exception:
            pass
