"""Profiling hooks — the tracing subsystem the reference lacked.

SURVEY §5.1: the reference relied on Chainer's TimerHook + external nvprof.
Here profiling is first-class: ``trace()`` wraps ``jax.profiler`` (produces
a TensorBoard/Perfetto trace of device steps incl. collective overlap),
``annotate()`` stamps named regions, and ``StepTimer`` gives the in-loop
throughput/bandwidth numbers that back ``bench.py`` — including the
``allreduce bus-bw GB/s`` metric BASELINE.json tracks.
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax


def setup_compilation_cache(cache_dir: Optional[str] = None) -> None:
    """Point JAX's persistent compilation cache at ``cache_dir`` (default:
    ``$CHAINERMN_TPU_JAX_CACHE``, else ``<repo>/.jax_cache``).  Big step
    functions over this environment's remote-compile tunnel are slow to
    compile; sharing one on-disk cache across bench/test/example entry
    points makes re-runs start in seconds.  Call before the first jit; a
    no-op on failure.  The env override exists for installed trees and
    multi-checkout machines, where a repo-relative path is wrong."""
    import os

    if cache_dir is None:
        cache_dir = os.environ.get("CHAINERMN_TPU_JAX_CACHE")
    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".jax_cache"
        )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass


def slope_time(run, n1: int, n2: Optional[int] = None) -> float:
    """Per-iteration time via the two-point slope ``(T₂−T₁)/(n₂−n₁)``.

    ``run(n)`` must execute ``n`` iterations (chained, or relying on the
    device's FIFO program order) and end with ONE :func:`sync`.  On the
    tunneled TPU backend that final readback costs ~100 ms (measured;
    docs/performance.md "Measuring"), so a single run over-reports
    per-iteration time by ~100/n ms — the slope between two run lengths
    cancels the constant exactly.  Used by bench.py and benchmarks/*.
    """
    if n2 is None:
        n2 = 5 * n1
    t1, t2 = run(n1), run(n2)
    return (t2 - t1) / (n2 - n1)


def median_slope(run, n1: int = 5, repeats: int = 3):
    """Median of ``repeats`` independent :func:`slope_time` measurements,
    with the sorted samples — on the tunneled chip one slope sample is
    not a number (run-to-run variance has masqueraded as real deltas
    before).  The shared timing backbone of ``bench.py`` and the kernel
    autotuner (``chainermn_tpu.tuning``).  Returns
    ``(median_seconds_per_iter, sorted_samples)``."""
    samples = sorted(slope_time(run, n1) for _ in range(repeats))
    return samples[len(samples) // 2], samples


def sync(tree):
    """Hard execution barrier: force every array in ``tree`` to finish
    executing by reading one element back to the host.

    ``jax.block_until_ready`` only waits for the *buffer* to be ready, and
    some PJRT backends (notably tunneled/remote plugins) report readiness at
    dispatch time — timing loops synchronized with it then measure dispatch
    rather than compute.  A device→host transfer of any output element
    cannot complete before the producing program does, on every backend.
    Use this (not ``block_until_ready``) around benchmark timing regions.

    For sharded arrays only one element of one locally-addressable shard is
    fetched: a whole-array ``device_get`` would gather the global buffer
    (and raise on multi-process runs where remote shards are not
    addressable), while one local element is enough to order this host
    behind the producing program.
    """
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            jax.device_get(shards[0].data.ravel()[:1])
        elif hasattr(leaf, "ravel"):
            jax.device_get(leaf.ravel()[:1])
    return tree


@contextlib.contextmanager
def trace(logdir: str = "/tmp/chainermn_tpu_trace"):
    """Capture a device-level profiler trace around the with-block.

    Degrades to a timing-only no-op (the with-block still runs, the
    logdir is still yielded) when ``jax.profiler`` is unavailable or the
    backend refuses to start a trace — stripped jax builds and PJRT
    plugins without profiler support must not take down a training run
    that merely asked for visibility."""
    prof = getattr(jax, "profiler", None)
    started = False
    if prof is not None and hasattr(prof, "start_trace"):
        try:
            prof.start_trace(logdir)
            started = True
        except Exception:
            pass
    try:
        yield logdir
    finally:
        if started:
            try:
                prof.stop_trace()
            except Exception:
                pass


def annotate(name: str):
    """Named region for profiler timelines (usable as context manager).
    A null context when ``jax.profiler`` is unavailable, so span-heavy
    code (``observability.span``) runs unchanged on stripped builds."""
    prof = getattr(jax, "profiler", None)
    if prof is None or not hasattr(prof, "TraceAnnotation"):
        return contextlib.nullcontext()
    try:
        return prof.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


class StepTimer:
    """Steady-state step timing with warmup discard."""

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self._times = []
        self._t0: Optional[float] = None
        self._count = 0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._count += 1
        if self._count > self.warmup:
            self._times.append(dt)
        return False

    @property
    def mean_s(self) -> float:
        return sum(self._times) / max(len(self._times), 1)

    def throughput(self, items_per_step: int) -> float:
        return items_per_step / self.mean_s if self._times else 0.0


def allreduce_bus_bandwidth_gbs(
    nbytes: int, n_devices: int, seconds_per_allreduce: float
) -> float:
    """Ring-allreduce bus bandwidth: each chip moves 2(n-1)/n of the buffer
    over its links per allreduce — the standard bus-bw formula, reported in
    GB/s as BASELINE.json asks."""
    if seconds_per_allreduce <= 0:
        return 0.0
    moved = 2 * (n_devices - 1) / max(n_devices, 1) * nbytes
    return moved / seconds_per_allreduce / 1e9
