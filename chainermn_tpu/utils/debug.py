"""Collective-order debug mode — the race-detection analogue.

SURVEY §5.2: the reference had no sanitizer; stream-ordering correctness
was by construction.  On TPU the corresponding hazard is a *collective
order mismatch* across hosts (host A's program issues psum/allgather in a
different sequence than host B's — the SPMD contract breach that shows up
as a hang or garbage).  This debug mode makes the contract checkable:

* ``CollectiveTrace`` wraps a communicator; every traced collective call
  records (op, shape, dtype, axes) into an order log at *trace time* —
  exactly when the SPMD program's collective sequence is fixed.
* Host/object-plane ops (``send_obj``/``recv_obj``/``bcast_obj``/
  ``gather_obj``/``allreduce_obj``/``scatter_obj``/``barrier``) are
  recorded too — (op, plane namespace, endpoint ints, payload type) —
  because the SPMD contract the object plane trusts (same ops, same
  order, on every process) is exactly what this mode exists to check.
  Construction-order divergence is additionally caught without debug
  mode: every plane publishes its construction site and validates it
  against rank 0's at first use (kvtransport.ObjectPlane), and a
  barrier-sequence skew fails fast inside ``sync_global_devices``'s
  name-equality assertion.
* ``fingerprint()`` hashes the log (native crc32c);
  ``verify_across_hosts()`` allgathers the fingerprint over the object
  plane and raises on divergence, pinpointing the first differing entry.
"""

from __future__ import annotations

import json
from typing import Any, List

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.utils import native

_WRAPPED = (
    "allreduce", "bcast", "allgather", "gather", "alltoall",
    "reduce_scatter", "scatter", "ppermute", "allreduce_grad",
    "broadcast_data",
)

# Host/object-plane ops: recorded by endpoint metadata and payload TYPE
# (not content — payloads may be huge and rank-varying by design).
_WRAPPED_OBJ = (
    "send_obj", "recv_obj", "bcast_obj", "gather_obj", "allgather_obj",
    "allreduce_obj", "scatter_obj", "barrier",
)


class CollectiveTrace:
    """Wrap ``comm`` so every collective appends to an order log.

    Use as ``dbg = CollectiveTrace(comm)`` and pass ``dbg`` wherever the
    communicator goes; it proxies everything else through.
    """

    def __init__(self, comm: CommunicatorBase):
        self._comm = comm
        self.log: List[str] = []
        # The cross-host equality check covers only SYMMETRIC ops: p2p
        # send_obj/recv_obj are rank-asymmetric by design (the sender logs
        # a send, the receiver a recv), so they appear in `log` for the
        # diagnostic trail but not in the verified sequence.
        self._sym: List[str] = []

    def _record(self, op: str, x: Any, **meta):
        import jax

        leaves = jax.tree.leaves(x)
        desc = [
            {"shape": list(getattr(l, "shape", ())),
             "dtype": str(getattr(l, "dtype", type(l).__name__))}
            for l in leaves
        ]
        entry = json.dumps({"op": op, "args": desc, **meta}, sort_keys=True)
        self.log.append(entry)
        self._sym.append(entry)

    def _record_obj(self, op: str, args, kwargs):
        meta = {
            "plane": self._comm._obj_plane.namespace,
            "args": [
                a if isinstance(a, (int, str)) else type(a).__name__
                for a in args
            ],
            "kwargs": {
                k: v if isinstance(v, (int, str)) else type(v).__name__
                for k, v in kwargs.items()
            },
        }
        entry = json.dumps({"op": op, **meta}, sort_keys=True)
        self.log.append(entry)
        if op not in ("send_obj", "recv_obj"):
            self._sym.append(entry)

    def __getattr__(self, name):
        attr = getattr(self._comm, name)
        if name in _WRAPPED and callable(attr):
            def traced(x, *args, **kwargs):
                self._record(name, x)
                return attr(x, *args, **kwargs)

            return traced
        if name in _WRAPPED_OBJ and callable(attr):
            def traced_obj(*args, **kwargs):
                self._record_obj(name, args, kwargs)
                return attr(*args, **kwargs)

            return traced_obj
        return attr

    # -- verification ---------------------------------------------------
    def fingerprint(self) -> int:
        return native.crc32c("\n".join(self._sym).encode())

    def verify_across_hosts(self) -> int:
        """Raise RuntimeError if any host recorded a different (symmetric)
        collective/object-plane order; returns the common fingerprint
        otherwise."""
        fp = self.fingerprint()
        fps = self._comm.gather_obj(fp)
        if len(set(fps)) > 1:
            # The full symbolic logs are bulky and only the diagnosis
            # needs them: point-to-root gather (MPI_Gather wire profile —
            # non-root ranks ship their log to rank 0 and fetch nothing).
            # Coordination-service-less runs keep the old symmetric
            # allgather: the diagnostic must never be masked by a
            # transport requirement.
            from chainermn_tpu.communicators import kvtransport

            if kvtransport.available():
                logs = self._comm.gather_obj(self._sym, root=0)
            else:
                logs = self._comm.gather_obj(self._sym)
            if logs is None:
                # Point-to-root path, non-root rank: the detail lives at
                # rank 0 by design (that is the wire saving).
                raise RuntimeError(
                    f"collective order mismatch across hosts: fingerprints "
                    f"{fps}; rank 0 holds the first differing call"
                )
            first_diff = None
            for i in range(max(len(l) for l in logs)):
                entries = {
                    r: (l[i] if i < len(l) else "<missing>")
                    for r, l in enumerate(logs)
                }
                if len(set(entries.values())) > 1:
                    first_diff = (i, entries)
                    break
            raise RuntimeError(
                f"collective order mismatch across hosts: fingerprints {fps}; "
                f"first differing call #{first_diff[0]}: {first_diff[1]}"
            )
        return fp

    def reset(self):
        self.log.clear()
        self._sym.clear()
