"""Collective-order debug mode — the race-detection analogue.

SURVEY §5.2: the reference had no sanitizer; stream-ordering correctness
was by construction.  On TPU the corresponding hazard is a *collective
order mismatch* across hosts (host A's program issues psum/allgather in a
different sequence than host B's — the SPMD contract breach that shows up
as a hang or garbage).  This debug mode makes the contract checkable:

* ``CollectiveTrace`` wraps a communicator; every traced collective call
  records (op, shape, dtype, axes) into an order log at *trace time* —
  exactly when the SPMD program's collective sequence is fixed.
* ``fingerprint()`` hashes the log (native crc32c);
  ``verify_across_hosts()`` allgathers the fingerprint over the object
  plane and raises on divergence, pinpointing the first differing entry.
"""

from __future__ import annotations

import json
from typing import Any, List, Optional

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.utils import native

_WRAPPED = (
    "allreduce", "bcast", "allgather", "gather", "alltoall",
    "reduce_scatter", "scatter", "ppermute", "allreduce_grad",
    "broadcast_data",
)


class CollectiveTrace:
    """Wrap ``comm`` so every collective appends to an order log.

    Use as ``dbg = CollectiveTrace(comm)`` and pass ``dbg`` wherever the
    communicator goes; it proxies everything else through.
    """

    def __init__(self, comm: CommunicatorBase):
        self._comm = comm
        self.log: List[str] = []

    def _record(self, op: str, x: Any, **meta):
        import jax

        leaves = jax.tree.leaves(x)
        desc = [
            {"shape": list(getattr(l, "shape", ())),
             "dtype": str(getattr(l, "dtype", type(l).__name__))}
            for l in leaves
        ]
        self.log.append(json.dumps(
            {"op": op, "args": desc, **meta}, sort_keys=True
        ))

    def __getattr__(self, name):
        attr = getattr(self._comm, name)
        if name in _WRAPPED and callable(attr):
            def traced(x, *args, **kwargs):
                self._record(name, x)
                return attr(x, *args, **kwargs)

            return traced
        return attr

    # -- verification ---------------------------------------------------
    def fingerprint(self) -> int:
        return native.crc32c("\n".join(self.log).encode())

    def verify_across_hosts(self) -> int:
        """Raise RuntimeError if any host recorded a different collective
        order; returns the common fingerprint otherwise."""
        fp = self.fingerprint()
        fps = self._comm.gather_obj(fp)
        if len(set(fps)) > 1:
            logs = self._comm.gather_obj(self.log)
            first_diff = None
            for i in range(max(len(l) for l in logs)):
                entries = {
                    r: (l[i] if i < len(l) else "<missing>")
                    for r, l in enumerate(logs)
                }
                if len(set(entries.values())) > 1:
                    first_diff = (i, entries)
                    break
            raise RuntimeError(
                f"collective order mismatch across hosts: fingerprints {fps}; "
                f"first differing call #{first_diff[0]}: {first_diff[1]}"
            )
        return fp

    def reset(self):
        self.log.clear()
