"""Pseudo-connect — graft a delegate variable into the graph.

Reference: REF:chainermn/functions/pseudo_connect.py — ``PseudoConnect``
returns its actual variables unchanged in forward, but wires the delegate
variable into the graph so backward reaches the ``Send`` node even when the
sent tensor has no local consumer; also merges multiple delegates.

TPU-native translation: attach a zero-valued contribution of the delegate's
token to the actual variable.  ``token`` is a zero-size slice of the
in-flight ppermute result, so summing it adds exactly 0.0 to the value while
creating the data dependence that (a) sequences the transfer before any
consumer of the actual variable and (b) routes cotangents through the
ppermute transpose back to the sender — the delegate-variable semantics,
expressed as dataflow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from chainermn_tpu.functions.point_to_point import DelegateVariable


def _token_zero(delegate: DelegateVariable):
    toks = jax.tree.leaves(delegate.token)
    z = jnp.zeros((), toks[0].dtype if toks else jnp.float32)
    for t in toks:
        z = z + jnp.sum(t)
    return z


def pseudo_connect(delegate_variable, *actual_variables):
    """Reference-parity ``pseudo_connect(delegate, *actuals)``.

    With no actuals: merges nothing and returns the delegate (it is already
    graph-connected through its token).  With actuals: returns them with the
    delegate's gradient path attached; multiple delegates may be chained by
    passing another delegate as an "actual".
    """
    if not actual_variables:
        return delegate_variable

    z = _token_zero(delegate_variable)

    def graft(v):
        if isinstance(v, DelegateVariable):
            # Delegate merging: combine tokens into a fresh delegate.
            merged = jax.tree.map(
                lambda t: t + z.astype(t.dtype)[()] * jnp.ones_like(t), v.token
            )
            return DelegateVariable(token=merged, payload=v.payload, dst=v.dst)
        return jax.tree.map(lambda x: x + z.astype(x.dtype), v)

    out = tuple(graft(v) for v in actual_variables)
    return out[0] if len(out) == 1 else out
