"""Differentiable point-to-point communication — the heart of model/pipeline
parallelism.

Reference: REF:chainermn/functions/point_to_point_communication.py —
``Send`` issues ``comm.send`` in forward and returns a zero-size dummy
"delegate variable" whose ``backward`` receives the incoming gradient;
``Recv`` blocks on ``comm.recv`` in forward and sends the gradient back in
``backward``.  Chaining the delegate variable into downstream calls (or the
final loss via ``pseudo_connect``) makes cross-process backprop fire in the
right order (SURVEY §3.3).

TPU-native translation (SURVEY §7 "hard part 1"): under a single traced
SPMD program there is no imperative graph whose topological order must be
coaxed — *data dependence* is the ordering mechanism, and a transfer is one
``lax.ppermute`` whose transpose (ppermute along the reversed permutation)
is exactly the reference's backward send/recv pair.  JAX differentiates
``ppermute`` natively, so no ``custom_vjp`` is needed; what remains of the
reference machinery is its *API shape*:

* ``send(x, comm, dst, src)`` issues the transfer and returns a
  :class:`DelegateVariable` — a zero-size slice of the in-flight value, so
  (a) downstream consumers can sequence on it and (b) gradients reaching
  the delegate flow back through the ppermute to ``x`` on the sender,
  mirroring the reference's delegate trick;
* ``recv(comm, delegate_variable)`` unwraps the transferred payload on the
  receiving rank (zeros elsewhere — every device runs the same program);
* both calls appear in *one* program rather than in two different ranks'
  scripts; ``MultiNodeChainList`` (chainermn_tpu.links) does the
  role-dispatch the reference's per-rank processes did.

Explicit ``src`` is the one signature divergence from the reference
(``send(x, communicator, rank)``): a ChainerMN process implicitly knew "I
am rank 3"; a traced SPMD program describes all ranks at once, so the
transfer's endpoints are both named at trace time.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from chainermn_tpu.communicators.base import CommunicatorBase


class DelegateVariable(NamedTuple):
    """The reference's zero-size delegate variable, with the in-flight value
    riding along (payload is meaningful on the destination rank only)."""

    token: jnp.ndarray  # shape (0,)-per-leaf grad-carrying slice
    payload: Any        # the transferred pytree
    dst: int            # destination flat rank (static)

    def __add__(self, other):
        # Delegate merging convenience, as the reference's pseudo_connect
        # supports combining multiple delegates.
        from chainermn_tpu.functions.pseudo_connect import pseudo_connect

        return pseudo_connect(self, other)


def _p2p(tree, comm: CommunicatorBase, src: int, dst: int):
    perm = [(src, dst)]
    return jax.tree.map(lambda x: comm.ppermute(x, perm), tree)


def send(x, communicator: CommunicatorBase, rank: int, src: int) -> DelegateVariable:
    """Transfer pytree ``x`` from flat device rank ``src`` to ``rank``.

    Returns the delegate variable (reference ``Send``'s dummy output).  The
    transferred payload travels on the delegate so the matching ``recv`` is
    a pure unwrap — one ppermute per logical transfer, like one MPI_Send.
    """
    payload = _p2p(x, communicator, src, rank)
    token = jax.tree.map(lambda p: jnp.ravel(p)[:0], payload)
    return DelegateVariable(token=token, payload=payload, dst=rank)


def recv(
    communicator: CommunicatorBase,
    rank: int | None = None,
    delegate_variable: DelegateVariable | None = None,
):
    """Unwrap the value sent by the matching ``send`` (reference ``Recv``).

    ``rank`` (the source, per the reference signature) is accepted for API
    parity and validated when the delegate knows its endpoints.
    """
    if delegate_variable is None:
        raise ValueError(
            "recv() needs the delegate_variable returned by send(): in a "
            "traced SPMD program the transfer is a single ppermute issued "
            "by send, not a blocking wait"
        )
    return delegate_variable.payload


def send_recv(x, communicator: CommunicatorBase, src: int, dst: int):
    """One-shot SPMD point-to-point: value of ``x`` on ``src`` arrives at
    ``dst`` (zeros elsewhere).  The primitive both reference functions
    lower to here."""
    return _p2p(x, communicator, src, dst)


def ring_exchange(x, communicator: CommunicatorBase, shift: int = 1):
    """Rotate values around the communicator's flattened world — the
    collective under ring attention (chainermn_tpu.parallel.ring_attention)
    and ``ppermute`` pipelines."""
    n = communicator.device_size
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.tree.map(lambda v: communicator.ppermute(v, perm), x)
