"""Differentiable collective communication.

Reference: REF:chainermn/functions/collective_communication.py —
``AllGather``/``AllToAll``/``Bcast``/``Gather``/``Scatter`` as Chainer
``Function`` classes whose ``backward`` issues the transpose collective
(e.g. allgather's backward reduce-scatters the incoming gradients).  These
enable channel/tensor-style parallelism: the parallel_convolution example
allgathers activations computed per-rank over a channel shard.

TPU-native translation: XLA's collectives are linear operators and JAX
differentiates them natively with exactly the transposes the reference
hand-wrote (``all_gather``ᵀ = ``psum_scatter``, ``psum``ᵀ = broadcast,
``all_to_all``ᵀ = ``all_to_all`` reversed, ``ppermute``ᵀ = inverse
ppermute).  So the "Function classes" dissolve into thin wrappers over the
communicator's traced collectives — kept as module-level functions for
reference API parity and a place to document the autodiff contract.
All must be called inside ``shard_map`` over the communicator's axes.
"""

from __future__ import annotations

from chainermn_tpu.communicators.base import CommunicatorBase


def allgather(communicator: CommunicatorBase, x, axis: int = 0, tiled: bool = False):
    """Differentiable allgather (reference ``chainermn.functions.allgather``).

    Forward: every rank receives the concatenation over the world axis.
    Backward (native): reduce-scatter of the cotangent — each rank gets the
    sum of all ranks' gradients for its own contribution.
    """
    return communicator.allgather(x, axis=axis, tiled=tiled)


def alltoall(communicator: CommunicatorBase, x, split_axis: int = 0, concat_axis: int = 0):
    """Differentiable all-to-all (reference ``chainermn.functions.alltoall``).
    Backward is the reverse all-to-all."""
    return communicator.alltoall(x, split_axis=split_axis, concat_axis=concat_axis)


def bcast(communicator: CommunicatorBase, x, root: int = 0):
    """Differentiable broadcast. Backward sums cotangents to the root (the
    psum in the masked formulation is its own transpose)."""
    return communicator.bcast(x, root)


def gather(communicator: CommunicatorBase, x, root: int = 0, axis: int = 0):
    """Differentiable point-to-root gather: root receives the stack, other
    ranks zeros (the reference returns None off-root).  Backward scatters
    the stacked cotangent back to each source."""
    return communicator.gather(x, root=root, axis=axis)


def scatter(communicator: CommunicatorBase, x, root: int = 0):
    """Differentiable scatter. Backward gathers the chunk cotangents back."""
    return communicator.scatter(x, root=root)


def allreduce(communicator: CommunicatorBase, x):
    """Differentiable allreduce (sum). Backward broadcasts — i.e. psum's
    transpose — matching the reference's allreduce Function."""
    return communicator.allreduce(x, "sum")
