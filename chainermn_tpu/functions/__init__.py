"""Model-parallel autodiff API — facade mirroring REF:chainermn/functions/.

``send``/``recv``/``pseudo_connect`` (point-to-point) and the
differentiable collectives (``allgather``/``alltoall``/``bcast``/
``gather``/``scatter``) as autodiff-transparent operations usable inside a
traced SPMD program.
"""

from chainermn_tpu.functions.point_to_point import (  # noqa: F401
    DelegateVariable,
    send,
    recv,
    send_recv,
)
from chainermn_tpu.functions.pseudo_connect import pseudo_connect  # noqa: F401
from chainermn_tpu.functions.collectives import (  # noqa: F401
    allgather,
    alltoall,
    bcast,
    gather,
    scatter,
    allreduce,
)
