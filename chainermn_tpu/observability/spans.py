"""Trace spans — named regions visible in three sinks at once.

``with span("fwd"):`` stamps the region onto the profiler timeline
(``utils/profiling.annotate`` → Perfetto/TensorBoard, a no-op when
``jax.profiler`` is unavailable), measures the host-side duration, and
publishes it to whichever telemetry sinks are active: the current
:class:`~chainermn_tpu.observability.reporter.Reporter` (as a
``span/<name>`` scalar + histogram) and the current
:class:`~chainermn_tpu.observability.step_log.StepRecorder` (buffered
into the next step row's ``spans`` field).  With neither active the
cost is two ``perf_counter`` calls — cheap enough to leave in library
hot paths permanently, the design stance nvprof-era tooling never
allowed the reference.

Host-side durations measure *dispatch + any blocking* — under JAX's
async dispatch a span around a jitted call is NOT device time (the
profiler trace is); they are still the right signal for host-bound
stalls (input pipeline, blocking readbacks, compile storms).

Inside traced code use :func:`named_scope` instead: it tags the HLO ops
so the regions survive into the compiled profile.
"""

from __future__ import annotations

import contextlib
import time

from chainermn_tpu.observability import reporter as _reporter
from chainermn_tpu.observability import step_log as _step_log


def telemetry_active() -> bool:
    """True when a Reporter or StepRecorder is installed — the gate
    library call sites use to keep the zero-telemetry hot path free of
    even span bookkeeping."""
    return (
        _reporter.get_reporter() is not None
        or _step_log.current_recorder() is not None
    )


@contextlib.contextmanager
def span(name: str):
    """Named host-side region: profiler annotation + duration fan-out.

    Exception-safe: the duration is recorded (and the span marked as an
    error) even when the body raises, so a failed request can't leave a
    half-open span behind for the next request on the thread.  The
    exception propagates unchanged.
    """
    from chainermn_tpu.utils.profiling import annotate

    t0 = time.perf_counter()
    err = False
    try:
        with annotate(name):
            yield
    except BaseException:
        err = True
        raise
    finally:
        dt = time.perf_counter() - t0
        rep = _reporter.get_reporter()
        if rep is not None:
            rep.observe(f"span/{name}", dt)
            rep.histogram_observe(f"span/{name}", dt)
            if err:
                rep.count(f"span/{name}/errors", 1)
        rec = _step_log.current_recorder()
        if rec is not None:
            rec.add_span(name, dt)
            if err:
                rec.add_span(f"{name}/error", dt)


def named_scope(name: str):
    """Device-side region naming for TRACED code (fwd/bwd/allreduce/
    opt-update): tags the ops' HLO metadata so the regions appear in
    compiled-program profiles.  Falls back to a null context on jax
    builds without ``named_scope``."""
    import jax

    try:
        return jax.named_scope(name)
    except Exception:
        return contextlib.nullcontext()
