"""Structured step-event log — one JSONL row per training event.

The reference's ``LogReport`` serialized its observation dict to
``log`` (JSON) once per report interval; the TPU-native version logs at
*step* granularity with crash-honest file semantics, because the
north-star scaling work needs per-step evidence (step time, throughput,
loss, grad norm, recompiles, device memory) rather than per-interval
averages.

File contract:

* **Atomic append** — each row is one ``os.write`` of a complete
  ``...\\n`` line on an ``O_APPEND`` descriptor, so concurrent writers
  (the train loop, the prefetch thread, a monitoring listener) never
  interleave bytes within a line.
* **Rotation** — when a write would push the file past ``rotate_bytes``
  the file rotates through ``path.1 … path.<max_files>`` (highest =
  oldest), bounding disk for soak runs.
* **Crash-safe recovery** — a SIGKILL mid-write leaves at most one
  truncated final line; :func:`read_records` skips it and
  :func:`recover` truncates it in place, so a resumed run appends to a
  valid file.

Compile/recompile visibility rides ``jax.monitoring`` where available:
the recorder registers an event-duration listener and turns every
``...compile...`` event into a ``{"event": "compile", ...}`` row —
the per-step recompile evidence XLA profiling otherwise hides in logs.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Iterator, List, Optional


def _jsonable(v):
    """Coerce numpy/jax scalars (and 0-d arrays) to plain Python; leave
    everything json.dumps already handles untouched."""
    if isinstance(v, (str, int, bool)) or v is None:
        return v
    if isinstance(v, float):
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    try:
        return float(v)  # np.float32, jax scalar arrays, np.int64, ...
    except Exception:
        return str(v)


def device_memory_stats() -> Optional[dict]:
    """Best-effort ``{bytes_in_use, peak_bytes_in_use, ...}`` from the
    first local device; ``None`` where the backend has no allocator
    stats (CPU) — callers omit the field rather than fake it."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        if not stats:
            return None
        keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "num_allocs")
        return {k: int(stats[k]) for k in keep if k in stats}
    except Exception:
        return None


class StepRecorder:
    """Append-only JSONL event recorder for one process.

    ``record(event, **fields)`` writes an arbitrary event row;
    :meth:`step` is the train-loop entry point — it stamps wall time,
    computes the host-side step duration since the previous ``step``
    call, derives throughput from ``items``, attaches any span
    durations buffered by :func:`chainermn_tpu.observability.span`,
    and samples device memory every ``mem_every`` steps.

    Use as a context manager (``with StepRecorder(path) as rec:``) to
    also install it as the *current* recorder that spans and the
    instrumented optimizer publish into.
    """

    def __init__(
        self,
        path: str,
        rotate_bytes: Optional[int] = None,
        max_files: int = 3,
        rank: int = 0,
        capture_compile_events: bool = True,
        mem_every: int = 1,
        clock=time.perf_counter,
    ):
        self.path = str(path)
        self.rotate_bytes = rotate_bytes
        self.max_files = max(1, int(max_files))
        self.rank = int(rank)
        self.mem_every = max(0, int(mem_every))
        self._clock = clock
        self._lock = threading.Lock()
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self._fd = os.open(
            self.path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644
        )
        self._prev_t: Optional[float] = None
        self._step_count = 0
        self._pending_spans: dict = {}
        self._pending_compiles: list = []
        self._unregister = None
        if capture_compile_events:
            self._register_compile_listener()

    # -- jax.monitoring bridge ----------------------------------------
    def _register_compile_listener(self):
        try:
            from jax import monitoring
        except Exception:
            return

        def listener(event: str, secs: float, **kw):
            if "compile" not in event:
                return
            # Buffer only: listeners fire inside the compile path and
            # must not re-enter file IO or raise into XLA.
            with self._lock:
                self._pending_compiles.append((event, float(secs)))

        try:
            monitoring.register_event_duration_secs_listener(listener)
        except Exception:
            return

        def unregister():
            try:
                from jax._src import monitoring as _m

                _m._unregister_event_duration_listener_by_callback(listener)
            except Exception:
                pass

        self._unregister = unregister

    # -- write side ----------------------------------------------------
    def record(self, event: str, **fields) -> None:
        """Append one ``{"event": event, "rank": r, "t": wall, ...}``
        row atomically (with rotation)."""
        row = {"event": event, "rank": self.rank, "t": time.time()}
        row.update({k: _jsonable(v) for k, v in fields.items()})
        line = (json.dumps(row) + "\n").encode("utf-8")
        with self._lock:
            self._maybe_rotate(len(line))
            os.write(self._fd, line)

    def _maybe_rotate(self, incoming: int) -> None:
        if not self.rotate_bytes:
            return
        try:
            size = os.fstat(self._fd).st_size
        except OSError:
            return
        if size == 0 or size + incoming <= self.rotate_bytes:
            return
        os.close(self._fd)
        for i in range(self.max_files - 1, 0, -1):
            src = self.path if i == 1 else f"{self.path}.{i - 1}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i}")
        self._fd = os.open(
            self.path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644
        )

    def add_span(self, name: str, seconds: float) -> None:
        """Buffer a span duration for the next :meth:`step` row (called
        by :func:`chainermn_tpu.observability.span`)."""
        with self._lock:
            self._pending_spans[name] = (
                self._pending_spans.get(name, 0.0) + seconds
            )

    def step(self, step: Optional[int] = None, items: Optional[int] = None,
             **fields) -> dict:
        """Record one training step.  Returns the written row (handy for
        tests and rank-0 printing).

        ``dt`` is the host wall time since the previous ``step`` call
        (absent on the first); ``items`` (tokens or images in the step)
        derives ``per_sec``.  Extra ``fields`` (loss, grad_norm, lr, …)
        pass through; jax/numpy scalars are read back to floats HERE —
        callers that care about async dispatch should pass host values.
        """
        now = self._clock()
        with self._lock:
            dt = None if self._prev_t is None else now - self._prev_t
            self._prev_t = now
            self._step_count += 1
            n = self._step_count
            spans, self._pending_spans = self._pending_spans, {}
            compiles, self._pending_compiles = self._pending_compiles, []
        for event, secs in compiles:
            self.record("compile", name=event, secs=secs)
        row: dict = {"step": n - 1 if step is None else int(step)}
        if dt is not None:
            row["dt"] = dt
            if items is not None:
                row["per_sec"] = items / dt if dt > 0 else 0.0
        if items is not None:
            row["items"] = int(items)
        if spans:
            row["spans"] = spans
        if self.mem_every and n % self.mem_every == 0:
            mem = device_memory_stats()
            if mem is not None:
                row["mem"] = mem
        row.update(fields)
        self.record("step", **row)
        row["event"] = "step"
        return row

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None
        if self._unregister is not None:
            self._unregister()
            self._unregister = None

    # -- current-recorder stack ---------------------------------------
    def __enter__(self):
        install(self)
        return self

    def __exit__(self, *exc):
        uninstall(self)
        self.close()
        return False


_stack: list = []
_stack_lock = threading.Lock()


def current_recorder() -> Optional[StepRecorder]:
    with _stack_lock:
        return _stack[-1] if _stack else None


def install(recorder: StepRecorder) -> None:
    with _stack_lock:
        _stack.append(recorder)


def uninstall(recorder: StepRecorder) -> None:
    with _stack_lock:
        if recorder in _stack:
            _stack.remove(recorder)


# ---------------------------------------------------------------------------
# Read side
# ---------------------------------------------------------------------------
def _iter_one(path: str, strict: bool) -> Iterator[dict]:
    with open(path, "rb") as f:
        data = f.read()
    lines = data.split(b"\n")
    # A crash mid-write leaves the LAST line unterminated; any other
    # undecodable line is real corruption.
    complete, tail = lines[:-1], lines[-1]
    for line in complete:
        if not line.strip():
            continue
        try:
            yield json.loads(line)
        except ValueError:
            if strict:
                raise
            continue
    if tail.strip():
        try:
            yield json.loads(tail)
        except ValueError:
            if strict:
                raise
            # partial final line: the crash-recovery case — skipped.


def read_records(path: str, include_rotated: bool = True,
                 strict: bool = False) -> List[dict]:
    """Parsed rows, oldest first, skipping a truncated final line.

    ``include_rotated``: read ``path.N … path.1`` (oldest → newest)
    before ``path`` so summaries cover the whole retained window."""
    paths = []
    if include_rotated:
        n = 1
        while os.path.exists(f"{path}.{n}"):
            n += 1
        paths.extend(f"{path}.{i}" for i in range(n - 1, 0, -1))
    if os.path.exists(path):
        paths.append(path)
    if not paths:
        raise FileNotFoundError(path)
    rows: List[dict] = []
    for p in paths:
        rows.extend(_iter_one(p, strict))
    return rows


def recover(path: str) -> int:
    """Truncate a trailing partial line in place (crash recovery before
    re-appending).  Returns the number of valid rows retained."""
    with open(path, "rb") as f:
        data = f.read()
    end = data.rfind(b"\n") + 1  # 0 when no newline at all
    n = 0
    for line in data[:end].split(b"\n"):
        if line.strip():
            json.loads(line)  # strict: retained rows must parse
            n += 1
    if end != len(data):
        with open(path, "r+b") as f:
            f.truncate(end)
    return n


@contextlib.contextmanager
def recording(path: str, **kwargs):
    """``with recording(path) as rec:`` — build, install, close."""
    with StepRecorder(path, **kwargs) as rec:
        yield rec
