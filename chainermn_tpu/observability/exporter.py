"""In-process HTTP ``/metrics`` scrape endpoint for live fleets.

Everything the repo exported before this module was post-hoc: ``tools.obs``
turns JSONL logs into a Prometheus *textfile* after the run.  A fleet
operator needs the pull model instead — Prometheus scrapes each process
while it runs.  :class:`MetricsExporter` is that bridge: a stdlib
``http.server`` on a background daemon thread rendering a metrics
*source* through :func:`chainermn_tpu.tools.obs.to_prometheus` on every
``GET /metrics``.

The source is either a
:class:`~chainermn_tpu.observability.reporter.Reporter` (its
:meth:`~chainermn_tpu.observability.reporter.Reporter.summary` is taken
fresh per scrape) or any zero-argument callable returning a
summary-shaped dict — the cluster router passes its merged *fleet view*
callable so one scrape of the router covers every replica.

Design constraints:

* **Injectable port** — ``port=0`` binds an ephemeral port (tests, many
  replicas per host); the bound port is available as :attr:`port` after
  :meth:`start`.
* **Zero impact on the serving path** — rendering happens on the scrape
  thread; the only shared state touched is the Reporter's lock for the
  duration of one ``summary()`` snapshot.  No jitted program gains
  inputs; nothing is exported unless somebody scrapes.
* **Crash-independent** — the thread is a daemon; a replica dying takes
  its endpoint with it (Prometheus sees the target go down, which *is*
  the signal).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

__all__ = ["MetricsExporter"]

_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsExporter:
    """Serve ``GET /metrics`` for one metrics source.

    ``source`` is a Reporter (anything with a ``summary()`` method) or a
    zero-arg callable returning a summary dict.  ``start()`` binds and
    returns the port; ``stop()`` shuts the server down.  Usable as a
    context manager.
    """

    def __init__(self, source, port: int = 0, host: str = "127.0.0.1",
                 prefix: str = "chainermn_tpu"):
        if hasattr(source, "summary"):
            snapshot: Callable[[], dict] = source.summary
        elif callable(source):
            snapshot = source
        else:
            raise TypeError(
                "source must be a Reporter or a zero-arg callable "
                f"returning a summary dict, got {type(source).__name__}"
            )
        self._snapshot = snapshot
        self._requested_port = int(port)
        self.host = host
        self.prefix = prefix
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> int:
        """Bind and serve in a daemon thread; returns the bound port."""
        if self._server is not None:
            return self.port
        self._server = ThreadingHTTPServer(
            (self.host, self._requested_port), self._make_handler()
        )
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None

    def __enter__(self) -> "MetricsExporter":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- read side -----------------------------------------------------
    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError("exporter not started")
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def render(self) -> str:
        """One exposition-format page — what a scrape returns, exposed
        for in-process assertions without a socket."""
        from chainermn_tpu.tools.obs import to_prometheus

        return to_prometheus(self._snapshot(), prefix=self.prefix)

    # -- handler -------------------------------------------------------
    def _make_handler(self):
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = exporter.render().encode()
                except Exception as exc:  # render must never kill serving
                    self.send_error(500, explain=str(exc))
                    return
                self.send_response(200)
                self.send_header("Content-Type", _CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not log events
                pass

        return Handler
