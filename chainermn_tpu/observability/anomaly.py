"""Rolling-window anomaly detection over the live fleet view.

The router's fleet summary (heartbeat-merged Reporter snapshots, see
``docs/observability.md``) is a cumulative view: counters only grow,
histogram buckets only fill.  The detectors here difference consecutive
snapshots into per-interval signals and compare a short *recent* window
against a longer *baseline* window — the standard burn-alert shape, but
over the fleet rather than one process:

* **latency regression** — the per-interval median of the
  ``trace/<stage>`` power-of-two histogram (new observations only)
  rising above ``regression_factor`` × the baseline median.
* **goodput drop** — the per-interval ``serving/tokens`` rate falling
  below ``drop_factor`` × the baseline median rate.

Each :meth:`AnomalyDetector.update` publishes ``anomaly/*`` gauges
(current 0/1 state plus the raw ratios) and counts a rising edge once
per alarm onset, so the event stream stays sparse.  The autoscaler takes
:meth:`AnomalyDetector.alarming` as an additional scale-up input
alongside its SLO burn-rate override — an anomaly is evidence the fleet
is degrading even when no SLO has formally burned yet.

Host-side Python only: no jitted program gains inputs, no collectives.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, Optional

__all__ = ["AnomalyDetector"]


def _hist_median(delta: Dict[int, int]) -> Optional[float]:
    """Weighted median upper-bound of a pow2 bucket-count delta."""
    total = sum(delta.values())
    if total <= 0:
        return None
    seen = 0
    for b in sorted(delta):
        seen += delta[b]
        if seen * 2 >= total:
            return 2.0 ** b
    return 2.0 ** max(delta)


class AnomalyDetector:
    """Differencing detector over cumulative fleet summaries.

    ``source`` (optional) is a zero-arg callable returning the fleet
    summary so driving code can call :meth:`update` with no arguments;
    passing the summary explicitly works the same.  ``reporter`` gets
    the ``anomaly/*`` series.  All windows are in *updates*, not
    seconds — call :meth:`update` on a fixed cadence (the autoscaler's
    interval) for time-meaningful windows.
    """

    def __init__(self, source: Optional[Callable[[], dict]] = None,
                 reporter=None, latency_stage: str = "decode",
                 window: int = 8, baseline: int = 64,
                 regression_factor: float = 2.0,
                 drop_factor: float = 0.5,
                 min_samples: int = 4,
                 clock: Callable[[], float] = time.monotonic):
        self._source = source
        self.reporter = reporter
        self.latency_stage = latency_stage
        self.window = max(1, int(window))
        self.baseline = max(self.window + 1, int(baseline))
        self.regression_factor = float(regression_factor)
        self.drop_factor = float(drop_factor)
        self.min_samples = max(1, int(min_samples))
        self.clock = clock
        self._prev_hist: Dict[int, int] = {}
        self._prev_tokens: Optional[float] = None
        self._prev_t: Optional[float] = None
        self._medians: deque = deque(maxlen=self.baseline)
        self._rates: deque = deque(maxlen=self.baseline)
        self._state = {"latency_regression": False, "goodput_drop": False}

    # -- the per-interval signals --------------------------------------
    def _latency_median(self, fleet: dict) -> Optional[float]:
        hist = fleet.get("histograms", {}).get(
            f"trace/{self.latency_stage}", {})
        cur = {int(b): int(c) for b, c in hist.items()}
        delta = {b: c - self._prev_hist.get(b, 0)
                 for b, c in cur.items()
                 if c - self._prev_hist.get(b, 0) > 0}
        self._prev_hist = cur
        return _hist_median(delta)

    def _goodput_rate(self, fleet: dict, now: float) -> Optional[float]:
        tokens = float(fleet.get("counters", {}).get("serving/tokens", 0.0))
        prev, prev_t = self._prev_tokens, self._prev_t
        self._prev_tokens, self._prev_t = tokens, now
        if prev is None or prev_t is None or now <= prev_t:
            return None
        # A replica loss can shrink the merged counter; a negative delta
        # is a fleet-membership change, not negative work.
        return max(0.0, tokens - prev) / (now - prev_t)

    @staticmethod
    def _split(history: deque, window: int):
        xs = list(history)
        return xs[:-window], xs[-window:]

    @staticmethod
    def _median(xs: List[float]) -> float:
        ys = sorted(xs)
        return ys[len(ys) // 2]

    # -- public --------------------------------------------------------
    def update(self, fleet: Optional[dict] = None,
               now: Optional[float] = None) -> dict:
        """Fold one fleet snapshot; returns the current alarm state
        (also kept for :meth:`alarming`)."""
        if fleet is None:
            if self._source is None:
                raise ValueError("no fleet summary and no source callable")
            fleet = self._source()
        now = self.clock() if now is None else now

        med = self._latency_median(fleet)
        if med is not None:
            self._medians.append(med)
        rate = self._goodput_rate(fleet, now)
        if rate is not None:
            self._rates.append(rate)

        lat_ratio = self._ratio(self._medians)
        rate_ratio = self._ratio(self._rates)
        latency_regression = (
            lat_ratio is not None and lat_ratio > self.regression_factor
        )
        goodput_drop = (
            rate_ratio is not None and rate_ratio < self.drop_factor
        )

        rep = self.reporter
        if rep is not None:
            if latency_regression and not self._state["latency_regression"]:
                rep.count("anomaly/latency_regression", 1)
            if goodput_drop and not self._state["goodput_drop"]:
                rep.count("anomaly/goodput_drop", 1)
            rep.gauge("anomaly/latency_regression",
                      1.0 if latency_regression else 0.0)
            rep.gauge("anomaly/goodput_drop",
                      1.0 if goodput_drop else 0.0)
            if lat_ratio is not None:
                rep.gauge("anomaly/latency_ratio", lat_ratio)
            if rate_ratio is not None:
                rep.gauge("anomaly/goodput_ratio", rate_ratio)

        self._state = {
            "latency_regression": latency_regression,
            "goodput_drop": goodput_drop,
        }
        return dict(self._state,
                    latency_ratio=lat_ratio, goodput_ratio=rate_ratio)

    def _ratio(self, history: deque) -> Optional[float]:
        """recent-median / baseline-median, or None before warm."""
        if len(history) < self.window + self.min_samples:
            return None
        base, recent = self._split(history, self.window)
        base_med = self._median(base)
        if base_med <= 0:
            return None
        return self._median(recent) / base_med

    def alarming(self) -> bool:
        """True while either detector is in alarm — the autoscaler's
        additional scale-up input."""
        return any(self._state.values())
