"""Cross-replica request tracing with a crash-surviving flight recorder.

Every serving request yields a **span tree**: a root ``request`` span
minted where the request enters the system (``ServeFrontend.submit`` or
the cluster router), plus flat stage children — ``queue``, ``placement``,
``prefill``, ``handoff``, ``migrate_send``/``migrate_recv``, per-iteration
``decode``, and a derived ``deliver`` span covering first→last streamed
token.  The context travels as a tiny value object (:class:`SpanCtx`:
trace id + parent span id) through the router's CMD frames,
:class:`~chainermn_tpu.serving.cluster.disagg.PrefillJob` handoffs and
KV-page migration, so one request's tree spans every process it touched.

Crash-robust parenting rule
---------------------------
A span only becomes durable when it *ends* (that is when its row is
written).  If stage spans parented to other stage spans, a replica
SIGKILLed mid-request would leave written children pointing at a parent
that was still open — an orphan.  So every replica-side stage span
parents **directly to the root context** carried on the wire, and the
root is owned by the process that survives failover (the router).  The
tree is therefore deliberately root + flat stage children: stitching the
flight files of a dead replica and the adopting replica yields one
connected tree with no orphan spans.

Flight recorder
---------------
:class:`FlightRecorder` is a bounded in-memory ring plus a
:class:`~chainermn_tpu.observability.step_log.StepRecorder`-backed JSONL
file: one atomic ``O_APPEND`` write per finished span, rotation bounding
disk.  A SIGKILL loses at most one truncated final line (skipped by the
reader) — everything the replica finished before dying is recoverable
for postmortems.

Exports: :func:`stitch` + :func:`validate_trace` reassemble trees from
flight files, :func:`to_chrome_trace` emits Chrome-trace/Perfetto JSON
(``tools.obs trace``), :func:`stage_percentiles` derives per-stage
p50/p99, :func:`detect_stragglers` flags replicas whose stage medians
drift beyond ``k``× the fleet median, and :class:`SLOConfig` drives
burn-rate gauges through the Reporter → Prometheus path.

Zero-overhead when disabled: every instrumented call site starts with
``tr = get_tracer()`` and does nothing when it returns ``None`` — no
ids are minted, no clocks are read, and no new jitted-function inputs
are introduced (tracing never changes compilation).
"""

from __future__ import annotations

import contextlib
import glob as _glob
import itertools
import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from chainermn_tpu.observability import step_log as _step_log

__all__ = [
    "SpanCtx",
    "Tracer",
    "FlightRecorder",
    "SLOConfig",
    "get_tracer",
    "install",
    "uninstall",
    "trace_scope",
    "tracing_active",
    "read_flight",
    "read_flight_dir",
    "stitch",
    "validate_trace",
    "to_chrome_trace",
    "stage_percentiles",
    "detect_stragglers",
    "percentile",
]


# ---------------------------------------------------------------------------
# Context
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class SpanCtx:
    """Wire-portable trace context: which trace, and which span new
    children should parent to."""

    trace_id: str
    span_id: str

    def to_wire(self) -> dict:
        return {"tid": self.trace_id, "sid": self.span_id}

    @staticmethod
    def from_wire(obj) -> Optional["SpanCtx"]:
        """Accept a wire dict, an existing SpanCtx, or None."""
        if obj is None:
            return None
        if isinstance(obj, SpanCtx):
            return obj
        return SpanCtx(trace_id=str(obj["tid"]), span_id=str(obj["sid"]))


@dataclass
class SLOConfig:
    """Latency objectives per stage (seconds) driving burn-rate gauges.

    ``burn rate = (violating fraction over the trailing window) /
    budget`` — 1.0 means exactly consuming the error budget, >1 means
    burning it faster than allowed.
    """

    targets: Dict[str, float] = field(default_factory=dict)
    budget: float = 0.01
    window: int = 256


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------
class FlightRecorder:
    """Per-replica crash-surviving span sink.

    Rides :class:`StepRecorder`'s O_APPEND + rotation machinery (compile
    listener and memory sampling disabled — this file holds only span and
    event rows).  ``rotate_bytes`` bounds disk for soak runs; each row is
    one atomic write, so a SIGKILL costs at most the final line.
    """

    def __init__(self, path: str, replica=None,
                 rotate_bytes: Optional[int] = 4 * 1024 * 1024,
                 max_files: int = 2):
        rank = replica if isinstance(replica, int) else 0
        self.path = str(path)
        self.replica = replica
        self._rec = _step_log.StepRecorder(
            path,
            rotate_bytes=rotate_bytes,
            max_files=max_files,
            rank=rank,
            capture_compile_events=False,
            mem_every=0,
        )

    def write(self, kind: str, row: dict) -> None:
        self._rec.record(kind, **row)

    def close(self) -> None:
        self._rec.close()


def read_flight(path: str) -> List[dict]:
    """Span/event rows from one flight file (rotated segments included,
    truncated final line skipped — the SIGKILL case)."""
    rows = _step_log.read_records(path, include_rotated=True, strict=False)
    return [r for r in rows if r.get("event") in ("span", "evt")]


def read_flight_dir(pattern: str) -> List[dict]:
    """Rows from every flight file matching a glob (e.g.
    ``dir/flight_r*.jsonl``), merged and sorted by start time."""
    rows: List[dict] = []
    for p in sorted(_glob.glob(pattern)):
        if p.endswith(tuple(f".{i}" for i in range(1, 10))):
            continue  # rotated segments are folded in by read_flight
        rows.extend(read_flight(p))
    rows.sort(key=lambda r: r.get("t0", r.get("ts", 0.0)))
    return rows


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------
class Tracer:
    """Mints trace/span ids and records finished spans to an in-memory
    ring, an optional :class:`FlightRecorder`, and an optional Reporter
    (``trace/<stage>`` pow2 histograms + SLO burn gauges).

    Thread-safe: the serving cluster drives replicas from threads.
    ``nonce`` seeds id minting — pass a fixed value for deterministic ids
    in golden tests; by default ids embed the pid so concurrent processes
    never collide.
    """

    def __init__(self, flight: Optional[FlightRecorder] = None,
                 reporter=None, replica=None,
                 slo: Optional[SLOConfig] = None,
                 ring: int = 4096, clock=time.time,
                 nonce: Optional[str] = None):
        self.flight = flight
        self.reporter = reporter
        self.replica = replica
        self.slo = slo
        self.clock = clock
        self._nonce = nonce if nonce is not None else f"{os.getpid():x}"
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(16, int(ring)))
        self._open: Dict[str, dict] = {}        # span_id -> open row
        self._tokens: Dict[str, dict] = {}      # trace_id -> deliver stats
        self._slo_win: Dict[str, deque] = {}

    # -- id minting ----------------------------------------------------
    def _sid(self) -> str:
        return f"{self._nonce}.{next(self._ids)}"

    def new_trace(self) -> str:
        return f"t{self._nonce}.{next(self._ids)}"

    # -- span lifecycle ------------------------------------------------
    def begin(self, name: str, parent: Optional[SpanCtx] = None,
              replica=None, **attrs) -> SpanCtx:
        """Open a span.  With ``parent=None`` a fresh trace is minted
        (this is the root).  Returns the context children parent to.
        Nothing is written until :meth:`end` — see the crash-robust
        parenting rule in the module docstring."""
        sid = self._sid()
        tid = parent.trace_id if parent is not None else self.new_trace()
        row = {
            "trace": tid,
            "span": sid,
            "parent": parent.span_id if parent is not None else None,
            "name": name,
            "t0": self.clock(),
            "replica": self.replica if replica is None else replica,
        }
        if attrs:
            row["attrs"] = dict(attrs)
        with self._lock:
            self._open[sid] = row
        return SpanCtx(trace_id=tid, span_id=sid)

    def end(self, ctx: Optional[SpanCtx], error=None, **attrs) -> None:
        """Close a span opened with :meth:`begin`.  Unknown / already
        closed ids are a no-op (double-end safe)."""
        if ctx is None:
            return
        with self._lock:
            row = self._open.pop(ctx.span_id, None)
        if row is None:
            return
        row["dur"] = max(0.0, self.clock() - row["t0"])
        if error:
            row["error"] = True
            if not isinstance(error, bool):
                row.setdefault("attrs", {})["error_msg"] = str(error)
        if attrs:
            row.setdefault("attrs", {}).update(attrs)
        if row["name"] == "request":
            self._emit_deliver(ctx)
        self._write(row)

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[SpanCtx] = None,
             replica=None, **attrs):
        """``with tr.span("prefill", parent=root):`` — closes and marks
        ``error=True`` on exception paths, then re-raises."""
        ctx = self.begin(name, parent, replica=replica, **attrs)
        try:
            yield ctx
        except BaseException as exc:
            self.end(ctx, error=exc)
            raise
        else:
            self.end(ctx)

    def record_span(self, name: str, parent: Optional[SpanCtx],
                    t0: float, dur: float, replica=None,
                    error=None, **attrs) -> None:
        """Record an externally-timed span in one shot (queue wait,
        shared batched-decode duration)."""
        if parent is None:
            return
        row = {
            "trace": parent.trace_id,
            "span": self._sid(),
            "parent": parent.span_id,
            "name": name,
            "t0": float(t0),
            "dur": max(0.0, float(dur)),
            "replica": self.replica if replica is None else replica,
        }
        if error:
            row["error"] = True
        if attrs:
            row["attrs"] = dict(attrs)
        self._write(row)

    def event(self, name: str, parent: Optional[SpanCtx],
              replica=None, **attrs) -> None:
        """Instantaneous annotation (``preempted``, ``failover``, …)."""
        if parent is None:
            return
        row = {
            "trace": parent.trace_id,
            "parent": parent.span_id,
            "name": name,
            "ts": self.clock(),
            "replica": self.replica if replica is None else replica,
        }
        if attrs:
            row["attrs"] = dict(attrs)
        with self._lock:
            self._ring.append(("evt", row))
        if self.flight is not None:
            self.flight.write("evt", row)

    def token(self, ctx: Optional[SpanCtx]) -> None:
        """Mark one streamed token delivered for ``ctx``'s trace; first
        and last arrivals become the derived ``deliver`` span when the
        root ends."""
        if ctx is None:
            return
        now = self.clock()
        with self._lock:
            st = self._tokens.get(ctx.trace_id)
            if st is None:
                self._tokens[ctx.trace_id] = {
                    "first": now, "last": now, "n": 1,
                    "parent": ctx.span_id,
                }
            else:
                st["last"] = now
                st["n"] += 1

    def _emit_deliver(self, root: SpanCtx) -> None:
        with self._lock:
            st = self._tokens.pop(root.trace_id, None)
        if st is None:
            return
        self._write({
            "trace": root.trace_id,
            "span": self._sid(),
            "parent": root.span_id,
            "name": "deliver",
            "t0": st["first"],
            "dur": max(0.0, st["last"] - st["first"]),
            "replica": self.replica,
            "attrs": {"tokens": st["n"]},
        })

    # -- sinks ---------------------------------------------------------
    def _write(self, row: dict) -> None:
        with self._lock:
            self._ring.append(("span", row))
        if self.flight is not None:
            self.flight.write("span", row)
        rep = self.reporter
        if rep is not None:
            name = row["name"]
            rep.histogram_observe(f"trace/{name}", row["dur"])
            if row.get("error"):
                rep.count(f"trace/{name}/errors", 1)
            tenant = (row.get("attrs") or {}).get("tenant")
            self._slo_observe(name, row["dur"], rep, tenant=tenant)

    def _slo_observe(self, name: str, dur: float, rep,
                     tenant=None) -> None:
        slo = self.slo
        if slo is None or name not in slo.targets:
            return
        bad = dur > slo.targets[name]
        with self._lock:
            win = self._slo_win.setdefault(
                name, deque(maxlen=max(1, slo.window)))
            win.append(bad)
            frac = sum(win) / len(win)
            tfrac = None
            if tenant is not None:
                # Per-tenant burn window: same SLO target and budget,
                # windowed over THIS tenant's spans only, so one noisy
                # tenant's violations don't hide inside the aggregate.
                twin = self._slo_win.setdefault(
                    (name, tenant), deque(maxlen=max(1, slo.window)))
                twin.append(bad)
                tfrac = sum(twin) / len(twin)
        if bad:
            rep.count(f"slo/violations/{name}", 1)
        scale = 1.0 / slo.budget if slo.budget > 0 else 0.0
        rep.gauge(f"slo/burn_rate/{name}", frac * scale)
        if tfrac is not None:
            rep.gauge(f"slo/burn_rate/{name}/tenant/{tenant}",
                      tfrac * scale)

    # -- read side -----------------------------------------------------
    def records(self) -> List[dict]:
        """Ring snapshot as flat rows (``event`` key restored) — same
        shape :func:`read_flight` returns from disk."""
        with self._lock:
            items = list(self._ring)
        out = []
        for kind, row in items:
            r = dict(row)
            r["event"] = kind
            out.append(r)
        return out

    def stage_stats(self) -> Dict[Tuple[Any, str], List[float]]:
        """``{(replica, stage): [durations]}`` over the ring — the
        straggler detector's input."""
        out: Dict[Tuple[Any, str], List[float]] = {}
        with self._lock:
            items = list(self._ring)
        for kind, row in items:
            if kind != "span":
                continue
            key = (row.get("replica"), row["name"])
            out.setdefault(key, []).append(row["dur"])
        return out

    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def close(self) -> None:
        if self.flight is not None:
            self.flight.close()


# ---------------------------------------------------------------------------
# Current-tracer stack (mirrors reporter.scope)
# ---------------------------------------------------------------------------
_stack: list = []
_stack_lock = threading.Lock()


def get_tracer() -> Optional[Tracer]:
    """The installed tracer, or None — the zero-overhead gate every
    instrumented call site checks first."""
    with _stack_lock:
        return _stack[-1] if _stack else None


def install(tracer: Tracer) -> None:
    with _stack_lock:
        _stack.append(tracer)


def uninstall(tracer: Tracer) -> None:
    with _stack_lock:
        if tracer in _stack:
            _stack.remove(tracer)


@contextlib.contextmanager
def trace_scope(tracer: Tracer):
    install(tracer)
    try:
        yield tracer
    finally:
        uninstall(tracer)


def tracing_active() -> bool:
    return get_tracer() is not None


# ---------------------------------------------------------------------------
# Stitching / validation / export
# ---------------------------------------------------------------------------
def stitch(records: List[dict]) -> Dict[str, dict]:
    """Group flat rows (from any number of flight files / rings) into
    ``{trace_id: {"spans": [...], "events": [...]}}``."""
    out: Dict[str, dict] = {}
    for r in records:
        tid = r.get("trace")
        if tid is None:
            continue
        slot = out.setdefault(tid, {"spans": [], "events": []})
        if r.get("event") == "evt":
            slot["events"].append(r)
        else:
            slot["spans"].append(r)
    for slot in out.values():
        slot["spans"].sort(key=lambda s: s.get("t0", 0.0))
        slot["events"].sort(key=lambda e: e.get("ts", 0.0))
    return out


def validate_trace(spans: List[dict], skew_s: float = 0.5) -> dict:
    """Postmortem checks for one stitched trace.

    * ``orphans`` — spans whose parent id was never written (the failure
      mode the crash-robust parenting rule exists to prevent).
    * ``monotone`` — every child starts no earlier than its parent
      (within ``skew_s`` cross-process clock tolerance) and finishes by
      the parent's end + skew.
    """
    ids = {s["span"] for s in spans}
    orphans = [s for s in spans
               if s.get("parent") is not None and s["parent"] not in ids]
    by_id = {s["span"]: s for s in spans}
    violations = []
    for s in spans:
        p = by_id.get(s.get("parent"))
        if p is None:
            continue
        if s["t0"] + skew_s < p["t0"]:
            violations.append((s["span"], "starts before parent"))
        if s["t0"] + s.get("dur", 0.0) > p["t0"] + p.get("dur", 0.0) + skew_s:
            violations.append((s["span"], "ends after parent"))
    roots = [s for s in spans if s.get("parent") is None]
    return {
        "spans": len(spans),
        "roots": len(roots),
        "orphans": [s["span"] for s in orphans],
        "monotone": not violations,
        "violations": violations,
        "connected": not orphans and len(roots) >= 1,
    }


def to_chrome_trace(records: List[dict],
                    app: str = "chainermn_tpu.serve") -> dict:
    """Chrome-trace/Perfetto JSON: one process row per replica, one
    thread row per trace, ``ph:"X"`` complete events for spans and
    ``ph:"i"`` instants for events.  ``ts``/``dur`` are microseconds."""
    replicas = sorted({str(r.get("replica")) for r in records},
                      key=lambda x: (x == "None", x))
    pid_of = {rep: i + 1 for i, rep in enumerate(replicas)}
    tids: Dict[str, int] = {}

    def tid_of(trace: str) -> int:
        if trace not in tids:
            tids[trace] = len(tids) + 1
        return tids[trace]

    events: List[dict] = []
    for rep in replicas:
        events.append({
            "name": "process_name", "ph": "M", "pid": pid_of[rep],
            "args": {"name": f"{app} replica={rep}"},
        })
    for r in sorted(records, key=lambda r: r.get("t0", r.get("ts", 0.0))):
        pid = pid_of[str(r.get("replica"))]
        if r.get("event") == "evt":
            events.append({
                "name": r["name"], "cat": "serve", "ph": "i", "s": "t",
                "ts": round(r["ts"] * 1e6, 3), "pid": pid,
                "tid": tid_of(r["trace"]),
                "args": {"trace": r["trace"], "parent": r.get("parent"),
                         **r.get("attrs", {})},
            })
            continue
        args = {"trace": r["trace"], "span": r["span"],
                "parent": r.get("parent")}
        if r.get("error"):
            args["error"] = True
        args.update(r.get("attrs", {}))
        events.append({
            "name": r["name"], "cat": "serve", "ph": "X",
            "ts": round(r["t0"] * 1e6, 3),
            "dur": round(r.get("dur", 0.0) * 1e6, 3),
            "pid": pid, "tid": tid_of(r["trace"]),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) — no numpy needed at
    postmortem time."""
    if not values:
        return 0.0
    xs = sorted(values)
    k = max(0, min(len(xs) - 1, math.ceil(q / 100.0 * len(xs)) - 1))
    return xs[k]


def stage_percentiles(records: List[dict]) -> Dict[str, dict]:
    """``{stage: {count, p50_s, p99_s, mean_s}}`` over span rows."""
    durs: Dict[str, List[float]] = {}
    for r in records:
        if r.get("event") == "evt" or "dur" not in r:
            continue
        durs.setdefault(r["name"], []).append(float(r["dur"]))
    out: Dict[str, dict] = {}
    for name, xs in sorted(durs.items()):
        out[name] = {
            "count": len(xs),
            "p50_s": percentile(xs, 50),
            "p99_s": percentile(xs, 99),
            "mean_s": sum(xs) / len(xs),
        }
    return out


def detect_stragglers(stats: Dict[Tuple[Any, str], List[float]],
                      k: float = 4.0,
                      min_samples: int = 4) -> Dict[Any, Dict[str, float]]:
    """Flag replicas whose per-stage median exceeds ``k``× the fleet
    median of that stage.  Input is :meth:`Tracer.stage_stats` output;
    returns ``{replica: {stage: ratio}}`` for flagged pairs only."""
    by_stage: Dict[str, Dict[Any, float]] = {}
    for (rep, stage), xs in stats.items():
        if rep is None or len(xs) < min_samples:
            continue
        by_stage.setdefault(stage, {})[rep] = percentile(xs, 50)
    flagged: Dict[Any, Dict[str, float]] = {}
    for stage, meds in by_stage.items():
        if len(meds) < 2:
            continue  # no fleet to compare against
        fleet = percentile(list(meds.values()), 50)
        if fleet <= 0:
            continue
        for rep, m in meds.items():
            ratio = m / fleet
            if ratio > k:
                flagged.setdefault(rep, {})[stage] = ratio
    return flagged
