"""Cross-host telemetry: metrics registry, step-event log, HLO collective
audit, and trace spans — the first layer that sees every rank every step.

The reference stack's visibility came from Chainer's ``Reporter`` +
``LogReport`` extensions plus external nvprof (SURVEY §5.1).  Here the
telemetry is library-native and SPMD-aware:

* :class:`Reporter` — scalars/counters/histograms per process,
  :meth:`Reporter.aggregate` merging across hosts through the
  communicator's object plane (mean/sum/max on rank 0, off-TPU safe).
* :class:`StepRecorder` — structured JSONL step-event log with atomic
  append, rotation, crash-safe partial-line recovery, compile events
  (``jax.monitoring``) and device-memory stats.
* :mod:`hlo_audit` — per-collective counts and per-mesh-axis operand
  bytes of any traced step fn (the generalized bench census).
* :func:`span` — named regions on the profiler timeline AND in the
  JSONL log with host-side durations; :func:`named_scope` for traced
  code.
* :mod:`tracing` — cross-replica request tracing for the serving tier:
  :class:`SpanCtx` contexts over the cluster wire, a crash-surviving
  :class:`FlightRecorder`, Chrome-trace export, per-stage percentiles,
  SLO burn-rate gauges and a straggler detector.

Summarize/export a log with ``python -m chainermn_tpu.tools.obs``
(incl. Prometheus textfile output).  See ``docs/observability.md``.
"""

from chainermn_tpu.observability.reporter import (  # noqa: F401
    Reporter,
    get_reporter,
    merge_summaries,
    report,
    scope,
)
from chainermn_tpu.observability.step_log import (  # noqa: F401
    StepRecorder,
    current_recorder,
    device_memory_stats,
    read_records,
    recover,
    recording,
)
from chainermn_tpu.observability.hlo_audit import (  # noqa: F401
    CollectiveAudit,
    TracedStep,
    audit_allreduce,
    audit_allreduce_tree,
    audit_compiled,
    audit_fn,
    audit_hlo_text,
    audit_jaxpr,
    fold_async_counts,
    trace_step,
)
from chainermn_tpu.observability.exporter import (  # noqa: F401
    MetricsExporter,
)
from chainermn_tpu.observability.anomaly import (  # noqa: F401
    AnomalyDetector,
)
from chainermn_tpu.observability.spans import (  # noqa: F401
    named_scope,
    span,
    telemetry_active,
)
from chainermn_tpu.observability.tracing import (  # noqa: F401
    FlightRecorder,
    SLOConfig,
    SpanCtx,
    Tracer,
    detect_stragglers,
    get_tracer,
    read_flight,
    read_flight_dir,
    stage_percentiles,
    stitch,
    to_chrome_trace,
    trace_scope,
    tracing_active,
    validate_trace,
)
