"""Collective audit — jaxpr-level census of a step function's wire cost.

This generalizes what ``benchmarks/allreduce_bench.py`` grew ad hoc: for
any traceable function (a jitted train step, a communicator's
``allreduce_grad``), count the collective primitives it lowers to and
charge each collective's per-device operand bytes to the mesh axes it
runs over.  The result is environment-independent evidence of an
algorithm's wire structure — readable on one chip, or on the virtual
CPU mesh, long before a v4-32 is available — and the input the
two_dimensional backend's bandwidth claim is verified against (its
inter-axis bytes must be the flat backend's divided by ``intra_size``).

``benchmarks/allreduce_bench.py`` and ``bench.py``'s
``allreduce_static_bytes_per_leg`` table now consume THIS module (one
source of truth for the bytes-per-leg metric); examples call
:func:`audit_fn` on their real train step and log the result as an
``hlo_audit`` row in the step-event log.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

# lax.psum → psum, lax.psum_scatter → reduce_scatter, lax.all_gather →
# all_gather, lax.ppermute → ppermute, lax.all_to_all → all_to_all.
COLLECTIVE_PRIMITIVES = (
    "psum", "reduce_scatter", "all_gather", "ppermute", "all_to_all",
)

#: The primitives that perform a reduction (the ones gradient bucketing
#: promises to make leaf-count-independent; all_gather/ppermute only move).
REDUCTION_PRIMITIVES = ("psum", "reduce_scatter")

# The four the gradient-allreduce census reports (all_to_all never appears
# in an allreduce lowering; kept out for byte-identical bench output).
ALLREDUCE_CENSUS_KEYS = ("psum", "reduce_scatter", "all_gather", "ppermute")


def _eqn_axes(eqn):
    """Mesh-axis names a collective eqn runs over, as a tuple."""
    for key in ("axes", "axis_name"):
        if key in eqn.params:
            ax = eqn.params[key]
            if isinstance(ax, (tuple, list)):
                out = []
                for a in ax:
                    out.extend(a) if isinstance(a, (tuple, list)) \
                        else out.append(a)
                return tuple(out)
            return (ax,)
    return ()


def _operand_bytes(eqn) -> int:
    """Per-device operand bytes of one eqn (sum over array invars)."""
    return sum(
        int(np.prod(v.aval.shape)) * np.dtype(v.aval.dtype).itemsize
        for v in eqn.invars
        if hasattr(v.aval, "shape")
    )


def iter_eqns(jaxpr):
    """Depth-first walk over every eqn, recursing into inner jaxprs
    (pjit/shard_map/scan/cond bodies) — collectives live inside the
    shard_map eqn, never at top level."""
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            # Inner jaxprs appear as raw Jaxpr (has .eqns) or ClosedJaxpr
            # (has .jaxpr) param values; `branches` holds a tuple of them.
            if isinstance(val, (tuple, list)):
                for v in val:
                    if hasattr(v, "eqns"):
                        yield from iter_eqns(v)
                    elif hasattr(v, "jaxpr"):
                        yield from iter_eqns(v.jaxpr)
            elif hasattr(val, "eqns"):
                yield from iter_eqns(val)
            elif hasattr(val, "jaxpr"):
                yield from iter_eqns(val.jaxpr)


@dataclasses.dataclass
class CollectiveAudit:
    """Census of one traced program's collectives.

    ``counts`` — occurrences per collective primitive name.
    ``bytes_per_axis`` — per-device operand bytes charged to each mesh
    axis a collective runs over (an op over both axes charges both),
    ``str(axis) → bytes``.
    ``bytes_per_primitive`` — per-device operand bytes per primitive.
    ``op_bytes`` — per-device operand bytes of each individual occurrence,
    in trace order per primitive: with gradient bucketing this IS the
    per-bucket byte profile of the allreduce.
    """

    counts: Dict[str, int]
    bytes_per_axis: Dict[str, int]
    bytes_per_primitive: Dict[str, int]
    op_bytes: Dict[str, List[int]] = dataclasses.field(default_factory=dict)

    def census(self, keys=ALLREDUCE_CENSUS_KEYS) -> Dict[str, int]:
        """Fixed-key count view (zeros included) — the allreduce-bench
        ``hlo_collectives`` record shape."""
        return {k: self.counts.get(k, 0) for k in keys}

    def reduction_collectives(self) -> int:
        """Total reduction-collective occurrences (psum + reduce_scatter)
        — the count bucketing makes O(n_buckets) instead of O(n_leaves)."""
        return sum(self.counts.get(k, 0) for k in REDUCTION_PRIMITIVES)

    def summary(self) -> dict:
        return {
            "counts": dict(self.counts),
            "bytes_per_axis": dict(self.bytes_per_axis),
            "bytes_per_primitive": dict(self.bytes_per_primitive),
            "op_bytes": {k: list(v) for k, v in self.op_bytes.items()},
            "reduction_collectives": self.reduction_collectives(),
        }


def audit_jaxpr(jaxpr) -> CollectiveAudit:
    """Audit an already-traced (Closed)Jaxpr."""
    if hasattr(jaxpr, "jaxpr"):  # ClosedJaxpr
        jaxpr = jaxpr.jaxpr
    counts: Dict[str, int] = {}
    per_axis: Dict[str, int] = {}
    per_prim: Dict[str, int] = {}
    op_bytes: Dict[str, List[int]] = {}
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMITIVES:
            continue
        counts[name] = counts.get(name, 0) + 1
        nbytes = _operand_bytes(eqn)
        per_prim[name] = per_prim.get(name, 0) + nbytes
        op_bytes.setdefault(name, []).append(nbytes)
        for ax in _eqn_axes(eqn):
            per_axis[str(ax)] = per_axis.get(str(ax), 0) + nbytes
    return CollectiveAudit(counts, per_axis, per_prim, op_bytes)


class TracedStep(NamedTuple):
    """One abstract trace of a step function — the shared entry point the
    audit AND the collective linter (:mod:`chainermn_tpu.analysis`) build
    on, so a step is traced exactly once however it is wrapped.

    ``donate_argnums`` carries the jit wrapper's donation declaration when
    the AOT ``trace`` path supplied it; ``None`` means "unknown — look for
    ``pjit`` eqn ``donated_invars`` inside the jaxpr instead".
    """

    closed_jaxpr: Any
    donate_argnums: Optional[Tuple[int, ...]]


def trace_step(fn, *args, **kwargs) -> TracedStep:
    """Trace ``fn(*args, **kwargs)`` without executing it.

    Accepts plain callables AND already-``jax.jit``-wrapped ones: a jitted
    callable is traced through its own AOT ``.trace`` surface (one trace,
    reusing jit's cached machinery — no re-wrap double-trace), which also
    exposes its ``donate_argnums``; everything else goes through
    ``jax.make_jaxpr``, kwargs included.  Args may be real arrays or
    ``jax.ShapeDtypeStruct``s."""
    import jax

    tracer = getattr(fn, "trace", None)
    if callable(tracer):
        try:
            tr = tracer(*args, **kwargs)
            closed = getattr(tr, "jaxpr", None)
            if closed is not None:
                donate = getattr(tr, "donate_argnums", None)
                return TracedStep(
                    closed, tuple(donate) if donate is not None else None
                )
        except Exception:
            pass  # not jit's AOT surface — fall through to make_jaxpr
    return TracedStep(jax.make_jaxpr(fn)(*args, **kwargs), None)


def audit_fn(fn, *args, **kwargs) -> CollectiveAudit:
    """Trace ``fn(*args, **kwargs)`` (jitted or plain) and audit the
    resulting program.  Args may be real arrays or
    ``jax.ShapeDtypeStruct``s; nothing executes.  Delegates the tracing
    to :func:`trace_step` — the entry point shared with the collective
    linter — so jitted callables and kwargs take the single-trace path."""
    return audit_jaxpr(trace_step(fn, *args, **kwargs).closed_jaxpr)


def _allreduce_jaxpr(comm, nbytes: int, dtype):
    """The traced ``allreduce_grad`` lowering every per-communicator
    census is computed on: a rank-stacked (device_size, elems) buffer
    through the communicator's characteristic collective pattern."""
    import jax
    import jax.numpy as jnp

    n = comm.device_size
    elems = max(1, nbytes // np.dtype(dtype).itemsize)
    spec = comm._world_spec

    def body(tree):
        sq = jax.tree.map(lambda x: jnp.squeeze(x, 0), tree)
        out = comm.allreduce_grad(sq)
        return jax.tree.map(lambda x: x[None], out)

    return jax.make_jaxpr(comm.shard_map(
        body, in_specs=({"g": spec},), out_specs={"g": spec}
    ))({"g": jnp.ones((n, elems), dtype)})


def audit_allreduce(comm, nbytes: int, dtype=np.float32) -> CollectiveAudit:
    """Audit one communicator's gradient-allreduce path at a given
    per-device payload — the library home of bench.py's
    ``allreduce_static_bytes_per_leg`` numbers."""
    return audit_jaxpr(_allreduce_jaxpr(comm, nbytes, dtype))


def audit_allreduce_tree(comm, tree) -> CollectiveAudit:
    """Audit ``allreduce_grad`` over a FULL gradient pytree.

    ``tree`` carries per-device leaf shapes (no leading rank axis) —
    arrays or ``jax.ShapeDtypeStruct``s; nothing executes.  This is the
    many-leaf generalization of :func:`audit_allreduce`: with bucketing
    on, ``reduction_collectives()`` is O(n_buckets) and ``op_bytes``
    holds each bucket's wire size; with ``bucket_bytes=0`` it shows the
    legacy per-leaf lowering for comparison.
    """
    import jax
    import jax.numpy as jnp

    n = comm.device_size
    spec = comm._world_spec
    stacked = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct((n,) + tuple(l.shape), l.dtype), tree
    )
    specs = jax.tree.map(lambda _: spec, stacked)

    def body(t):
        sq = jax.tree.map(lambda x: jnp.squeeze(x, 0), t)
        out = comm.allreduce_grad(sq)
        return jax.tree.map(lambda x: x[None], out)

    return audit_jaxpr(jax.make_jaxpr(comm.shard_map(
        body, in_specs=(specs,), out_specs=specs
    ))(stacked))


def assert_two_dimensional_inter_savings(profiles: dict,
                                         intra_size: int) -> None:
    """``profiles``: {communicator_name: bytes_per_axis dict}.  Asserts
    the 2D claim when both sides are present: two_dimensional's
    inter-axis operand bytes == flat's / intra_size (SURVEY §2.1
    two-dimensional row — the reference's rationale for the 2D algorithm
    on >1 GbE clusters)."""
    flat = next(
        (profiles[k] for k in ("flat", "xla_ici", "pure_nccl")
         if k in profiles), None,
    )
    td = profiles.get("two_dimensional")
    if flat is None or td is None:
        return
    flat_inter = flat.get("inter", 0)
    td_inter = td.get("inter", 0)
    assert flat_inter > 0 and td_inter > 0, (profiles,)
    assert td_inter * intra_size == flat_inter, (
        f"two_dimensional inter-axis bytes {td_inter} x intra "
        f"{intra_size} != flat's {flat_inter} — the 2D bandwidth claim "
        "does not hold in the traced lowering"
    )
