"""Collective audit — jaxpr- and HLO-level census of a step's wire cost.

This generalizes what ``benchmarks/allreduce_bench.py`` grew ad hoc: for
any traceable function (a jitted train step, a communicator's
``allreduce_grad``), count the collective primitives it lowers to and
charge each collective's per-device operand bytes to the mesh axes it
runs over.  The result is environment-independent evidence of an
algorithm's wire structure — readable on one chip, or on the virtual
CPU mesh, long before a v4-32 is available — and the input the
two_dimensional backend's bandwidth claim is verified against (its
inter-axis bytes must be the flat backend's divided by ``intra_size``).

``benchmarks/allreduce_bench.py`` and ``bench.py``'s
``allreduce_static_bytes_per_leg`` table now consume THIS module (one
source of truth for the bytes-per-leg metric); examples call
:func:`audit_fn` on their real train step and log the result as an
``hlo_audit`` row in the step-event log.

Two census sources, one :class:`CollectiveAudit` shape:

* :func:`audit_jaxpr` (and the ``audit_*`` wrappers) — the traced
  program, where collectives are single primitives (``psum``, …).
* :func:`audit_hlo_text` — compiled HLO, where the TPU compiler's
  async-collective machinery may have SPLIT a collective into an
  ``all-reduce-start``/``all-reduce-done`` pair (likewise
  ``collective-permute-start/done``, ``all-gather-start/done``) so the
  latency-hiding scheduler can place independent backward compute
  between the two halves — the lowering the backward-overlapped bucket
  schedule (:mod:`chainermn_tpu.communicators.overlap`) exists to
  trigger.  The HLO parser folds each start/done pair into ONE logical
  collective under its jaxpr-primitive name (so
  ``reduction_collectives()`` and ``census()`` never double-count) and
  reports ``overlap_fraction``: the fraction of async pairs with real
  compute scheduled strictly between start and done.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

# lax.psum → psum, lax.psum_scatter → reduce_scatter, lax.all_gather →
# all_gather, lax.ppermute → ppermute, lax.all_to_all → all_to_all.
COLLECTIVE_PRIMITIVES = (
    "psum", "reduce_scatter", "all_gather", "ppermute", "all_to_all",
)

#: The primitives that perform a reduction (the ones gradient bucketing
#: promises to make leaf-count-independent; all_gather/ppermute only move).
REDUCTION_PRIMITIVES = ("psum", "reduce_scatter")

# The four the gradient-allreduce census reports (all_to_all never appears
# in an allreduce lowering; kept out for byte-identical bench output).
ALLREDUCE_CENSUS_KEYS = ("psum", "reduce_scatter", "all_gather", "ppermute")

#: HLO opcode → jaxpr primitive name, the vocabulary bridge that lets an
#: HLO-text census reuse every count consumer (``census()``,
#: ``reduction_collectives()``, lint R004) unchanged.
HLO_COLLECTIVE_OPS = {
    "all-reduce": "psum",
    "reduce-scatter": "reduce_scatter",
    "all-gather": "all_gather",
    "collective-permute": "ppermute",
    "all-to-all": "all_to_all",
}

_ASYNC_START = "-start"
_ASYNC_DONE = "-done"


def fold_async_counts(counts: Dict[str, int]) -> Dict[str, int]:
    """Fold a counts dict that may contain RAW HLO opcodes — including
    unpaired ``*-start``/``*-done`` entries — into jaxpr-primitive
    counts, one logical collective per async pair.

    ``-start`` carries the count (each pair has exactly one), ``-done``
    is dropped, synchronous HLO opcodes map through
    :data:`HLO_COLLECTIVE_OPS`, and names already in jaxpr vocabulary
    pass unchanged.  This is the defensive normalization lint R004 runs
    before comparing collective counts to leaf counts, so a census fed
    from compiled HLO can never make split collectives look like a
    bucketing regression.
    """
    out: Dict[str, int] = {}
    for name, n in counts.items():
        base = name
        if base.endswith(_ASYNC_DONE):
            continue
        if base.endswith(_ASYNC_START):
            base = base[: -len(_ASYNC_START)]
        base = HLO_COLLECTIVE_OPS.get(base, base)
        out[base] = out.get(base, 0) + int(n)
    return out


def _eqn_axes(eqn):
    """Mesh-axis names a collective eqn runs over, as a tuple."""
    for key in ("axes", "axis_name"):
        if key in eqn.params:
            ax = eqn.params[key]
            if isinstance(ax, (tuple, list)):
                out = []
                for a in ax:
                    out.extend(a) if isinstance(a, (tuple, list)) \
                        else out.append(a)
                return tuple(out)
            return (ax,)
    return ()


def _operand_bytes(eqn) -> int:
    """Per-device operand bytes of one eqn (sum over array invars)."""
    return sum(
        int(np.prod(v.aval.shape)) * np.dtype(v.aval.dtype).itemsize
        for v in eqn.invars
        if hasattr(v.aval, "shape")
    )


def iter_eqns(jaxpr):
    """Depth-first walk over every eqn, recursing into inner jaxprs
    (pjit/shard_map/scan/cond bodies) — collectives live inside the
    shard_map eqn, never at top level."""
    for eqn in jaxpr.eqns:
        yield eqn
        for val in eqn.params.values():
            # Inner jaxprs appear as raw Jaxpr (has .eqns) or ClosedJaxpr
            # (has .jaxpr) param values; `branches` holds a tuple of them.
            if isinstance(val, (tuple, list)):
                for v in val:
                    if hasattr(v, "eqns"):
                        yield from iter_eqns(v)
                    elif hasattr(v, "jaxpr"):
                        yield from iter_eqns(v.jaxpr)
            elif hasattr(val, "eqns"):
                yield from iter_eqns(val)
            elif hasattr(val, "jaxpr"):
                yield from iter_eqns(val.jaxpr)


@dataclasses.dataclass
class CollectiveAudit:
    """Census of one traced program's collectives.

    ``counts`` — occurrences per collective primitive name.
    ``bytes_per_axis`` — per-device operand bytes charged to each mesh
    axis a collective runs over (an op over both axes charges both),
    ``str(axis) → bytes``.
    ``bytes_per_primitive`` — per-device operand bytes per primitive.
    ``op_bytes`` — per-device operand bytes of each individual occurrence,
    in trace order per primitive: with gradient bucketing this IS the
    per-bucket byte profile of the allreduce.
    ``async_pairs`` — start/done pairs folded into the counts (HLO-text
    audits only; a jaxpr audit never sees the split representation).
    ``overlap_fraction`` — fraction of those pairs with at least one
    real compute instruction scheduled strictly between start and done:
    the audit's measure of how much of the collective actually hides
    under backward compute (0.0 when there are no async pairs).
    """

    counts: Dict[str, int]
    bytes_per_axis: Dict[str, int]
    bytes_per_primitive: Dict[str, int]
    op_bytes: Dict[str, List[int]] = dataclasses.field(default_factory=dict)
    async_pairs: int = 0
    overlap_fraction: float = 0.0

    def census(self, keys=ALLREDUCE_CENSUS_KEYS) -> Dict[str, int]:
        """Fixed-key count view (zeros included) — the allreduce-bench
        ``hlo_collectives`` record shape.  Counts are normalized through
        :func:`fold_async_counts`, so an audit built from raw HLO
        opcodes (async pairs included) reports one logical collective
        per pair."""
        folded = fold_async_counts(self.counts)
        return {k: folded.get(k, 0) for k in keys}

    def reduction_collectives(self) -> int:
        """Total reduction-collective occurrences (psum + reduce_scatter)
        — the count bucketing makes O(n_buckets) instead of O(n_leaves).
        An ``all-reduce-start``/``all-reduce-done`` pair is ONE
        occurrence (:func:`fold_async_counts`)."""
        folded = fold_async_counts(self.counts)
        return sum(folded.get(k, 0) for k in REDUCTION_PRIMITIVES)

    def summary(self) -> dict:
        return {
            "counts": dict(self.counts),
            "bytes_per_axis": dict(self.bytes_per_axis),
            "bytes_per_primitive": dict(self.bytes_per_primitive),
            "op_bytes": {k: list(v) for k, v in self.op_bytes.items()},
            "reduction_collectives": self.reduction_collectives(),
            "async_pairs": self.async_pairs,
            "overlap_fraction": self.overlap_fraction,
        }


def audit_jaxpr(jaxpr) -> CollectiveAudit:
    """Audit an already-traced (Closed)Jaxpr."""
    if hasattr(jaxpr, "jaxpr"):  # ClosedJaxpr
        jaxpr = jaxpr.jaxpr
    counts: Dict[str, int] = {}
    per_axis: Dict[str, int] = {}
    per_prim: Dict[str, int] = {}
    op_bytes: Dict[str, List[int]] = {}
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMITIVES:
            continue
        counts[name] = counts.get(name, 0) + 1
        nbytes = _operand_bytes(eqn)
        per_prim[name] = per_prim.get(name, 0) + nbytes
        op_bytes.setdefault(name, []).append(nbytes)
        for ax in _eqn_axes(eqn):
            per_axis[str(ax)] = per_axis.get(str(ax), 0) + nbytes
    return CollectiveAudit(counts, per_axis, per_prim, op_bytes)


# ---------------------------------------------------------------------------
# HLO-text census — the post-compilation view, where async collectives
# appear as start/done pairs the jaxpr never contains.
# ---------------------------------------------------------------------------

#: HLO element type → itemsize, for payload bytes parsed out of HLO text.
_HLO_ITEMSIZE = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "f8e4m3fn": 1, "f8e5m2": 1, "c64": 8, "c128": 16,
}

#: Instructions that are pure plumbing — NOT evidence of compute between
#: an async start and its done (the scheduler moving a tuple or a
#: parameter between the halves hides nothing).
_HLO_NONCOMPUTE = frozenset((
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "bitcast-convert", "copy", "copy-start", "copy-done", "after-all",
    "partition-id", "replica-id", "opt-barrier", "domain",
))

_HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*(?P<rest>.+)$"
)
_HLO_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


class _HloInstr(NamedTuple):
    index: int
    name: str
    opcode: str
    operands: Tuple[str, ...]
    nbytes: int


def _hlo_shape_bytes(type_str: str) -> int:
    """Payload bytes of the FIRST array shape in an HLO type string —
    for a collective's result type this is the buffer it moves (async
    start tuples repeat the same buffer shape)."""
    m = _HLO_SHAPE_RE.search(type_str)
    if not m:
        return 0
    itemsize = _HLO_ITEMSIZE.get(m.group(1))
    if itemsize is None:
        return 0
    dims = m.group(2)
    elems = 1
    for d in dims.split(","):
        if d.strip():
            elems *= int(d)
    return elems * itemsize


def _parse_hlo_instr(index: int, line: str) -> Optional[_HloInstr]:
    m = _HLO_INSTR_RE.match(line)
    if m is None:
        return None
    rest = m.group("rest").lstrip()
    # Skip the result type: either one balanced-paren tuple type or a
    # single array/scalar token; the opcode follows immediately.
    type_str = rest
    if rest.startswith("("):
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    type_str, rest = rest[: i + 1], rest[i + 1:].lstrip()
                    break
        else:
            return None
    else:
        parts = rest.split(None, 1)
        if len(parts) < 2:
            return None
        type_str, rest = parts[0], parts[1]
    om = re.match(r"([a-zA-Z][\w\-]*)\s*\(", rest)
    if om is None:
        return None
    return _HloInstr(
        index=index,
        name=m.group("name"),
        opcode=om.group(1),
        operands=tuple(re.findall(r"%([\w.\-]+)", rest)),
        nbytes=_hlo_shape_bytes(type_str),
    )


def audit_hlo_text(hlo_text: str) -> CollectiveAudit:
    """Census of compiled HLO text (``jitted.lower(...).compile()
    .as_text()``), the representation where the TPU compiler's async
    machinery splits collectives into start/done pairs.

    Folding rule: an ``X-start``/``X-done`` pair is ONE logical ``X``
    (counted under the jaxpr-primitive name via
    :data:`HLO_COLLECTIVE_OPS`), with the pair tallied in
    ``async_pairs``; an unmatched ``-start`` still counts once (the
    collective exists) and an unmatched ``-done`` never does.
    ``overlap_fraction`` is the fraction of matched pairs with at least
    one non-plumbing instruction scheduled strictly between start and
    done — the post-scheduler evidence that gradient collectives hide
    under backward compute.  ``bytes_per_axis`` stays empty (HLO has
    replica groups, not mesh-axis names); per-collective payload bytes
    land in ``op_bytes``/``bytes_per_primitive`` as usual.
    """
    instrs: List[_HloInstr] = []
    by_name: Dict[str, _HloInstr] = {}
    for i, line in enumerate(hlo_text.splitlines()):
        ins = _parse_hlo_instr(i, line)
        if ins is not None:
            instrs.append(ins)
            by_name[ins.name] = ins

    counts: Dict[str, int] = {}
    per_prim: Dict[str, int] = {}
    op_bytes: Dict[str, List[int]] = {}
    async_pairs = 0
    overlapped = 0
    consumed_dones = set()

    def _tally(prim: str, nbytes: int) -> None:
        counts[prim] = counts.get(prim, 0) + 1
        per_prim[prim] = per_prim.get(prim, 0) + nbytes
        op_bytes.setdefault(prim, []).append(nbytes)

    # Pair dones with their starts first (done references the start's
    # result by name), so the start-side walk knows which are paired.
    start_to_done: Dict[str, _HloInstr] = {}
    for ins in instrs:
        if not ins.opcode.endswith(_ASYNC_DONE):
            continue
        base = ins.opcode[: -len(_ASYNC_DONE)]
        if base not in HLO_COLLECTIVE_OPS:
            continue
        for operand in ins.operands:
            src = by_name.get(operand)
            if src is not None and src.opcode == base + _ASYNC_START:
                start_to_done[src.name] = ins
                consumed_dones.add(ins.name)
                break

    for ins in instrs:
        op = ins.opcode
        if op in HLO_COLLECTIVE_OPS:
            _tally(HLO_COLLECTIVE_OPS[op], ins.nbytes)
            continue
        if op.endswith(_ASYNC_START):
            base = op[: -len(_ASYNC_START)]
            if base not in HLO_COLLECTIVE_OPS:
                continue
            _tally(HLO_COLLECTIVE_OPS[base], ins.nbytes)
            done = start_to_done.get(ins.name)
            if done is None:
                continue
            async_pairs += 1
            between = (
                other for other in instrs
                if ins.index < other.index < done.index
            )
            if any(
                o.opcode not in _HLO_NONCOMPUTE
                and o.opcode not in HLO_COLLECTIVE_OPS
                and not o.opcode.endswith((_ASYNC_START, _ASYNC_DONE))
                for o in between
            ):
                overlapped += 1
    return CollectiveAudit(
        counts=counts,
        bytes_per_axis={},
        bytes_per_primitive=per_prim,
        op_bytes=op_bytes,
        async_pairs=async_pairs,
        overlap_fraction=(overlapped / async_pairs) if async_pairs else 0.0,
    )


def audit_compiled(fn, *args, **kwargs) -> CollectiveAudit:
    """Compile ``fn(*args, **kwargs)`` (jitted or plain) and audit the
    OPTIMIZED HLO — the only level where async start/done pairs and the
    latency-hiding schedule are visible.  Args may be real arrays or
    ``jax.ShapeDtypeStruct``s; nothing executes.  This is what
    ``bench.py`` reports its ``overlap_fraction`` from."""
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    return audit_hlo_text(compiled.as_text())


class TracedStep(NamedTuple):
    """One abstract trace of a step function — the shared entry point the
    audit AND the collective linter (:mod:`chainermn_tpu.analysis`) build
    on, so a step is traced exactly once however it is wrapped.

    ``donate_argnums`` carries the jit wrapper's donation declaration when
    the AOT ``trace`` path supplied it; ``None`` means "unknown — look for
    ``pjit`` eqn ``donated_invars`` inside the jaxpr instead".
    """

    closed_jaxpr: Any
    donate_argnums: Optional[Tuple[int, ...]]


def trace_step(fn, *args, **kwargs) -> TracedStep:
    """Trace ``fn(*args, **kwargs)`` without executing it.

    Accepts plain callables AND already-``jax.jit``-wrapped ones: a jitted
    callable is traced through its own AOT ``.trace`` surface (one trace,
    reusing jit's cached machinery — no re-wrap double-trace), which also
    exposes its ``donate_argnums``; everything else goes through
    ``jax.make_jaxpr``, kwargs included.  Args may be real arrays or
    ``jax.ShapeDtypeStruct``s."""
    import jax

    tracer = getattr(fn, "trace", None)
    if callable(tracer):
        try:
            tr = tracer(*args, **kwargs)
            closed = getattr(tr, "jaxpr", None)
            if closed is not None:
                donate = getattr(tr, "donate_argnums", None)
                return TracedStep(
                    closed, tuple(donate) if donate is not None else None
                )
        except Exception:
            pass  # not jit's AOT surface — fall through to make_jaxpr
    return TracedStep(jax.make_jaxpr(fn)(*args, **kwargs), None)


def audit_fn(fn, *args, **kwargs) -> CollectiveAudit:
    """Trace ``fn(*args, **kwargs)`` (jitted or plain) and audit the
    resulting program.  Args may be real arrays or
    ``jax.ShapeDtypeStruct``s; nothing executes.  Delegates the tracing
    to :func:`trace_step` — the entry point shared with the collective
    linter — so jitted callables and kwargs take the single-trace path."""
    return audit_jaxpr(trace_step(fn, *args, **kwargs).closed_jaxpr)


def _allreduce_jaxpr(comm, nbytes: int, dtype):
    """The traced ``allreduce_grad`` lowering every per-communicator
    census is computed on: a rank-stacked (device_size, elems) buffer
    through the communicator's characteristic collective pattern."""
    import jax
    import jax.numpy as jnp

    n = comm.device_size
    elems = max(1, nbytes // np.dtype(dtype).itemsize)
    spec = comm._world_spec

    def body(tree):
        sq = jax.tree.map(lambda x: jnp.squeeze(x, 0), tree)
        out = comm.allreduce_grad(sq)
        return jax.tree.map(lambda x: x[None], out)

    return jax.make_jaxpr(comm.shard_map(
        body, in_specs=({"g": spec},), out_specs={"g": spec}
    ))({"g": jnp.ones((n, elems), dtype)})


def audit_allreduce(comm, nbytes: int, dtype=np.float32) -> CollectiveAudit:
    """Audit one communicator's gradient-allreduce path at a given
    per-device payload — the library home of bench.py's
    ``allreduce_static_bytes_per_leg`` numbers."""
    return audit_jaxpr(_allreduce_jaxpr(comm, nbytes, dtype))


def audit_allreduce_tree(comm, tree) -> CollectiveAudit:
    """Audit ``allreduce_grad`` over a FULL gradient pytree.

    ``tree`` carries per-device leaf shapes (no leading rank axis) —
    arrays or ``jax.ShapeDtypeStruct``s; nothing executes.  This is the
    many-leaf generalization of :func:`audit_allreduce`: with bucketing
    on, ``reduction_collectives()`` is O(n_buckets) and ``op_bytes``
    holds each bucket's wire size; with ``bucket_bytes=0`` it shows the
    legacy per-leaf lowering for comparison.
    """
    import jax
    import jax.numpy as jnp

    n = comm.device_size
    spec = comm._world_spec
    stacked = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct((n,) + tuple(l.shape), l.dtype), tree
    )
    specs = jax.tree.map(lambda _: spec, stacked)

    def body(t):
        sq = jax.tree.map(lambda x: jnp.squeeze(x, 0), t)
        out = comm.allreduce_grad(sq)
        return jax.tree.map(lambda x: x[None], out)

    return audit_jaxpr(jax.make_jaxpr(comm.shard_map(
        body, in_specs=(specs,), out_specs=specs
    ))(stacked))


def assert_two_dimensional_inter_savings(profiles: dict,
                                         intra_size: int) -> None:
    """``profiles``: {communicator_name: bytes_per_axis dict}.  Asserts
    the 2D claim when both sides are present: two_dimensional's
    inter-axis operand bytes == flat's / intra_size (SURVEY §2.1
    two-dimensional row — the reference's rationale for the 2D algorithm
    on >1 GbE clusters)."""
    flat = next(
        (profiles[k] for k in ("flat", "xla_ici", "pure_nccl")
         if k in profiles), None,
    )
    td = profiles.get("two_dimensional")
    if flat is None or td is None:
        return
    flat_inter = flat.get("inter", 0)
    td_inter = td.get("inter", 0)
    assert flat_inter > 0 and td_inter > 0, (profiles,)
    assert td_inter * intra_size == flat_inter, (
        f"two_dimensional inter-axis bytes {td_inter} x intra "
        f"{intra_size} != flat's {flat_inter} — the 2D bandwidth claim "
        "does not hold in the traced lowering"
    )
