"""Metrics registry — the reference's ``Reporter`` re-grounded for SPMD.

Reference: Chainer's ``Reporter``/``DictSummary`` (REF:chainer/reporter.py,
consumed by ChainerMN's examples through ``LogReport``) — a process-local
registry of named observations that extensions read and reset per report
interval.  The TPU-native difference is the aggregation plane: the
reference ran one process per GPU and let ``LogReport`` average locally,
leaning on the evaluator's ``allreduce_obj`` for the cross-process view.
Here one :class:`Reporter` per process accumulates host-side observations
(scalars, counters, histograms) and :meth:`Reporter.aggregate` merges them
across processes through the communicator's object plane — mean/sum/max
reductions usable on rank 0 (and returned on every rank, keeping callers
SPMD-branch-free), off-TPU safe on the naive/single-host communicators
where the object plane degenerates to a local no-op.

Three metric kinds, chosen to merge exactly under concatenation so the
cross-host reduction is lossless:

* **scalar** — ``observe(name, v)`` keeps ``(count, sum, min, max, last)``;
  the mean is ``sum/count`` so a weighted cross-host mean needs no
  per-observation storage.
* **counter** — ``count(name, n)`` a monotonic sum (events, steps, bytes).
* **histogram** — ``histogram_observe(name, v)`` buckets ``v`` into
  power-of-two bins (log2 of the upper bound), the standard
  latency-histogram shape; bucket counts sum across hosts.
* **gauge** — ``gauge(name, v)`` a set-style level (queue depth, cache
  occupancy): the LAST value wins locally — re-setting replaces, never
  accumulates — and ranks merge to ``sum`` with ``min``/``max``, the
  natural reading for capacity-like levels (total in-flight across the
  job, plus the most/least loaded rank).

A module-level *current reporter* stack (``scope``/``get_reporter``/
``report``) mirrors the reference's ``reporter.report({...})`` idiom so
library code (the multi-node evaluator, span timings) can publish metrics
without threading a reporter handle through every call.
"""

from __future__ import annotations

import contextlib
import math
import threading
from typing import Dict, Mapping, Optional


class _Scalar:
    __slots__ = ("count", "sum", "min", "max", "last")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.last = 0.0

    def add(self, v: float):
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        self.last = v

    def merge(self, d: Mapping):
        if d["count"] == 0:
            return
        self.count += d["count"]
        self.sum += d["sum"]
        self.min = min(self.min, d["min"])
        self.max = max(self.max, d["max"])
        self.last = d["last"]  # merge order = rank order; rank-dependent

    def snapshot(self) -> dict:
        out = {"count": self.count, "sum": self.sum, "last": self.last,
               "min": self.min, "max": self.max}
        if self.count:
            out["mean"] = self.sum / self.count
        return out


class _Gauge:
    """Merge-side accumulator for set-style gauges.  A single rank's
    snapshot is ``{"value": v, "sum": v, "min": v, "max": v, "n": 1}``;
    merging sums ``sum``/``n`` and spreads ``min``/``max`` — composable,
    so a merge of merges equals one flat merge."""

    __slots__ = ("value", "sum", "min", "max", "n")

    def __init__(self):
        self.value = 0.0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.n = 0

    def merge(self, d: Mapping):
        if d.get("n", 0) == 0:
            return
        self.value = d["value"]  # merge order = rank order
        self.sum += d["sum"]
        self.min = min(self.min, d["min"])
        self.max = max(self.max, d["max"])
        self.n += d["n"]

    def snapshot(self) -> dict:
        return {"value": self.value, "sum": self.sum, "min": self.min,
                "max": self.max, "n": self.n}


def _bucket(v: float) -> int:
    """Histogram bucket id: ceil(log2(v)) clamped into [-30, 63] (bucket b
    covers (2^(b-1), 2^b]); non-positive values land in the lowest bucket."""
    if v <= 0:
        return -30
    return max(-30, min(63, math.ceil(math.log2(v))))


class Reporter:
    """Process-local metrics registry.  Thread-safe: the prefetch thread,
    jax.monitoring listeners, and the train loop may all observe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._scalars: Dict[str, _Scalar] = {}
        self._counters: Dict[str, float] = {}
        self._hists: Dict[str, Dict[int, int]] = {}
        self._gauges: Dict[str, float] = {}

    # -- write side ----------------------------------------------------
    def observe(self, name: str, value) -> None:
        """Record one scalar observation (loss, step time, grad norm)."""
        v = float(value)
        with self._lock:
            self._scalars.setdefault(name, _Scalar()).add(v)

    def count(self, name: str, n=1) -> None:
        """Bump a monotonic counter (steps, compile events, bytes)."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def histogram_observe(self, name: str, value) -> None:
        """Record one observation into the power-of-two histogram."""
        b = _bucket(float(value))
        with self._lock:
            h = self._hists.setdefault(name, {})
            h[b] = h.get(b, 0) + 1

    def gauge(self, name: str, value) -> None:
        """Set a level gauge (queue depth, cache occupancy): last value
        wins — setting replaces the previous value, never accumulates."""
        v = float(value)
        with self._lock:
            self._gauges[name] = v

    def report(self, values: Mapping[str, float]) -> None:
        """Batch scalar observations — the reference's ``report({...})``."""
        for k, v in values.items():
            self.observe(k, v)

    # -- read side -----------------------------------------------------
    def summary(self) -> dict:
        """Plain-dict snapshot: ``{"scalars": {...}, "counters": {...},
        "histograms": {...}}`` — JSON-safe, the merge/wire format."""
        with self._lock:
            return {
                "scalars": {
                    k: s.snapshot() for k, s in self._scalars.items()
                },
                "counters": dict(self._counters),
                # JSON object keys are strings; keep int buckets on the
                # in-memory side, stringify only here.
                "histograms": {
                    k: {str(b): c for b, c in h.items()}
                    for k, h in self._hists.items()
                },
                "gauges": {
                    k: {"value": v, "sum": v, "min": v, "max": v, "n": 1}
                    for k, v in self._gauges.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._scalars.clear()
            self._counters.clear()
            self._hists.clear()
            self._gauges.clear()

    def forget_replica(self, replica_id) -> int:
        """Drop every series labelled with ``replica_id`` (names ending
        in ``/replica/<id>`` or containing it as a path segment).

        A retired or SIGKILLed replica otherwise leaves its last
        ``serving/*/replica/<id>`` gauges in the registry forever — an
        operator's dashboard would show a dead replica at its final
        (healthy-looking) levels.  Returns the number of series dropped.
        """
        tail = f"/replica/{replica_id}"
        mid = tail + "/"

        def stale(name: str) -> bool:
            return name.endswith(tail) or mid in name

        dropped = 0
        with self._lock:
            for table in (self._scalars, self._counters, self._hists,
                          self._gauges):
                for name in [k for k in table if stale(k)]:
                    del table[name]
                    dropped += 1
        return dropped

    # -- cross-host ----------------------------------------------------
    def aggregate(self, comm, reset: bool = False) -> dict:
        """Merge every process's summary across ``comm``'s host plane.

        One object-plane allgather (the reference evaluator's
        ``allreduce_obj`` mechanism) carries each rank's snapshot; the
        merge is performed identically on every rank, so the result is
        valid everywhere while rank 0 does the logging (the reference
        pattern).  Scalars merge to the observation-weighted mean with
        global min/max; counters and histogram buckets sum.  Single-host
        communicators (naive / single_host / one-process xla_ici) take
        the trivial path — no collective, off-TPU safe.
        """
        local = self.summary()
        if getattr(comm, "size", 1) > 1:
            snaps = comm.gather_obj(local)  # allgather: list on every rank
        else:
            snaps = [local]
        merged = merge_summaries(snaps)
        if reset:
            self.reset()
        return merged


def merge_summaries(snapshots) -> dict:
    """Merge :meth:`Reporter.summary` dicts (one per rank) into one —
    the pure reduction :meth:`Reporter.aggregate` applies after its
    allgather, exposed for tests and offline tooling."""
    scalars: Dict[str, _Scalar] = {}
    counters: Dict[str, float] = {}
    hists: Dict[str, Dict[str, int]] = {}
    gauges: Dict[str, _Gauge] = {}
    for snap in snapshots:
        for k, d in snap.get("scalars", {}).items():
            scalars.setdefault(k, _Scalar()).merge(d)
        for k, v in snap.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, h in snap.get("histograms", {}).items():
            out = hists.setdefault(k, {})
            for b, c in h.items():
                out[b] = out.get(b, 0) + c
        for k, d in snap.get("gauges", {}).items():
            gauges.setdefault(k, _Gauge()).merge(d)
    return {
        "scalars": {k: s.snapshot() for k, s in scalars.items()},
        "counters": counters,
        "histograms": hists,
        "gauges": {k: g.snapshot() for k, g in gauges.items()},
    }


# ---------------------------------------------------------------------------
# Current-reporter stack (the reference's thread-global reporter idiom)
# ---------------------------------------------------------------------------
_stack: list = []
_stack_lock = threading.Lock()


def get_reporter() -> Optional[Reporter]:
    """The innermost active reporter, or ``None`` (telemetry off)."""
    with _stack_lock:
        return _stack[-1] if _stack else None


@contextlib.contextmanager
def scope(reporter: Reporter):
    """Make ``reporter`` current for the with-block (re-entrant)."""
    with _stack_lock:
        _stack.append(reporter)
    try:
        yield reporter
    finally:
        with _stack_lock:
            _stack.remove(reporter)


def report(values: Mapping[str, float]) -> None:
    """Publish scalars to the current reporter; silent no-op when none is
    active — library call sites stay unconditional."""
    r = get_reporter()
    if r is not None:
        r.report(values)
