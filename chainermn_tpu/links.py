"""MultiNodeChainList — a model spanning device ranks.

Reference: REF:chainermn/links.py — ``MultiNodeChainList(comm)`` with
``add_link(link, rank_in=, rank_out=)``: an orchestrating ``__call__``
walks the registered components, calling ``recv`` for ``rank_in``, the
sublink, and ``send`` for ``rank_out``, threading delegate variables so
cross-process backprop sequences correctly (SURVEY §3.3).  In the
reference's per-process world each rank constructs the chain holding *its*
components, and ``rank_in``/``rank_out`` name peer ranks; deadlock-freedom
comes from every rank issuing sends/recvs in matching order by
construction.

TPU-native translation: one traced SPMD program describes *all* ranks, so

* each component names its ``rank`` (owner) explicitly — the fact the
  reference read from ``comm.rank`` implicitly;
* every transfer is a single ``lax.ppermute`` issued by
  ``functions.send`` and unwrapped by ``functions.recv``; matching order
  is by construction of the component walk, as in the reference, but
  enforced at trace time — a mismatched send/recv is a *trace error*
  (missing in-flight payload), not a runtime deadlock;
* non-owner devices skip a component's FLOPs via ``lax.cond`` on the
  traced rank (both branches compile; one executes), with parameters
  replicated — the stage-sharded perf path for homogeneous stage stacks
  is ``chainermn_tpu.parallel.pipeline``;
* the final component's output is broadcast to every rank via the masked
  psum, so the loss is globally available (what the reference achieved by
  evaluating loss on the last rank only).

Memory tiers:

* ``apply``/``make_forward`` — parameters replicated on every device
  (simple, fine for small models, the reference's effective profile
  since each ChainerMN process held only its own submodel but the
  equivalent here replicates);
* ``shard_params`` + ``apply_sharded``/``make_sharded_train_step`` — the
  heterogeneous-pipeline memory tier: each device *persistently* holds
  one flat fp32 row packing only the components it owns (a ragged
  stage-sharded layout; the global buffer is ``(n * row_size,)`` sharded
  over the world, ``row_size`` = the largest per-device packed total).
  At each component every device transiently unpacks that component's
  tree from its own row, masked to zeros on non-owners — zeros keep
  every branch finite for standard NN blocks, and masking is a
  ``select`` so forward values and gradients are exact.  Per-device
  persistent parameter footprint is its OWN stages (≈ ``1/n`` for a
  balanced chain), the property the reference got for free from
  one-process-per-rank and the replicated tier gives up.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.functions import point_to_point as p2p


class _Component(NamedTuple):
    fn: Callable            # fn(params, x) -> y  (local compute, no collectives)
    rank: int               # owner flat device rank
    rank_in: Optional[Sequence[int]]
    rank_out: Optional[Sequence[int]]
    needs_input: bool       # also pass the chain's global input to fn


def _as_ranks(r) -> Optional[Sequence[int]]:
    if r is None:
        return None
    if isinstance(r, int):
        return (r,)
    return tuple(r)


class MultiNodeChainList:
    """Declarative model-spanning container (reference-parity API, explicit
    owner rank added — see module docstring)."""

    def __init__(self, comm: CommunicatorBase):
        self.comm = comm
        self._components: list[_Component] = []
        self._shard_meta = None  # set by shard_params

    def add_link(
        self,
        fn: Callable,
        rank: int,
        rank_in=None,
        rank_out=None,
        needs_input: bool = False,
    ):
        """Register ``fn(params, x) -> y`` owned by flat device ``rank``.

        ``rank_in``: peer rank(s) whose sends feed this component (None →
        the chain's global input).  ``rank_out``: peer rank(s) to send the
        output to (None → this component's output is the chain's output).
        Matches the reference's ``add_link(link, rank_in, rank_out)`` with
        the owner made explicit.  ``needs_input=True`` additionally passes
        the chain's global input after the received payload(s) — the
        analogue of a reference component closing over its local batch
        (e.g. a decoder needing both the encoder state and the target
        tokens).
        """
        self._components.append(
            _Component(fn, rank, _as_ranks(rank_in), _as_ranks(rank_out), needs_input)
        )
        return self

    # ------------------------------------------------------------------
    def apply(self, params_list: Sequence[Any], x):
        """Traced SPMD forward — call inside ``shard_map`` over the
        communicator's axes (or use :meth:`make_forward`).

        ``params_list[i]`` are the i-th registered component's parameters
        (replicated tier).  Returns the final component's output,
        broadcast to every rank.
        """
        if len(params_list) != len(self._components):
            raise ValueError(
                f"params_list has {len(params_list)} entries for "
                f"{len(self._components)} components"
            )
        return self._walk(lambda i, c: params_list[i], x)

    def _walk(self, get_params: Callable, x):
        """The component walk shared by the replicated and sharded tiers.
        ``get_params(i, component)`` produces component i's parameter tree
        in the current trace context."""
        comm = self.comm
        my_rank = comm.axis_index()

        # In-flight transfers keyed by (src_rank, dst_rank) — FIFO per edge,
        # so matching order is by construction as in the reference.
        inflight: dict[tuple[int, int], list] = {}
        out = None

        for i, component in enumerate(self._components):
            fn, owner, rank_in, rank_out, needs_input = component
            params = get_params(i, component)

            # 1. Gather inputs (reference: recv for rank_in).
            if rank_in is None:
                inp = x
            else:
                payloads = []
                for src in rank_in:
                    queue = inflight.get((src, owner))
                    if not queue:
                        raise ValueError(
                            f"component owned by rank {owner} expects a send "
                            f"from rank {src}, but no send to {owner} was "
                            "issued earlier in the chain — check "
                            "rank_in/rank_out wiring (the reference would "
                            "deadlock here; we fail at trace time)"
                        )
                    delegate = queue.pop(0)
                    payloads.append(p2p.recv(comm, src, delegate_variable=delegate))
                if needs_input:
                    payloads.append(x)
                inp = payloads[0] if len(payloads) == 1 else tuple(payloads)

            # 2. Local compute, skipped (runtime branch) on non-owners.
            out_shape = jax.eval_shape(fn, params, inp)
            y = lax.cond(
                my_rank == owner,
                lambda p, v: fn(p, v),
                lambda p, v: jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), out_shape
                ),
                params,
                inp,
            )

            # 3. Emit outputs (reference: send for rank_out).
            if rank_out is None:
                out = (y, owner)
            else:
                for dst in rank_out:
                    delegate = p2p.send(y, comm, dst, src=owner)
                    inflight.setdefault((owner, dst), []).append(delegate)

        if out is None:
            raise ValueError(
                "no component has rank_out=None; the chain never produces "
                "an output"
            )
        y, owner = out
        # Broadcast the final output from its owner so every rank returns
        # the same value (loss available globally).
        return jax.tree.map(lambda v: comm.bcast(v, owner), y)

    def make_forward(self, batch_spec=P(), jit: bool = True):
        """Wrap :meth:`apply` in ``shard_map`` (params replicated, input per
        ``batch_spec``), optionally jitted — the "just call the model"
        surface the reference's ``__call__`` provided."""
        comm = self.comm

        def fwd(params_list, x):
            return self.apply(params_list, x)

        mapped = comm.shard_map(
            fwd, in_specs=(P(), batch_spec), out_specs=P()
        )
        return jax.jit(mapped) if jit else mapped

    # ------------------------------------------------------------------
    # Sharded-parameter tier (heterogeneous pipeline memory profile)
    # ------------------------------------------------------------------
    @property
    def _world(self):
        return self.comm.world_axes

    def shard_params(self, params_list: Sequence[Any]):
        """Pack each component's parameters into its owner's flat fp32 row
        and return the ``(n * row_size,)`` global buffer sharded over the
        world — each device persistently holds only its OWN components.

        The returned buffer is what :meth:`apply_sharded` /
        :meth:`make_sharded_train_step` trade in; recover the pytree list
        with :meth:`materialize_params`.
        """
        import numpy as np
        from jax.sharding import NamedSharding

        if len(params_list) != len(self._components):
            raise ValueError(
                f"params_list has {len(params_list)} entries for "
                f"{len(self._components)} components"
            )
        comm = self.comm
        n = comm.device_size
        metas, offsets = [], []
        cursor = {r: 0 for r in range(n)}
        for comp, params in zip(self._components, params_list):
            if not (0 <= comp.rank < n):
                raise ValueError(
                    f"component owner rank {comp.rank} outside the "
                    f"{n}-device world"
                )
            leaves, treedef = jax.tree.flatten(params)
            leaf_meta = tuple(
                (l.shape, jnp.asarray(l).dtype, int(jnp.asarray(l).size))
                for l in leaves
            )
            size = sum(m[2] for m in leaf_meta)
            metas.append((treedef, leaf_meta))
            offsets.append(cursor[comp.rank])
            cursor[comp.rank] += size
        row_size = max(max(cursor.values(), default=0), 1)
        # Fully hashable (treedefs, shape/dtype tuples): used as the
        # compile-cache key everywhere a traced program bakes it in.
        self._shard_meta = (tuple(metas), tuple(offsets), row_size)

        rows = np.zeros((n, row_size), np.float32)
        cur = {r: 0 for r in range(n)}
        for comp, params in zip(self._components, params_list):
            vec = np.concatenate(
                [
                    np.asarray(l, np.float32).reshape(-1)
                    for l in jax.tree.leaves(params)
                ] or [np.zeros((0,), np.float32)]
            )
            rows[comp.rank, cur[comp.rank] : cur[comp.rank] + vec.size] = vec
            cur[comp.rank] += vec.size
        return jax.device_put(
            jnp.asarray(rows.reshape(-1)),
            NamedSharding(comm.mesh, P(self._world)),
        )

    def _unpack_component(self, row, i):
        """Component i's parameter tree sliced out of the local row —
        meaningful on the owner, arbitrary elsewhere (callers mask)."""
        (metas, offsets, _row_size) = self._shard_meta
        treedef, leaf_meta = metas[i]
        off = offsets[i]
        leaves = []
        for shape, dtype, size in leaf_meta:
            leaves.append(row[off : off + size].reshape(shape).astype(dtype))
            off += size
        return jax.tree.unflatten(treedef, leaves)

    def apply_sharded(self, row, x):
        """Traced SPMD forward over the sharded parameter row (this
        device's ``(row_size,)`` slice of the :meth:`shard_params` buffer).
        Same semantics as :meth:`apply` with per-device persistent memory
        ≈ the device's own components."""
        self._require_shard_meta()
        comm = self.comm
        my_rank = comm.axis_index()

        def get_params(i, component):
            tree = self._unpack_component(row, i)
            # Mask non-owners to zero parameters: the local row holds a
            # DIFFERENT component's bytes there, and zeros keep every
            # transient branch finite (select → exact values and grads).
            return jax.tree.map(
                lambda l: jnp.where(my_rank == component.rank, l,
                                    jnp.zeros_like(l)),
                tree,
            )

        return self._walk(get_params, x)

    def _shard_jit_cache(self):
        cache = getattr(self, "_shard_jit", None)
        if cache is None:
            cache = self._shard_jit = {}
        return cache

    def materialize_params(self, flat):
        """Sharded row buffer → replicated ``params_list`` (for eval,
        checkpoint export, or moving back to the replicated tier).  The
        jitted gather+unpack program is cached per shard layout, so
        eval-per-epoch loops don't recompile."""
        self._require_shard_meta()
        comm = self.comm
        world = self._world
        (metas, offsets, row_size) = self._shard_meta

        cache = self._shard_jit_cache()
        key = ("materialize", self._shard_meta)
        fn = cache.get(key)
        if fn is None:

            def body(flat_local):
                rows = lax.all_gather(flat_local, world, axis=0, tiled=True)
                rows = rows.reshape(comm.device_size, row_size)
                return tuple(
                    self._unpack_component(rows[c.rank], i)
                    for i, c in enumerate(self._components)
                )

            fn = cache[key] = jax.jit(
                comm.shard_map(body, in_specs=(P(world),), out_specs=P())
            )
        return fn(flat)

    def _require_shard_meta(self):
        if getattr(self, "_shard_meta", None) is None:
            raise RuntimeError("call shard_params(params_list) first")

    def _row_state_spec(self, optimizer, row_size):
        from chainermn_tpu.optimizers import flat_shard_state_spec

        return flat_shard_state_spec(optimizer, row_size, self._world)

    def make_sharded_train_step(
        self,
        optimizer,
        loss_fn: Callable,
        batch_spec=P(),
        donate: bool = True,
    ):
        """Build a jitted train step over the sharded row buffer.

        This is pure model parallelism (the reference's seq2seq shape):
        every rank sees the SAME batch (``create_multi_node_iterator``'s
        invariant), so gradients need no cross-rank averaging — each
        device's row gradient concerns only its own components, and the
        ``optax`` update runs on the local row shard (optimizer state is
        sharded alongside, ZeRO-style for free).

        ``loss_fn(chain_output, batch) -> scalar``; the chain input is
        ``batch`` itself (components select what they need; use
        ``needs_input=True`` components for targets).

        Returns ``step(row, opt_state, batch) -> (row, opt_state, loss)``.
        """
        import optax as _optax

        comm = self.comm
        world = self._world

        def body(row, opt_state, batch):
            def loss_of(r):
                out = self.apply_sharded(r, batch)
                return loss_fn(out, batch)

            loss, grow = jax.value_and_grad(loss_of)(row)
            updates, opt_state = optimizer.update(grow, opt_state, row)
            return _optax.apply_updates(row, updates), opt_state, loss

        compiled = {}

        def step(row, opt_state, batch):
            self._require_shard_meta()
            row_size = self._shard_meta[2]
            # The traced body bakes in the shard layout (offsets,
            # treedefs), so the cache key must include it — a later
            # shard_params with a different layout but equal row shape
            # must re-trace, not silently reuse the wrong unpacking.
            key = (row.shape, self._shard_meta)
            fn = compiled.get(key)
            if fn is None:
                spec = self._row_state_spec(optimizer, row_size)
                mapped = comm.shard_map(
                    body,
                    in_specs=(P(world), spec, batch_spec),
                    out_specs=(P(world), spec, P()),
                )
                fn = compiled[key] = jax.jit(
                    mapped, donate_argnums=(0, 1) if donate else ()
                )
            return fn(row, opt_state, batch)

        return step

    def init_sharded_opt_state(self, optimizer, row):
        """Optimizer state for the sharded row (state sharded alongside the
        parameters — each device holds state only for its own stages)."""
        self._require_shard_meta()
        comm = self.comm
        world = self._world
        spec = self._row_state_spec(optimizer, self._shard_meta[2])
        return jax.jit(
            comm.shard_map(
                lambda local: optimizer.init(local),
                in_specs=(P(world),), out_specs=spec,
            )
        )(row)
