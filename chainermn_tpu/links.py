"""MultiNodeChainList — a model spanning device ranks.

Reference: REF:chainermn/links.py — ``MultiNodeChainList(comm)`` with
``add_link(link, rank_in=, rank_out=)``: an orchestrating ``__call__``
walks the registered components, calling ``recv`` for ``rank_in``, the
sublink, and ``send`` for ``rank_out``, threading delegate variables so
cross-process backprop sequences correctly (SURVEY §3.3).  In the
reference's per-process world each rank constructs the chain holding *its*
components, and ``rank_in``/``rank_out`` name peer ranks; deadlock-freedom
comes from every rank issuing sends/recvs in matching order by
construction.

TPU-native translation: one traced SPMD program describes *all* ranks, so

* each component names its ``rank`` (owner) explicitly — the fact the
  reference read from ``comm.rank`` implicitly;
* every transfer is a single ``lax.ppermute`` issued by
  ``functions.send`` and unwrapped by ``functions.recv``; matching order
  is by construction of the component walk, as in the reference, but
  enforced at trace time — a mismatched send/recv is a *trace error*
  (missing in-flight payload), not a runtime deadlock;
* non-owner devices skip a component's FLOPs via ``lax.cond`` on the
  traced rank (both branches compile; one executes), with parameters
  replicated — the stage-sharded perf path is
  ``chainermn_tpu.parallel.pipeline``;
* the final component's output is broadcast to every rank via the masked
  psum, so the loss is globally available (what the reference achieved by
  evaluating loss on the last rank only).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.functions import point_to_point as p2p


class _Component(NamedTuple):
    fn: Callable            # fn(params, x) -> y  (local compute, no collectives)
    rank: int               # owner flat device rank
    rank_in: Optional[Sequence[int]]
    rank_out: Optional[Sequence[int]]
    needs_input: bool       # also pass the chain's global input to fn


def _as_ranks(r) -> Optional[Sequence[int]]:
    if r is None:
        return None
    if isinstance(r, int):
        return (r,)
    return tuple(r)


class MultiNodeChainList:
    """Declarative model-spanning container (reference-parity API, explicit
    owner rank added — see module docstring)."""

    def __init__(self, comm: CommunicatorBase):
        self.comm = comm
        self._components: list[_Component] = []

    def add_link(
        self,
        fn: Callable,
        rank: int,
        rank_in=None,
        rank_out=None,
        needs_input: bool = False,
    ):
        """Register ``fn(params, x) -> y`` owned by flat device ``rank``.

        ``rank_in``: peer rank(s) whose sends feed this component (None →
        the chain's global input).  ``rank_out``: peer rank(s) to send the
        output to (None → this component's output is the chain's output).
        Matches the reference's ``add_link(link, rank_in, rank_out)`` with
        the owner made explicit.  ``needs_input=True`` additionally passes
        the chain's global input after the received payload(s) — the
        analogue of a reference component closing over its local batch
        (e.g. a decoder needing both the encoder state and the target
        tokens).
        """
        self._components.append(
            _Component(fn, rank, _as_ranks(rank_in), _as_ranks(rank_out), needs_input)
        )
        return self

    # ------------------------------------------------------------------
    def apply(self, params_list: Sequence[Any], x):
        """Traced SPMD forward — call inside ``shard_map`` over the
        communicator's axes (or use :meth:`make_forward`).

        ``params_list[i]`` are the i-th registered component's parameters.
        Returns the final component's output, broadcast to every rank.
        """
        if len(params_list) != len(self._components):
            raise ValueError(
                f"params_list has {len(params_list)} entries for "
                f"{len(self._components)} components"
            )
        comm = self.comm
        my_rank = comm.axis_index()

        # In-flight transfers keyed by (src_rank, dst_rank) — FIFO per edge,
        # so matching order is by construction as in the reference.
        inflight: dict[tuple[int, int], list] = {}
        out = None

        for component, params in zip(self._components, params_list):
            fn, owner, rank_in, rank_out, needs_input = component

            # 1. Gather inputs (reference: recv for rank_in).
            if rank_in is None:
                inp = x
            else:
                payloads = []
                for src in rank_in:
                    queue = inflight.get((src, owner))
                    if not queue:
                        raise ValueError(
                            f"component owned by rank {owner} expects a send "
                            f"from rank {src}, but no send to {owner} was "
                            "issued earlier in the chain — check "
                            "rank_in/rank_out wiring (the reference would "
                            "deadlock here; we fail at trace time)"
                        )
                    delegate = queue.pop(0)
                    payloads.append(p2p.recv(comm, src, delegate_variable=delegate))
                if needs_input:
                    payloads.append(x)
                inp = payloads[0] if len(payloads) == 1 else tuple(payloads)

            # 2. Local compute, skipped (runtime branch) on non-owners.
            out_shape = jax.eval_shape(fn, params, inp)
            y = lax.cond(
                my_rank == owner,
                lambda p, v: fn(p, v),
                lambda p, v: jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), out_shape
                ),
                params,
                inp,
            )

            # 3. Emit outputs (reference: send for rank_out).
            if rank_out is None:
                out = (y, owner)
            else:
                for dst in rank_out:
                    delegate = p2p.send(y, comm, dst, src=owner)
                    inflight.setdefault((owner, dst), []).append(delegate)

        if out is None:
            raise ValueError(
                "no component has rank_out=None; the chain never produces "
                "an output"
            )
        y, owner = out
        # Broadcast the final output from its owner so every rank returns
        # the same value (loss available globally).
        return jax.tree.map(lambda v: comm.bcast(v, owner), y)

    def make_forward(self, batch_spec=P(), jit: bool = True):
        """Wrap :meth:`apply` in ``shard_map`` (params replicated, input per
        ``batch_spec``), optionally jitted — the "just call the model"
        surface the reference's ``__call__`` provided."""
        comm = self.comm

        def fwd(params_list, x):
            return self.apply(params_list, x)

        mapped = comm.shard_map(
            fwd, in_specs=(P(), batch_spec), out_specs=P()
        )
        return jax.jit(mapped) if jit else mapped
