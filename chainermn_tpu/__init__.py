"""chainermn_tpu — a TPU-native distributed training framework with the
capabilities of ChainerMN (reference: keisukefukuda/chainermn), built
idiomatically on jax/XLA rather than ported.

Facade mirroring REF:chainermn/__init__.py's re-exports: the communicator
factory, the data-parallel trio (multi-node optimizer / dataset scatter /
multi-node evaluator), and the model-parallel API (differentiable
point-to-point and collective functions, ``MultiNodeChainList``).
"""

from chainermn_tpu.communicators import (  # noqa: F401
    CommunicatorBase,
    create_communicator,
    build_mesh,
)

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy facade for the higher layers so `import chainermn_tpu` stays
    # cheap and cycle-free while the package grows.
    if name in (
        "create_multi_node_optimizer",
        "MultiNodeOptimizer",
    ):
        from chainermn_tpu import optimizers

        return getattr(optimizers, name)
    if name in ("scatter_dataset", "create_empty_dataset"):
        from chainermn_tpu import datasets

        return getattr(datasets, name)
    if name in ("create_multi_node_evaluator", "create_multi_node_checkpointer"):
        from chainermn_tpu import extensions

        return getattr(extensions, name)
    if name in ("MultiNodeChainList",):
        from chainermn_tpu import links

        return getattr(links, name)
    if name in ("analysis", "functions", "observability", "elastic"):
        import importlib

        return importlib.import_module(f"chainermn_tpu.{name}")
    if name in (
        "create_multi_node_iterator",
        "create_synchronized_iterator",
        "create_prefetch_iterator",
    ):
        from chainermn_tpu import iterators

        return getattr(iterators, name)
    if name in ("global_except_hook",):
        # importlib, NOT `from chainermn_tpu import ...`: the from-import
        # re-enters this __getattr__ before the submodule is bound and
        # recurses forever.
        import importlib

        return importlib.import_module("chainermn_tpu.global_except_hook")
    raise AttributeError(f"module 'chainermn_tpu' has no attribute {name!r}")
