"""Deterministic fault injection — the chaos harness.

A :class:`ChaosSchedule` is a declarative list of faults, written as a
single string so it travels through env vars and CLI flags unchanged::

    kill:rank=1:step=5;term:rank=0:step=8;hb_stall:rank=1:step=3:secs=30
    ckpt_corrupt:rank=0:gen=4;ckpt_torn:rank=1:gen=6;ckpt_slow:secs=0.05

Faults fire *inside the targeted rank* at that rank's own step counter
— not from the supervisor's clock — so a schedule is exactly
reproducible: ``kill:rank=1:step=5`` dies at the same optimizer state
every run.  Each fault carries the incarnation it belongs to
(default 0, the first launch), so a kill does not re-fire after the
supervisor respawns the world.

Kinds:

* ``kill`` — ``SIGKILL`` self at ``step`` (a hard crash: no cleanup,
  peers stall until the supervisor's heartbeat deadline).
* ``term`` — ``SIGTERM`` self at ``step`` (preemption: the elastic
  runtime's handler turns it into a coordinated grace-window
  checkpoint and a distinct exit code).
* ``hb_stall`` — suppress heartbeats for ``secs`` starting at ``step``
  (alive-but-silent: only the deadline can catch it).
* ``ckpt_corrupt`` — after generation ``gen`` commits, flip a payload
  byte in this rank's snapshot (crc32c must catch it on load).
* ``ckpt_torn`` — truncate the tail of generation ``gen``'s snapshot
  (a torn write: the header parses, the payload doesn't).
* ``ckpt_slow`` — sleep ``secs`` inside every checkpoint save (slow
  snapshot I/O widening the crash window).

The schedule drives both the test suite and ``bench.py --chaos``; the
supervisor passes it to ranks via ``CHAINERMN_TPU_CHAOS``.

Serving-tier coordinates: the same grammar also addresses *serving
replicas* on a *wall-clock* axis — ``kill:replica=1:at=0.25`` kills
replica 1 a quarter second into a traffic run.  ``replica=`` targets a
replica id instead of a training rank, and ``at=`` (seconds since the
harness armed) replaces ``step=`` where there is no shared step counter
— a cluster of free-running replica threads has no step, only time.
``kill``/``term`` accept either coordinate; :class:`TimedChaos` is the
serving-side executor that fires ``at=`` faults exactly once as their
deadline passes (the *caller* maps the fault onto an action —
``router.fail_replica`` for an in-process harness, a real ``SIGKILL``
for a multi-process one — so the grammar stays policy-free).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import sys
import time
from typing import List, Optional, Tuple

ENV_SCHEDULE = "CHAINERMN_TPU_CHAOS"

_KINDS = ("kill", "term", "hb_stall", "ckpt_corrupt", "ckpt_torn",
          "ckpt_slow")
# kill/term fire at a training step OR a wall-clock offset (one of the
# tuple suffices); every other kind keeps its fixed requirement.
_REQUIRED = {
    "kill": (("step", "at"),),
    "term": (("step", "at"),),
    "hb_stall": ("step", "secs"),
    "ckpt_corrupt": ("gen",),
    "ckpt_torn": ("gen",),
    "ckpt_slow": ("secs",),
}


@dataclasses.dataclass(frozen=True)
class Fault:
    kind: str
    rank: Optional[int] = None  # None targets every rank
    step: Optional[int] = None
    gen: Optional[int] = None
    secs: float = 0.0
    inc: int = 0  # incarnation the fault belongs to (-1: every one)
    replica: Optional[int] = None  # serving-replica target (vs. rank)
    at: Optional[float] = None  # seconds since harness start (vs. step)

    def targets(self, rank: int, incarnation: int) -> bool:
        if self.rank is not None and self.rank != rank:
            return False
        return self.inc == -1 or self.inc == incarnation

    def format(self) -> str:
        parts = [self.kind]
        if self.rank is not None:
            parts.append(f"rank={self.rank}")
        if self.replica is not None:
            parts.append(f"replica={self.replica}")
        if self.step is not None:
            parts.append(f"step={self.step}")
        if self.at is not None:
            parts.append(f"at={self.at:g}")
        if self.gen is not None:
            parts.append(f"gen={self.gen}")
        if self.secs:
            parts.append(f"secs={self.secs:g}")
        if self.inc != 0:
            parts.append(f"inc={self.inc}")
        return ":".join(parts)


@dataclasses.dataclass(frozen=True)
class ChaosSchedule:
    faults: Tuple[Fault, ...] = ()

    @classmethod
    def parse(cls, text: str) -> "ChaosSchedule":
        faults = []
        for item in (text or "").split(";"):
            item = item.strip()
            if not item:
                continue
            fields = item.split(":")
            kind = fields[0].strip()
            if kind not in _KINDS:
                raise ValueError(
                    f"chaos: unknown fault kind {kind!r} in {item!r} "
                    f"(known: {', '.join(_KINDS)})"
                )
            kw: dict = {}
            for kv in fields[1:]:
                if "=" not in kv:
                    raise ValueError(
                        f"chaos: expected key=value, got {kv!r} in {item!r}"
                    )
                k, v = kv.split("=", 1)
                k = k.strip()
                if k in ("rank", "step", "gen", "inc", "replica"):
                    kw[k] = int(v)
                elif k in ("secs", "at"):
                    kw[k] = float(v)
                else:
                    raise ValueError(
                        f"chaos: unknown key {k!r} in {item!r}"
                    )
            missing = [
                req for req in _REQUIRED[kind]
                if not any(
                    k in kw
                    for k in (req if isinstance(req, tuple) else (req,))
                )
            ]
            if missing:
                names = [
                    "|".join(m) if isinstance(m, tuple) else m
                    for m in missing
                ]
                raise ValueError(
                    f"chaos: fault {kind!r} requires "
                    f"{'/'.join(names)} in {item!r}"
                )
            faults.append(Fault(kind=kind, **kw))
        return cls(tuple(faults))

    def format(self) -> str:
        return ";".join(f.format() for f in self.faults)

    def for_rank(self, rank: int, incarnation: int) -> Tuple[Fault, ...]:
        return tuple(
            f for f in self.faults if f.targets(rank, incarnation)
        )

    def timed(self) -> Tuple[Fault, ...]:
        """Faults on the wall-clock axis (``at=``), in firing order —
        the subset a :class:`TimedChaos` executor arms."""
        return tuple(
            sorted(
                (f for f in self.faults if f.at is not None),
                key=lambda f: f.at,
            )
        )


class ChaosEngine:
    """Worker-side fault executor: armed with the faults that target
    this (rank, incarnation), it fires step faults from
    :meth:`on_step` and checkpoint faults from a wrapped
    ``MultiNodeCheckpointer.save``."""

    def __init__(self, schedule: ChaosSchedule, rank: int,
                 incarnation: int, heartbeat=None):
        self.rank = int(rank)
        self.incarnation = int(incarnation)
        self.heartbeat = heartbeat
        self._armed = list(schedule.for_rank(rank, incarnation))
        self._fired: set = set()

    def _due(self, kinds, step=None, gen=None):
        for f in self._armed:
            if f.kind not in kinds or id(f) in self._fired:
                continue
            if step is not None and (f.step is None or step < f.step):
                continue
            if gen is not None and (f.gen is None or gen < f.gen):
                continue
            self._fired.add(id(f))
            yield f

    # -- step faults ---------------------------------------------------
    def on_step(self, step: int) -> None:
        """Call once per training step, BEFORE the step executes: a
        ``step=s`` fault fires with exactly ``s`` steps completed."""
        for f in self._due(("hb_stall",), step=step):
            if self.heartbeat is not None:
                self.heartbeat.suppress(f.secs)
        for f in self._due(("term",), step=step):
            sys.stdout.flush()
            os.kill(os.getpid(), signal.SIGTERM)
        for f in self._due(("kill",), step=step):
            sys.stdout.write(
                f"chaos: SIGKILL rank {self.rank} at step {step}\n"
            )
            sys.stdout.flush()
            os.kill(os.getpid(), signal.SIGKILL)

    # -- checkpoint faults ---------------------------------------------
    def wrap_checkpointer(self, ckpt) -> None:
        """Wrap ``ckpt.save`` so ckpt_* faults fire at the declared
        generation.  Corruption happens AFTER the save commits (the
        two-phase rename completed, the marker is up): precisely the
        torn-payload-with-valid-marker state maybe_load's crc vote must
        catch."""
        if not any(f.kind.startswith("ckpt_") for f in self._armed):
            return
        orig = ckpt.save

        def save(state, iteration, block=True):
            for f in self._due(("ckpt_slow",), gen=None):
                self._fired.discard(id(f))  # every save, not once
                time.sleep(f.secs)
            hit = list(self._due(("ckpt_corrupt", "ckpt_torn"),
                                 gen=iteration))
            if hit:
                orig(state, iteration, block=True)
                ckpt.wait()
                snap = ckpt._snap(iteration, ckpt.comm.rank)
                for f in hit:
                    _damage(snap, torn=(f.kind == "ckpt_torn"))
                    sys.stdout.write(
                        f"chaos: {f.kind} rank {self.rank} "
                        f"gen {iteration}\n"
                    )
                    sys.stdout.flush()
                return
            return orig(state, iteration, block=block)

        ckpt.save = save


def _damage(path: str, torn: bool) -> None:
    size = os.path.getsize(path)
    if torn:
        with open(path, "r+b") as f:
            f.truncate(max(0, size - 7))
        return
    # Flip one payload byte (the last byte before the trailing u32
    # crc32c) so the payload checksum mismatches.
    off = max(0, size - 5)
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")


def engine_from_env(rank: int, incarnation: int,
                    heartbeat=None) -> Optional[ChaosEngine]:
    text = os.environ.get(ENV_SCHEDULE)
    if not text:
        return None
    return ChaosEngine(
        ChaosSchedule.parse(text), rank, incarnation, heartbeat=heartbeat
    )


class TimedChaos:
    """Serving-side executor for ``at=`` faults.

    Training chaos fires inside the victim at its own step counter;
    serving replicas free-run with no shared step, so the only
    reproducible coordinate is elapsed time since the harness armed.
    :meth:`due` returns each fault exactly once when its deadline
    passes — the caller maps it onto an action (``fail_replica`` for
    thread replicas, ``os.kill`` for process ones), keeping the grammar
    itself free of any cluster policy."""

    def __init__(self, schedule: ChaosSchedule,
                 clock=time.monotonic):
        self.clock = clock
        self._armed = list(schedule.timed())
        self._t0: Optional[float] = None

    def start(self, now: Optional[float] = None) -> None:
        self._t0 = self.clock() if now is None else now

    @property
    def pending(self) -> int:
        return len(self._armed)

    def due(self, now: Optional[float] = None) -> Tuple[Fault, ...]:
        """Newly-due faults (armed, deadline passed), oldest first.
        Arms the clock lazily on first call so bare ``due()`` polling
        works without an explicit :meth:`start`."""
        now = self.clock() if now is None else now
        if self._t0 is None:
            self._t0 = now
        elapsed = now - self._t0
        fired = tuple(f for f in self._armed if f.at <= elapsed)
        if fired:
            self._armed = [f for f in self._armed if f.at > elapsed]
        return fired


# Canonical corpus for grammar smoke checks (``tools.lint --self``):
# every accepted form round-trips parse→format→parse unchanged, and
# each rejected form must raise — so a grammar regression is caught by
# the same lint gate that guards source hygiene.
GRAMMAR_CORPUS_OK = (
    "kill:rank=1:step=5",
    "term:rank=0:step=8;hb_stall:rank=1:step=3:secs=30",
    "ckpt_corrupt:rank=0:gen=4;ckpt_torn:rank=1:gen=6;ckpt_slow:secs=0.05",
    "kill:replica=1:at=0.25",
    "kill:replica=2:at=1.5;term:replica=0:at=3",
    "kill:rank=1:step=5:inc=-1",
)
GRAMMAR_CORPUS_BAD = (
    "explode:rank=1:step=5",        # unknown kind
    "kill:rank=1",                  # kill needs step or at
    "kill:replica=1",               # ... regardless of target axis
    "hb_stall:rank=1:step=3",       # hb_stall needs secs
    "kill:rank=1:step",             # not key=value
    "kill:rank=1:when=5",           # unknown key
)


def validate_grammar() -> List[str]:
    """Self-check the schedule grammar against the canonical corpus.
    Returns a list of problems (empty when healthy)."""
    problems: List[str] = []
    for text in GRAMMAR_CORPUS_OK:
        try:
            sched = ChaosSchedule.parse(text)
            rt = ChaosSchedule.parse(sched.format())
            if rt != sched:
                problems.append(
                    f"chaos grammar: {text!r} does not round-trip "
                    f"(format() -> {sched.format()!r})"
                )
        except ValueError as e:
            problems.append(f"chaos grammar: {text!r} rejected: {e}")
    for text in GRAMMAR_CORPUS_BAD:
        try:
            ChaosSchedule.parse(text)
        except ValueError:
            continue
        problems.append(
            f"chaos grammar: invalid schedule {text!r} was accepted"
        )
    return problems
