"""Elastic training supervisor — owns the trainer processes end to end.

The supervisor spawns the N-rank ``jax.distributed`` world, monitors
liveness (process exit codes AND heartbeat-file deadlines through the
shared :class:`~chainermn_tpu.elastic.heartbeat.HeartbeatMonitor` — a
rank that is alive-but-wedged looks identical to a dead one), and when
a rank dies it tears the survivors down with *bounded* waits
(SIGTERM → backoff polls → SIGKILL; nothing in this module blocks
without a deadline), then rebuilds the world and lets training
auto-resume from the newest consistent checkpoint generation:

* **respawn-in-place** (default): the same world size on a fresh
  coordinator port;
* **rescale** (``rescale_on_failure``): shrink to the surviving host
  count — the relaunched ranks re-shard params/moments for the new
  mesh through the ``ShardingPlan`` registry (``plan.resolve`` on a
  different mesh), so N→M restart needs no conversion step.

SIGTERM-as-preemption is first-class: ranks that exit with
``EXIT_PREEMPTED`` (the elastic runtime's grace-window checkpoint path)
are counted separately from crashes and always respawned — the
spot-capacity story, where preemption is routine and crash budgets are
for bugs.

Everything the supervisor observes — spawns, deaths (with the crash
postmortem row the dying rank appended), teardowns, restarts,
preemptions, resume generations — is written to a step-event log
(``--step-log``) as ``elastic`` event rows plus ``counter`` rows that
``tools.obs summarize``/``prom`` surface as ``elastic/restarts``,
``elastic/preemptions``, ``elastic/resume_generation``.

This module deliberately imports neither jax nor the communicator
stack: it is pure process supervision, cheap enough to unit-test with
stdlib dummy workers.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from chainermn_tpu.elastic.heartbeat import HeartbeatMonitor, read_beat

#: Exit code the elastic runtime uses for a clean preemption exit
#: (EX_TEMPFAIL-adjacent: "try again", distinct from the crash
#: barrier's 13 and from signal deaths' negative codes).
EXIT_PREEMPTED = 75

_RESUME_RE = re.compile(r"resumed from iteration (\d+)")


@dataclasses.dataclass
class SupervisorConfig:
    """One elastic job.  ``argv`` is the rank command line, launched
    identically for every rank — rank identity travels via env
    (``CHAINERMN_TPU_ELASTIC_*``), never argv, so respawn and rescale
    need no argv surgery."""

    argv: List[str]
    nproc: int
    max_restarts: int = 2          # crash-restart budget (preemptions excluded)
    max_preemptions: int = 16      # backstop so a term-looping job terminates
    rescale_on_failure: bool = False
    min_nproc: int = 1
    heartbeat_timeout_s: float = 60.0
    start_grace_s: float = 120.0   # deadline for the FIRST beat (jax init, compile)
    poll_s: float = 0.1
    grace_s: float = 10.0          # teardown: SIGTERM → this long → SIGKILL
    backoff_s: float = 0.5         # respawn backoff base (doubles, capped 8s)
    chaos: Optional[str] = None
    workdir: Optional[str] = None  # heartbeat/postmortem files live here
    step_log: Optional[str] = None
    env: Optional[Dict[str, str]] = None
    echo: bool = True              # prefix-echo rank output to our stdout
    coordinator_host: str = "127.0.0.1"
    barrier_timeout_s: Optional[float] = 120.0  # exported to ranks
    init_timeout_s: float = 120.0
    #: serve a live Prometheus /metrics scrape endpoint on this port
    #: while the job runs (0 = ephemeral; None = off): the elastic/*
    #: counters plus per-event-kind counts, scrapeable mid-chaos.
    metrics_port: Optional[int] = None


class _Rank:
    """One spawned rank: the process, its heartbeat file, and a reader
    thread draining stdout (scanning for resume/digest markers while
    preventing pipe-full deadlock)."""

    def __init__(self, rank: int, proc: subprocess.Popen, hb_path: str,
                 echo: bool):
        self.rank = rank
        self.proc = proc
        self.hb_path = hb_path
        self.lines: List[str] = []
        self._echo = echo
        self.reader = threading.Thread(target=self._drain, daemon=True)
        self.reader.start()

    def _drain(self):
        try:
            for line in self.proc.stdout:
                self.lines.append(line)
                if self._echo:
                    sys.stdout.write(f"[r{self.rank}] {line}")
                    sys.stdout.flush()
        except Exception:
            pass

    def output(self) -> str:
        return "".join(self.lines)


class ElasticSupervisor:
    def __init__(self, config: SupervisorConfig):
        if config.nproc < 1:
            raise ValueError("nproc must be >= 1")
        self.config = config
        self.restarts = 0
        self.preemptions = 0
        self.incarnation = 0
        #: fabric control surface: a chip arbiter asks the job to
        #: change size via :meth:`yield_ranks`/:meth:`grant_ranks`.
        #: The resize rides the normal preemption path (SIGTERM →
        #: grace-window checkpoint → exit 75 → respawn), so resumes
        #: stay bit-exact; lease-driven rescales are counted separately
        #: and never burn the ``max_preemptions`` budget.
        self.world = config.nproc
        self.running = False
        self.lease_rescales = 0
        self.lease_tag = ""
        self._ctl_lock = threading.Lock()
        self._target_world: Optional[int] = None
        self._fabric_preempt = False
        self._live_ranks: List[_Rank] = []
        self.resume_generation: Optional[int] = None
        self.params_digest: Optional[str] = None
        self.events: List[dict] = []
        self._recorder = None
        self._reporter = None
        self._exporter = None
        #: scrape URL once the exporter is up (config.metrics_port).
        self.metrics_url: Optional[str] = None
        self._workdir = config.workdir or os.path.join(
            os.getcwd(), "elastic-supervisor"
        )
        os.makedirs(self._workdir, exist_ok=True)

    # -- observability -------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        row = {"kind": kind, "incarnation": self.incarnation, **fields}
        self.events.append(row)
        if self._recorder is not None:
            self._recorder.record("elastic", **row)
            for name, value in (
                ("elastic/restarts", self.restarts),
                ("elastic/preemptions", self.preemptions),
                ("elastic/resume_generation",
                 self.resume_generation or 0),
            ):
                self._recorder.record("counter", name=name, value=value)
        if self._reporter is not None:
            self._reporter.count(f"elastic/events/{kind}", 1)
            self._reporter.gauge("elastic/restarts", self.restarts)
            self._reporter.gauge("elastic/preemptions", self.preemptions)
            self._reporter.gauge("elastic/incarnation", self.incarnation)
            self._reporter.gauge("elastic/resume_generation",
                                 self.resume_generation or 0)

    # -- fabric control surface ----------------------------------------
    def set_lease_tag(self, tag: str) -> None:
        """Stamp subsequent incarnations with the fabric lease id (the
        ranks echo it into their heartbeat files)."""
        self.lease_tag = tag

    def request_world(self, new_world: int) -> bool:
        """Ask the running job to resize to ``new_world`` ranks.

        Returns immediately (False when the job is not running or the
        size is a no-op); the resize completes asynchronously: live
        ranks get SIGTERM, take the grace-window checkpoint, exit 75,
        and the run loop respawns at the new size, where ``maybe_load``
        re-shards through the ShardingPlan registry and resumes
        bit-exactly.  Watch :attr:`world` to observe completion.
        """
        new_world = max(int(new_world), self.config.min_nproc)
        with self._ctl_lock:
            if not self.running:
                return False
            if new_world == (self._target_world
                             if self._target_world is not None
                             else self.world):
                return False
            self._target_world = new_world
            self._fabric_preempt = True
            live = list(self._live_ranks)
        for rk in live:
            if rk.proc.poll() is None:
                try:
                    rk.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        return True

    def yield_ranks(self, k: int) -> bool:
        """Shrink the job by ``k`` ranks (fabric preempts chips for
        serving)."""
        return self.request_world(self.world - int(k))

    def grant_ranks(self, k: int) -> bool:
        """Grow the job by ``k`` ranks (fabric returns chips)."""
        return self.request_world(self.world + int(k))

    # -- process plumbing ----------------------------------------------
    def _free_port(self) -> int:
        with socket.socket() as s:
            s.bind((self.config.coordinator_host, 0))
            return s.getsockname()[1]

    def _spawn_world(self, world: int) -> List[_Rank]:
        cfg = self.config
        port = self._free_port()
        coord = f"{cfg.coordinator_host}:{port}"
        inc_dir = os.path.join(self._workdir, f"inc{self.incarnation}")
        os.makedirs(inc_dir, exist_ok=True)
        ranks = []
        for r in range(world):
            hb = os.path.join(inc_dir, f"hb.rank{r}")
            env = dict(os.environ)
            env.update(cfg.env or {})
            env.update({
                "CHAINERMN_TPU_ELASTIC": "1",
                "CHAINERMN_TPU_ELASTIC_RANK": str(r),
                "CHAINERMN_TPU_ELASTIC_NPROC": str(world),
                "CHAINERMN_TPU_ELASTIC_COORD": coord,
                "CHAINERMN_TPU_ELASTIC_HB_FILE": hb,
                "CHAINERMN_TPU_ELASTIC_INCARNATION":
                    str(self.incarnation),
                "CHAINERMN_TPU_ELASTIC_INIT_TIMEOUT_S":
                    str(cfg.init_timeout_s),
                "CHAINERMN_TPU_POSTMORTEM_FILE":
                    os.path.join(self._workdir, "postmortem.jsonl"),
                "CHAINERMN_TPU_ELASTIC_PLANE": "train",
                "CHAINERMN_TPU_ELASTIC_LEASE": self.lease_tag,
            })
            if cfg.chaos:
                env["CHAINERMN_TPU_CHAOS"] = cfg.chaos
            if cfg.barrier_timeout_s is not None:
                env["CHAINERMN_TPU_BARRIER_TIMEOUT_S"] = \
                    str(cfg.barrier_timeout_s)
            proc = subprocess.Popen(
                cfg.argv, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True, env=env,
            )
            ranks.append(_Rank(r, proc, hb, cfg.echo))
        with self._ctl_lock:
            self._live_ranks = ranks
            self.world = world
        self._event("spawn", world=world, coordinator=coord,
                    pids=[rk.proc.pid for rk in ranks])
        return ranks

    def _teardown(self, ranks: List[_Rank]) -> None:
        """Bounded: SIGTERM everyone alive, poll with backoff up to
        ``grace_s``, SIGKILL stragglers, then reap (a SIGKILLed process
        cannot refuse the reap, so the final joins are brief)."""
        cfg = self.config
        alive = [rk for rk in ranks if rk.proc.poll() is None]
        for rk in alive:
            try:
                rk.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + cfg.grace_s
        pause = cfg.poll_s
        while alive and time.monotonic() < deadline:
            alive = [rk for rk in alive if rk.proc.poll() is None]
            if alive:
                time.sleep(pause)
                pause = min(pause * 2, 1.0)
        killed = []
        for rk in alive:
            try:
                rk.proc.kill()
                killed.append(rk.rank)
            except OSError:
                pass
        for rk in ranks:
            try:
                rk.proc.wait(timeout=cfg.grace_s)
            except subprocess.TimeoutExpired:
                pass
            if rk.proc.stdout is not None:
                rk.reader.join(timeout=2.0)
                try:
                    rk.proc.stdout.close()
                except OSError:
                    pass
        self._event("teardown", sigkilled=killed)

    # -- postmortem ----------------------------------------------------
    def _postmortem_rows(self) -> List[dict]:
        path = os.path.join(self._workdir, "postmortem.jsonl")
        try:
            from chainermn_tpu.observability.step_log import read_records

            return [r for r in read_records(path)
                    if r.get("event") == "crash"]
        except Exception:
            return []

    # -- one incarnation -----------------------------------------------
    def _monitor(self, ranks: List[_Rank]) -> dict:
        """Run one incarnation to an outcome:
        ``{"outcome": "ok"|"preempted"|"crash", ...}``.  Every exit
        path through here is deadline-bounded."""
        cfg = self.config
        monitor = HeartbeatMonitor(
            [rk.rank for rk in ranks],
            miss_after_s=cfg.heartbeat_timeout_s, clock=time.time,
        )
        first_beat: Dict[int, bool] = {rk.rank: False for rk in ranks}
        start = time.time()
        while True:
            exited_bad = []
            running = []
            for rk in ranks:
                code = rk.proc.poll()
                if code is None:
                    running.append(rk)
                    mtime = read_beat(rk.hb_path)
                    if mtime is not None:
                        first_beat[rk.rank] = True
                        monitor.beat(rk.rank, now=mtime)
                    elif time.time() - start < cfg.start_grace_s:
                        # Pre-first-beat grace: jax init + compile can
                        # dwarf the steady-state deadline.
                        monitor.beat(rk.rank)
                elif code not in (0, EXIT_PREEMPTED):
                    exited_bad.append((rk.rank, code))
                    monitor.mark_dead(rk.rank)
            hb_dead = monitor.check()
            if exited_bad or hb_dead:
                # A rank that already left with EXIT_PREEMPTED makes this
                # a preemption, not a crash: the coordinated grace-window
                # checkpoint barrier completed on EVERY rank before any
                # rank exits, so peers killed by the coordinator's
                # departure (the jax.distributed leader dying tears down
                # its clients) are collateral, and resume is safe.
                preempted = any(
                    rk.proc.poll() == EXIT_PREEMPTED for rk in ranks
                )
                if not preempted:
                    # A fabric resize SIGTERMs every rank; one that dies
                    # to the signal before its grace handler is up exits
                    # -SIGTERM.  When a resize is pending and every exit
                    # is explained by it (clean, checkpointed, or killed
                    # by our own signal), the wave is the resize — it
                    # must ride the lease budget, not the crash budget.
                    with self._ctl_lock:
                        fabric_pending = self._fabric_preempt
                    preempted = fabric_pending and not hb_dead and all(
                        rk.proc.poll()
                        in (None, 0, EXIT_PREEMPTED, -signal.SIGTERM)
                        for rk in ranks
                    )
                self._event(
                    "failure", exited=exited_bad, heartbeat_dead=hb_dead,
                    preempted=preempted,
                    postmortem=self._postmortem_rows()[-3:],
                )
                self._teardown(ranks)
                self._scan_output(ranks)
                codes = {rk.rank: rk.proc.poll() for rk in ranks}
                if preempted:
                    return {"outcome": "preempted", "codes": codes,
                            "dead": set()}
                dead = {r for r, _ in exited_bad} | set(hb_dead)
                return {"outcome": "crash", "codes": codes, "dead": dead}
            if not running:
                codes = {rk.rank: rk.proc.poll() for rk in ranks}
                self._scan_output(ranks)
                if any(c == EXIT_PREEMPTED for c in codes.values()):
                    return {"outcome": "preempted", "codes": codes,
                            "dead": set()}
                return {"outcome": "ok", "codes": codes, "dead": set()}
            time.sleep(cfg.poll_s)

    def _scan_output(self, ranks: List[_Rank]) -> None:
        for rk in ranks:
            rk.reader.join(timeout=2.0)
            out = rk.output()
            for m in _RESUME_RE.finditer(out):
                self.resume_generation = int(m.group(1))
            m = re.search(r"params_digest ([0-9a-f]{8})", out)
            if m:
                self.params_digest = m.group(1)

    # -- the job -------------------------------------------------------
    def run(self) -> dict:
        cfg = self.config
        world = cfg.nproc
        status = "failed"
        last_codes: dict = {}
        recorder_cm = None
        if cfg.step_log:
            from chainermn_tpu.observability.step_log import StepRecorder

            # No compile listener / device-memory sampling: the
            # supervisor must not drag jax into its own process.
            recorder_cm = StepRecorder(
                cfg.step_log, capture_compile_events=False, mem_every=0,
            )
            self._recorder = recorder_cm
        if cfg.metrics_port is not None:
            from chainermn_tpu.observability import (
                MetricsExporter,
                Reporter,
            )

            self._reporter = Reporter()
            self._exporter = MetricsExporter(
                self._reporter, port=cfg.metrics_port
            )
            self._exporter.start()
            self.metrics_url = self._exporter.url
        self.running = True
        try:
            while True:
                # Consume a pending fabric resize before (re)spawning:
                # request_world may have landed during the previous
                # incarnation's teardown or the backoff window.
                with self._ctl_lock:
                    target = self._target_world
                    self._target_world = None
                if target is not None and target != world:
                    self._event("lease_rescale", from_world=world,
                                to_world=target)
                    world = target
                ranks = self._spawn_world(world)
                result = self._monitor(ranks)
                last_codes = {
                    str(k): v for k, v in result["codes"].items()
                }
                if result["outcome"] == "ok":
                    status = "ok"
                    self._event("success", world=world, codes=last_codes)
                    break
                if result["outcome"] == "preempted":
                    with self._ctl_lock:
                        fabric = self._fabric_preempt
                        self._fabric_preempt = False
                    if fabric:
                        # Arbiter-initiated resize: same checkpoint
                        # exit, but routine by design — it must never
                        # exhaust the preemption budget.
                        self.lease_rescales += 1
                        self._event("lease_preempt", codes=last_codes)
                    else:
                        self.preemptions += 1
                        self._event("preempted", codes=last_codes)
                        if self.preemptions > cfg.max_preemptions:
                            self._event("give_up",
                                        reason="max_preemptions")
                            break
                else:
                    self.restarts += 1
                    if self.restarts > cfg.max_restarts:
                        self._event("give_up", reason="max_restarts",
                                    codes=last_codes)
                        break
                    if cfg.rescale_on_failure:
                        survivors = world - len(result["dead"])
                        new_world = max(cfg.min_nproc, survivors)
                        if new_world != world:
                            self._event("rescale", from_world=world,
                                        to_world=new_world)
                            world = new_world
                self.incarnation += 1
                # Respawn backoff: exponential in the restart count so a
                # crash-looping job cannot spin the host.
                time.sleep(min(
                    cfg.backoff_s * (2 ** max(0, self.restarts - 1)), 8.0
                ))
        finally:
            self.running = False
            report = {
                "status": status,
                "nproc": cfg.nproc,
                "world": world,
                "incarnations": self.incarnation + 1,
                "restarts": self.restarts,
                "preemptions": self.preemptions,
                "lease_rescales": self.lease_rescales,
                "resume_generation": self.resume_generation,
                "params_digest": self.params_digest,
                "exit_codes": last_codes,
            }
            self._event("report", **report)
            if recorder_cm is not None:
                recorder_cm.close()
                self._recorder = None
            if self._exporter is not None:
                self._exporter.stop()
                self._exporter = None
        return report


def run_supervised(config: SupervisorConfig) -> dict:
    """One-call form: build, run, return the report dict."""
    return ElasticSupervisor(config).run()


def main_report_line(report: dict) -> str:
    """The stable one-line JSON the CLI prints and tests parse."""
    return "ELASTIC_REPORT " + json.dumps(report, sort_keys=True)
