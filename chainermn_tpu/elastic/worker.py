"""Worker-side elastic runtime — the rank half of the supervisor
contract.

A supervised rank receives its identity through env
(``CHAINERMN_TPU_ELASTIC_*``); :func:`init_from_env` reads it, joins
the ``jax.distributed`` world with bounded retries + backoff (a
half-started coordinator must surface as an error, never a hang),
installs the crash barrier (whose postmortem row the supervisor reads)
and a SIGTERM handler that records preemption instead of dying
mid-collective, and arms the chaos engine when a fault schedule is
present.

Training loops drive three methods:

* :meth:`ElasticContext.beat` once per step — fires due chaos faults,
  then touches the heartbeat file the supervisor watches;
* :meth:`ElasticContext.check_preemption` — a host-plane allreduce of
  the SIGTERM flag, so ONE preempted rank moves ALL ranks into the
  grace-window checkpoint together (a lone rank cannot checkpoint: the
  save barrier needs everyone);
* :meth:`ElasticContext.exit_preempted` — flush and exit with
  ``EXIT_PREEMPTED`` so the supervisor counts a preemption, not a
  crash.

:meth:`ElasticContext.reshard` is the rescale half: resolve a named
``ShardingPlan`` against the *current* mesh and re-place restored
params/moments through it — N→M restart is ``plan.resolve`` on a
different mesh, no conversion tooling.
"""

from __future__ import annotations

import os
import signal
import sys
import time
from typing import Optional

from chainermn_tpu.elastic import chaos as chaos_mod
from chainermn_tpu.elastic.heartbeat import FileBeat
from chainermn_tpu.elastic.supervisor import EXIT_PREEMPTED

ENV_ACTIVE = "CHAINERMN_TPU_ELASTIC"


def active() -> bool:
    """True when this process runs under the elastic supervisor."""
    return os.environ.get(ENV_ACTIVE) == "1"


class ElasticContext:
    def __init__(self, rank: int, nproc: int, coordinator: str,
                 incarnation: int, heartbeat: Optional[FileBeat],
                 chaos_engine):
        self.rank = rank
        self.nproc = nproc
        self.coordinator = coordinator
        self.incarnation = incarnation
        self.heartbeat = heartbeat
        self.chaos = chaos_engine
        self._preempted = False

    # -- per-step ------------------------------------------------------
    def beat(self, step: int) -> None:
        from chainermn_tpu import global_except_hook

        global_except_hook.set_current_step(step)
        if self.chaos is not None:
            self.chaos.on_step(step)
        if self.heartbeat is not None:
            self.heartbeat.beat(step)

    @property
    def preempted(self) -> bool:
        return self._preempted

    def check_preemption(self, comm) -> bool:
        """Did ANY rank receive SIGTERM?  Collective: every rank must
        call it at the same step so the grace-window checkpoint is
        coordinated."""
        if comm is None or comm.size <= 1:
            return self._preempted
        return bool(comm.allreduce_obj(int(self._preempted)))

    def exit_preempted(self) -> "None":
        """Exit with the preemption code.  ``os._exit`` on purpose: all
        ranks leave together right after a blocking checkpoint save, and
        no atexit teardown (distributed shutdown barriers included) may
        outlive the supervisor's grace window."""
        sys.stdout.flush()
        sys.stderr.flush()
        if self.rank == 0 and self.nproc > 1:
            # The coordination service lives in rank 0: leaving first
            # hard-kills every peer's distributed client mid-exit.  Give
            # them a head start; the supervisor treats any stragglers'
            # deaths as preemption collateral regardless.
            time.sleep(1.0)
        os._exit(EXIT_PREEMPTED)

    # -- checkpoint integration ---------------------------------------
    def attach_checkpointer(self, ckpt) -> None:
        """Arm checkpoint-path chaos faults (corrupt/torn/slow) on this
        rank's checkpointer.  No-op without a schedule."""
        if self.chaos is not None:
            self.chaos.wrap_checkpointer(ckpt)

    # -- rescale -------------------------------------------------------
    def reshard(self, params, opt_state, comm, plan: str = "dp",
                place: bool = True):
        """Re-place restored state for the CURRENT mesh through a named
        sharding plan.  Returns ``(params, opt_state, validation)`` —
        the :class:`~chainermn_tpu.sharding.PlanValidation` is the
        machine-checkable proof the resharded layout is legal on this
        mesh (every leaf matched, no axis conflicts).

        ``place=False`` validates the plan against the new mesh without
        committing device placement — for host-plane training loops (or
        backends without cross-process device collectives) that still
        want the N→M layout proof."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from chainermn_tpu.sharding import get_plan, validate

        p = get_plan(plan)
        report = validate(p, params, mesh=comm.mesh)
        if not report.ok:
            raise ValueError(
                "elastic reshard: plan does not cover the restored "
                "state on the new mesh:\n" + report.render()
            )
        if not place:
            return params, opt_state, report

        def place_tree(tree, specs):
            shardings = jax.tree.map(
                lambda s: NamedSharding(comm.mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P),
            )

            def put(x, sh):
                import numpy as np

                arr = np.asarray(x)
                return jax.make_array_from_callback(
                    arr.shape, sh, lambda idx: arr[idx]
                )

            return jax.tree.map(put, tree, shardings)

        params = place_tree(params, p.resolve(params))
        if opt_state is not None:
            opt_state = place_tree(opt_state, p.resolve_moments(opt_state))
        return params, opt_state, report


def init_from_env(install_hooks: bool = True) -> Optional[ElasticContext]:
    """Join the supervised world, or return None when not supervised
    (so ``--elastic`` examples degrade to plain runs).

    Must run BEFORE the jax backend initializes (i.e. before
    ``create_communicator`` / any ``jax.devices()`` call)."""
    if not active():
        return None
    rank = int(os.environ["CHAINERMN_TPU_ELASTIC_RANK"])
    nproc = int(os.environ["CHAINERMN_TPU_ELASTIC_NPROC"])
    coord = os.environ["CHAINERMN_TPU_ELASTIC_COORD"]
    incarnation = int(
        os.environ.get("CHAINERMN_TPU_ELASTIC_INCARNATION", "0")
    )
    init_timeout = float(
        os.environ.get("CHAINERMN_TPU_ELASTIC_INIT_TIMEOUT_S", "120")
    )

    early_term = {"fired": False}
    if install_hooks:
        # A fabric resize can SIGTERM this rank between exec and the
        # real handler below (jax.distributed clobbers SIGTERM during
        # init, so the real handler can only go in afterwards).  Record
        # instead of dying so the early window doesn't turn a lease
        # rescale into a -SIGTERM crash.
        signal.signal(
            signal.SIGTERM,
            lambda signum, frame: early_term.__setitem__("fired", True),
        )

    hb = None
    hb_path = os.environ.get("CHAINERMN_TPU_ELASTIC_HB_FILE")
    if hb_path:
        # Fabric identity (which plane/lease this chip serves) rides
        # the beat payload; absent env vars keep the legacy format.
        hb = FileBeat(
            hb_path,
            plane=os.environ.get("CHAINERMN_TPU_ELASTIC_PLANE", ""),
            lease_id=os.environ.get("CHAINERMN_TPU_ELASTIC_LEASE", ""),
            world=nproc,
        )
    engine = chaos_mod.engine_from_env(rank, incarnation, heartbeat=hb)
    ctx = ElasticContext(rank, nproc, coord, incarnation, hb, engine)

    if install_hooks:
        from chainermn_tpu import global_except_hook

        global_except_hook.add_hook()

    if nproc > 1:
        _distributed_init(coord, nproc, rank, init_timeout)

    if install_hooks:
        def on_term(signum, frame):
            # Record only: the training loop propagates the flag through
            # check_preemption and does the coordinated checkpoint at a
            # step boundary — never from inside a signal handler.
            ctx._preempted = True

        # AFTER distributed init: jax.distributed installs its own
        # SIGTERM handler there, which would otherwise clobber ours and
        # turn every preemption into an uncoordinated shutdown.
        signal.signal(signal.SIGTERM, on_term)
        if early_term["fired"]:
            ctx._preempted = True
    if hb is not None:
        hb.beat(-1)  # prove liveness before the first training step
    return ctx


def _distributed_init(coord: str, nproc: int, rank: int,
                      timeout_s: float) -> None:
    """``jax.distributed.initialize`` with bounded retries + backoff —
    a respawned incarnation can race the previous coordinator's port
    release, and that must cost a retry, not a hang."""
    import jax

    kwargs = {}
    try:
        import inspect

        if "initialization_timeout" in inspect.signature(
            jax.distributed.initialize
        ).parameters:
            kwargs["initialization_timeout"] = max(10, int(timeout_s))
    except (TypeError, ValueError):
        pass
    delay, attempts = 0.2, 3
    for attempt in range(attempts + 1):
        try:
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=nproc,
                process_id=rank, **kwargs,
            )
            break
        except Exception:
            if attempt >= attempts:
                raise
            time.sleep(delay)
            delay = min(delay * 2, 5.0)
    jax.devices()  # materialize the world before any collective
