"""Shared heartbeat/deadline liveness machinery.

This is the liveness core both tiers import: the serving cluster's
replica failover (``serving/cluster/health.py`` re-exports
:class:`HeartbeatMonitor` from here) and the elastic training
supervisor (:mod:`chainermn_tpu.elastic.supervisor`).  Anything that
proves a peer executed recently counts as a beat — serving replicas
beat on every scheduler step or event batch; training ranks beat once
per training step through a :class:`FileBeat`, whose file mtime the
supervisor polls from outside the process boundary.

The monitor itself is transport-agnostic: callers feed ``beat()`` /
``mark_dead()`` and poll ``check()`` for *newly* dead peers (exactly
once per death — both the router's failover trigger and the
supervisor's restart path must not re-fire).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Dict, Iterable, List, Optional


@dataclasses.dataclass(frozen=True)
class BeatInfo:
    """Decoded contents of a beat file.

    ``mtime`` IS the liveness signal (what :func:`read_beat` returns);
    the payload fields are diagnostics.  The fabric fields (``plane``,
    ``lease_id``, ``world``) are trailing-defaulted so pre-fabric beat
    files — a bare step number, possibly empty — keep decoding: wire
    compatibility across the supervisor/rank version boundary."""

    mtime: float
    step: int = -1
    plane: str = ""
    lease_id: str = ""
    world: int = 0


class HeartbeatMonitor:
    """Deadline-based liveness over caller-supplied beats.

    ``miss_after_s`` without a beat marks a peer dead; :meth:`check`
    reports NEWLY dead peers exactly once (failover/restart triggers
    must not re-fire).  A beat from a dead peer revives it
    (replacement incarnation)."""

    def __init__(self, replica_ids: Iterable, miss_after_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        self.miss_after_s = float(miss_after_s)
        self.clock = clock
        now = clock()
        self._last: Dict[object, float] = {r: now for r in replica_ids}
        self._dead: set = set()

    def beat(self, replica_id, now: Optional[float] = None) -> None:
        self._last[replica_id] = self.clock() if now is None else now
        self._dead.discard(replica_id)

    def mark_dead(self, replica_id) -> None:
        """Out-of-band death report (e.g. a ``PeerGone`` from the
        transport, or a supervisor's ``proc.poll()``) — faster than
        waiting out the heartbeat deadline."""
        self._dead.add(replica_id)

    def forget(self, replica_id) -> None:
        """Stop tracking a peer that left *on purpose* (a drained and
        retired replica) — without this, its silence would read as a
        death and re-fire the failover path."""
        self._last.pop(replica_id, None)
        self._dead.discard(replica_id)

    def alive(self, replica_id) -> bool:
        return replica_id in self._last and replica_id not in self._dead

    def check(self, now: Optional[float] = None) -> List:
        """Returns replicas that died SINCE the last check."""
        now = self.clock() if now is None else now
        newly = [
            r for r, t in self._last.items()
            if r not in self._dead and now - t > self.miss_after_s
        ]
        self._dead.update(newly)
        return newly


class FileBeat:
    """Training-rank beat writer: one tiny file whose *mtime* is the
    beat signal, readable across the process boundary without any
    shared transport (the supervisor may not share a KV store or socket
    with the ranks it owns — a half-dead rank can't fake beats it isn't
    writing).

    The write is a whole-file rewrite of the current step (handy in
    postmortems); chaos's delayed-heartbeat fault suppresses beats via
    :meth:`suppress` without touching the training loop."""

    def __init__(self, path: str, clock: Callable[[], float] = time.time,
                 plane: str = "", lease_id: str = "", world: int = 0):
        self.path = str(path)
        self._clock = clock
        self._suppress_until = 0.0
        #: fabric identity stamped into each beat (who holds this
        #: chip); empty means pre-fabric legacy format.
        self.plane = str(plane)
        self.lease_id = str(lease_id)
        self.world = int(world)
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)

    def suppress(self, secs: float) -> None:
        """Drop beats for ``secs`` (the chaos ``hb_stall`` fault — the
        process is alive but looks dead to the deadline)."""
        self._suppress_until = self._clock() + float(secs)

    def beat(self, step: Optional[int] = None) -> None:
        if self._clock() < self._suppress_until:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            if not (self.plane or self.lease_id or self.world):
                # Legacy format: bare step number (or empty).  Readers
                # of old supervisors only ever stat the mtime.
                f.write("" if step is None else str(int(step)))
            else:
                f.write(json.dumps({
                    "step": -1 if step is None else int(step),
                    "plane": self.plane,
                    "lease": self.lease_id,
                    "world": self.world,
                }, sort_keys=True))
        os.replace(tmp, self.path)  # atomic: readers never see a torn file


def read_beat(path: str) -> Optional[float]:
    """The beat file's mtime (wall clock), or None before the first
    beat.  Feed into a ``HeartbeatMonitor(clock=time.time)`` as
    ``monitor.beat(rank, now=mtime)``."""
    try:
        return os.stat(path).st_mtime
    except OSError:
        return None


def read_beat_info(path: str) -> Optional[BeatInfo]:
    """Decode a beat file into a :class:`BeatInfo` — parses both the
    legacy bare-step format and the fabric JSON payload, so a new
    supervisor reads old ranks' beats and vice versa."""
    mtime = read_beat(path)
    if mtime is None:
        return None
    try:
        with open(path) as f:
            raw = f.read().strip()
    except OSError:
        return None
    if not raw:
        return BeatInfo(mtime=mtime)
    if raw.startswith("{"):
        try:
            d = json.loads(raw)
        except ValueError:
            return BeatInfo(mtime=mtime)
        return BeatInfo(
            mtime=mtime,
            step=int(d.get("step", -1)),
            plane=str(d.get("plane", "")),
            lease_id=str(d.get("lease", "")),
            world=int(d.get("world", 0)),
        )
    try:
        return BeatInfo(mtime=mtime, step=int(raw))
    except ValueError:
        return BeatInfo(mtime=mtime)
