"""Elastic training: supervisor, fault-injection (chaos) harness, and
the worker-side runtime (see docs/fault_tolerance.md).

Launch::

    python -m chainermn_tpu.tools.elastic --nproc 2 -- \\
        python examples/mnist/train_mnist.py --communicator naive \\
        --elastic --checkpoint-dir ckpt

Training scripts opt in with :func:`init_from_env` (a no-op outside a
supervised run) and one :meth:`ElasticContext.beat` per step.
"""

from chainermn_tpu.elastic.chaos import (  # noqa: F401
    ChaosEngine,
    ChaosSchedule,
    Fault,
)
from chainermn_tpu.elastic.heartbeat import (  # noqa: F401
    BeatInfo,
    FileBeat,
    HeartbeatMonitor,
    read_beat,
    read_beat_info,
)
from chainermn_tpu.elastic.supervisor import (  # noqa: F401
    EXIT_PREEMPTED,
    ElasticSupervisor,
    SupervisorConfig,
    run_supervised,
)
from chainermn_tpu.elastic.worker import (  # noqa: F401
    ElasticContext,
    active,
    init_from_env,
)

__all__ = [
    "ChaosEngine",
    "ChaosSchedule",
    "Fault",
    "BeatInfo",
    "FileBeat",
    "HeartbeatMonitor",
    "read_beat",
    "read_beat_info",
    "EXIT_PREEMPTED",
    "ElasticSupervisor",
    "SupervisorConfig",
    "run_supervised",
    "ElasticContext",
    "active",
    "init_from_env",
]
