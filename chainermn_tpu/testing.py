"""Test doubles — the reference's ``chainermn/testing`` stub communicator.

``DummyCommunicator`` pins host-plane topology (``rank``/``size``) and runs
the object plane locally, so wrapper logic (dataset chunking arithmetic,
evaluator dict averaging, iterator lockstep) is unit-testable without any
mesh — exactly the reference's dummy-communicator trick (SURVEY §4
"unit vs integration").  Device-plane collectives raise: anything touching
transport belongs in a shard_map integration test on the virtual CPU mesh.
"""

from __future__ import annotations

from typing import Any, List, Optional


class DummyCommunicator:
    def __init__(self, rank: int = 0, size: int = 1, peers: Optional[List["DummyCommunicator"]] = None):
        self.rank = rank
        self.size = size
        self._peers = peers  # optional shared mailbox group
        self._mailbox: dict[str, Any] = {}

    # ---- host/object plane (local semantics) --------------------------
    def bcast_obj(self, obj, root: int = 0):
        if self._peers is not None:
            group = self._peers
            if self.rank == root:
                for p in group:
                    p._mailbox["bcast"] = obj
            return group[root]._mailbox.get("bcast", obj)
        return obj

    def gather_obj(self, obj, root: "int | None" = None,
                   timeout_ms: "int | None" = None):
        # Mirror the real contract exactly: root=None → allgather (full
        # list everywhere); root=r → list at root, None elsewhere — a
        # double that hid the None would green-light wrappers that crash
        # on a real communicator.  timeout_ms is accepted (and, like the
        # real contract, rejected without root) but nothing here blocks.
        if timeout_ms is not None and root is None:
            raise ValueError(
                "gather_obj: timeout_ms is only supported with root=..."
            )
        full = [obj] * self.size if self.size > 1 else [obj]
        if root is None:
            return full
        return full if self.rank == root else None

    def allgather_obj(self, obj):
        return self.gather_obj(obj)

    def allreduce_obj(self, obj, op=None):
        result = obj
        for _ in range(self.size - 1):
            result = op(result, obj) if op is not None else result + obj
        return result

    def scatter_obj(self, objs, root: int = 0):
        return objs[self.rank]

    def barrier(self):
        pass

    # ---- device plane: explicitly unsupported -------------------------
    def __getattr__(self, name):
        if name in (
            "allreduce", "bcast", "allgather", "alltoall", "reduce_scatter",
            "scatter", "ppermute", "allreduce_grad", "broadcast_data",
            "shard_map", "axis_index",
        ):
            raise NotImplementedError(
                f"DummyCommunicator has no device plane ({name}); use a real "
                "communicator on the virtual CPU mesh for transport tests"
            )
        raise AttributeError(name)


def dummy_communicators(size: int) -> List[DummyCommunicator]:
    """A group of dummies sharing a bcast mailbox (one per simulated rank)."""
    group: List[DummyCommunicator] = []
    for r in range(size):
        group.append(DummyCommunicator(rank=r, size=size, peers=group))
    return group
