"""Communicator factory.

Reference: ``create_communicator`` in
REF:chainermn/communicators/__init__.py — a string → class dispatch that is
the single user entry point for distributed setup, defaulting ``mpi_comm``
to ``MPI.COMM_WORLD``.  Here the "world" default is the full device mesh
built from ``jax.devices()`` (``mesh_utils.build_mesh``).

Name map (reference → this package):

=================  ==========================================================
``naive``          per-parameter psum, CPU-friendly correctness oracle
``flat``           single fused psum over one packed buffer (alias)
``pure_nccl``      alias of ``xla_ici`` — the fastest flat backend
``xla_ici``        the TPU-native headline backend (BASELINE.json)
``hierarchical``   psum over ``intra`` (ICI) then ``inter`` (DCN)
``two_dimensional``  reduce-scatter/allreduce/all-gather over ICI×DCN
``single_host``    ICI-only; asserts one host (ref: ``single_node``)
``non_cuda_aware``  alias of ``hierarchical`` — the reference's host-staged
                   fallback has no TPU meaning (XLA owns staging), but the
                   name resolves for API parity
=================  ==========================================================
"""

from __future__ import annotations

from typing import Any

from jax.sharding import Mesh

from .base import CommunicatorBase
from .hierarchical import HierarchicalCommunicator
from .naive import NaiveCommunicator
from .single_host import SingleHostCommunicator, SingleNodeCommunicator
from .two_dimensional import TwoDimensionalCommunicator
from .xla_ici import FlatCommunicator, XlaIciCommunicator
from . import mesh_utils, overlap, packing, quant
from .mesh_utils import build_mesh
from .overlap import OverlapSchedule, build_overlap_schedule
from .packing import DEFAULT_BUCKET_BYTES, GradPacker, pack_tree

_COMMUNICATORS: dict[str, type[CommunicatorBase]] = {
    "naive": NaiveCommunicator,
    "flat": FlatCommunicator,
    "xla_ici": XlaIciCommunicator,
    "pure_nccl": XlaIciCommunicator,
    "hierarchical": HierarchicalCommunicator,
    "non_cuda_aware": HierarchicalCommunicator,
    "two_dimensional": TwoDimensionalCommunicator,
    "single_host": SingleHostCommunicator,
    "single_node": SingleNodeCommunicator,
}


def create_communicator(
    communicator_name: str = "xla_ici",
    mesh: Mesh | None = None,
    allreduce_grad_dtype: Any | None = None,
    inter_size: int | None = None,
    intra_size: int | None = None,
    bucket_bytes: int | None = None,
    scatter_inter: bool = False,
    overlap: bool | None = None,
    overlap_granularity: int | None = None,
    comm_dtype: Any | None = None,
) -> CommunicatorBase:
    """Create a communicator by name (reference signature:
    ``create_communicator(communicator_name='hierarchical', mpi_comm=None,
    allreduce_grad_dtype=None)``).

    ``mesh`` defaults to the full-slice ``(inter, intra)`` mesh;
    ``inter_size``/``intra_size`` force a factorization (testing analogue of
    running ``mpiexec -n 2`` on one box, SURVEY §4).

    ``bucket_bytes`` caps the fused gradient-allreduce buckets (see
    :mod:`chainermn_tpu.communicators.packing` and docs/performance.md):
    ``None`` resolves env override → tuned value → 4 MiB default, ``0``
    disables bucketing (legacy per-leaf lowering), ``>0`` is an explicit
    cap.  ``scatter_inter`` (hierarchical only) decomposes its intra leg
    into reduce-scatter/all-gather so the inter (DCN) hop moves
    ``1/intra_size`` of the bytes.

    ``overlap`` controls the backward-overlapped bucket emission
    (:mod:`chainermn_tpu.communicators.overlap`): ``None`` resolves the
    ``CHAINERMN_TPU_OVERLAP`` env gate (default ON), ``False`` pins the
    eager pack-all-then-reduce-all schedule (the ``--no-overlap`` A/B in
    bench.py).  ``overlap_granularity`` sets buckets emitted per
    schedule stage (``None`` = env → tuned → 1).

    ``comm_dtype`` puts gradient buckets on a low-precision wire
    (:mod:`chainermn_tpu.communicators.quant`): ``"int8"`` or ``"fp8"``
    (e4m3 where the backend supports it, int8 fallback otherwise) scale
    each packed bucket by its global amax, run the sum collective on
    the narrow dtype, and dequantize in f32.  ``None`` resolves the
    ``CHAINERMN_TPU_COMM_DTYPE`` env → tuned value → off; ``"none"``
    pins it off.  Error vs the fp32 allreduce is bounded per dtype
    (docs/performance.md).
    """
    try:
        cls = _COMMUNICATORS[communicator_name]
    except KeyError:
        raise ValueError(
            f"unknown communicator {communicator_name!r}; "
            f"choose from {sorted(_COMMUNICATORS)}"
        ) from None
    if mesh is None:
        mesh = build_mesh(inter_size=inter_size, intra_size=intra_size)
    kwargs: dict = dict(
        allreduce_grad_dtype=allreduce_grad_dtype, bucket_bytes=bucket_bytes,
        overlap=overlap, overlap_granularity=overlap_granularity,
        comm_dtype=comm_dtype,
    )
    if scatter_inter:
        if not issubclass(cls, HierarchicalCommunicator):
            raise ValueError(
                "scatter_inter is only meaningful for the hierarchical "
                f"communicator, not {communicator_name!r}"
            )
        kwargs["scatter_inter"] = True
    return cls(mesh, **kwargs)


__all__ = [
    "CommunicatorBase",
    "NaiveCommunicator",
    "FlatCommunicator",
    "XlaIciCommunicator",
    "HierarchicalCommunicator",
    "TwoDimensionalCommunicator",
    "SingleHostCommunicator",
    "SingleNodeCommunicator",
    "create_communicator",
    "build_mesh",
    "mesh_utils",
    "overlap",
    "packing",
    "quant",
    "GradPacker",
    "OverlapSchedule",
    "build_overlap_schedule",
    "pack_tree",
    "DEFAULT_BUCKET_BYTES",
]
