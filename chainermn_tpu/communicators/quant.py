"""Low-precision scaling core — shared by gradient comms and the KV cache.

One quantization discipline, two consumers:

* **Training comms** (:meth:`CommunicatorBase.allreduce_grad` with
  ``comm_dtype=``): each packed gradient bucket is scaled by its global
  amax and cast to a narrow wire dtype (int8, or fp8-e4m3 where the
  backend supports it) before the sum collective, then cast back and
  unscaled after.  The blessed emission pattern is

      amax = pmax(max(|bucket|))          # one tiny f32 collective
      s    = amax / per_rank_qmax         # world headroom: the SUM fits
      q    = clip(round(bucket / s))      # narrow wire dtype
      out  = psum(q) * s / world          # sum collective + dequant mean

  ``per_rank_qmax`` is ``floor(qmax / world)`` for int8 (an INTEGER
  budget, so ``round(x/s) <= per_rank_qmax`` exactly — a fractional
  budget like ``127/8 = 15.875`` would round up to 16 and the summed
  wire value would wrap int8), and ``qmax / world`` with a 2**-3
  rounding-headroom divisor for fp8 (which saturates rather than wraps,
  but the headroom keeps the sum representable).  The collective needs
  no widening accumulator, and division by the world happens in f32 at
  dequant time, never in integer arithmetic.

* **Serving KV** (``kv_dtype="int8"`` on the engine): K/V pages are
  stored int8 with one f32 scale per written token per KV head (amax
  over ``d_head``), carried in page-shaped scale buffers that ride the
  same block table — so copy-on-write splits, defragmentation and
  migration snapshots move scales with their pages for free.

Error bounds (documented in docs/performance.md, enforced by
tests/test_quant.py): with ``A = pmax(amax)`` per bucket and ``n`` the
world size, the per-element error of the quantized *mean* vs the fp32
mean is at most

* int8: ``A / (2 * floor(127 / n))`` — each rank rounds to a grid of
  step ``s = A / floor(127/n)``, contributing ``s/2`` worst case; the
  mean divides the summed error back by ``n``.
* fp8 (e4m3): ``A * (n + 1) / 16`` — half-ulp relative error ``2**-4``
  per quantized element plus the fp8 summation's own rounding.  Loose by
  construction (fp8 is a *relative*-error format); observed error is far
  smaller on gradient-shaped data.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

#: Environment override for an unset constructor ``comm_dtype``.
#: Values: ``int8`` | ``fp8`` | ``none`` (explicit off).
ENV_COMM_DTYPE = "CHAINERMN_TPU_COMM_DTYPE"

#: Environment override for an unset engine ``kv_dtype``.
ENV_KV_DTYPE = "CHAINERMN_TPU_KV_DTYPE"

#: Canonical comm wire-dtype names accepted by ``comm_dtype=`` (plus
#: ``"none"`` for explicit off and ``None`` for "resolve env -> tuned").
COMM_DTYPE_CHOICES = ("int8", "fp8")

#: Canonical KV cache storage dtypes accepted by ``kv_dtype=``.
KV_DTYPE_CHOICES = ("int8",)

_INT8_QMAX = 127.0

_NAME_ALIASES = {
    "": None,
    "none": "none",
    "off": "none",
    "0": "none",
    "float32": "none",
    "bfloat16": "none",
    "bf16": "none",
    "int8": "int8",
    "s8": "int8",
    "fp8": "fp8",
    "e4m3": "fp8",
    "float8_e4m3fn": "fp8",
    # e2m1 (fp4) has no backend support anywhere we run; the ISSUE's
    # "where the backend supports it, int8 fallback otherwise" contract
    # maps it to the fp8 resolution path, which falls back in turn.
    "e2m1": "fp8",
}


def canonical_comm_dtype(name: Any) -> Optional[str]:
    """Normalize a user spelling of ``comm_dtype``.

    Returns ``None`` for "unset" (resolve env -> tuned -> off), the
    string ``"none"`` for an explicit off, or a canonical member of
    :data:`COMM_DTYPE_CHOICES`.  Raises on unknown names so typos fail
    at construction, not silently at full precision.
    """
    if name is None:
        return None
    key = str(name).strip().lower()
    if key in _NAME_ALIASES:
        return _NAME_ALIASES[key]
    raise ValueError(
        f"unknown comm_dtype {name!r}; choose from "
        f"{COMM_DTYPE_CHOICES} (or 'none' to disable)"
    )


def canonical_kv_dtype(name: Any) -> Optional[str]:
    """Normalize a ``kv_dtype`` spelling: ``None``/"none"/model-dtype
    names mean "store pages at the model dtype" (off); ``"int8"`` turns
    quantized pages on."""
    if name is None:
        return None
    key = str(name).strip().lower()
    if key in ("", "none", "off", "bf16", "bfloat16", "float32", "fp32"):
        return None
    if key in ("int8", "s8"):
        return "int8"
    raise ValueError(
        f"unknown kv_dtype {name!r}; choose from {KV_DTYPE_CHOICES} "
        "(or 'none' to store pages at the model dtype)"
    )


@functools.lru_cache(maxsize=None)
def fp8_supported() -> bool:
    """Whether this jax/backend pair can compile arithmetic on
    ``float8_e4m3fn`` (probed once; collectives on e4m3 follow where
    the elementwise ops compile — verified on the CPU and TPU backends
    this repo targets)."""
    if not hasattr(jnp, "float8_e4m3fn"):
        return False
    try:
        jax.jit(lambda x: x + x)(
            jnp.ones((2,), jnp.float8_e4m3fn)
        ).block_until_ready()
        return True
    except Exception:  # pragma: no cover - backend without fp8
        return False


def wire_dtype(comm_dtype: Optional[str]):
    """Canonical comm dtype name -> the jnp dtype that goes on the wire.

    ``"fp8"`` resolves to ``float8_e4m3fn`` where the backend supports
    it and **falls back to int8** otherwise (the ISSUE's contract);
    ``None``/``"none"`` -> ``None`` (quantization off).
    """
    if comm_dtype is None or comm_dtype == "none":
        return None
    if comm_dtype == "int8":
        return jnp.int8
    if comm_dtype == "fp8":
        return jnp.float8_e4m3fn if fp8_supported() else jnp.int8
    raise ValueError(f"unknown canonical comm_dtype {comm_dtype!r}")


def qmax(wire_dt) -> float:
    """Largest representable magnitude of a wire dtype."""
    wire_dt = jnp.dtype(wire_dt)
    if wire_dt == jnp.dtype(jnp.int8):
        return _INT8_QMAX
    return float(jnp.finfo(wire_dt).max)  # e4m3fn: 448


def quantizable(dtype) -> bool:
    """Only inexact (float) buckets are quantized; integer gradients
    (rare, but legal pytree leaves) pass through at full precision."""
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def _chunked(buf, chunk_elems: Optional[int]):
    """View a 1-D buffer as (n_chunks, chunk) when ``chunk_elems``
    divides it, else as one chunk.  Per-chunk scales tighten the error
    bound on buckets whose leaves have very different magnitudes."""
    n = buf.shape[0]
    if chunk_elems and chunk_elems < n and n % chunk_elems == 0:
        return buf.reshape(n // chunk_elems, chunk_elems)
    return buf.reshape(1, n)


def local_amax(buf, chunk_elems: Optional[int] = None):
    """Per-chunk max-abs of this rank's bucket, f32, shape (n_chunks,)."""
    x = _chunked(buf, chunk_elems).astype(jnp.float32)
    return jnp.max(jnp.abs(x), axis=1)


def per_rank_qmax(wire_dt, world: int) -> float:
    """Each rank's magnitude budget on the wire, such that the WORLD SUM
    stays representable.  int8: an integer budget (``round`` can never
    exceed an integer bound, see module docstring) — worlds beyond 127
    chips have no int8 budget left and must shard the sum (the 2-D /
    scatter legs) or stay at full precision.  fp8: ``qmax/world`` with a
    2**-3 divisor absorbing the format's relative rounding."""
    wire_dt = jnp.dtype(wire_dt)
    if wire_dt == jnp.dtype(jnp.int8):
        return max(1.0, float(np.floor(_INT8_QMAX / world)))
    return qmax(wire_dt) / world / (1.0 + 2.0 ** -3)


def scale_for(amax_global, wire_dt, world: int):
    """The shared scale ``s = amax / per_rank_qmax`` (f32, per chunk).

    The world headroom in :func:`per_rank_qmax` keeps every rank's
    quantized value small enough that the wire-dtype SUM cannot
    overflow.  Zero-amax chunks (all-zero gradients) get ``s = 1`` so
    the divide is finite and the round trip is exactly zero.
    """
    s = amax_global / per_rank_qmax(wire_dt, world)
    return jnp.where(amax_global > 0, s, jnp.ones_like(s))


def quantize(buf, scale, wire_dt, chunk_elems: Optional[int] = None):
    """Scale + cast one bucket buffer to the wire dtype."""
    x = _chunked(buf, chunk_elems).astype(jnp.float32) / scale[:, None]
    wire_dt = jnp.dtype(wire_dt)
    if wire_dt == jnp.dtype(jnp.int8):
        x = jnp.clip(jnp.round(x), -_INT8_QMAX, _INT8_QMAX)
    return x.astype(wire_dt).reshape(buf.shape)


def dequantize_mean(qsum, scale, world: int, out_dtype,
                    chunk_elems: Optional[int] = None):
    """Summed wire buffer -> the fp mean: ``qsum * s / world``.

    The division happens in f32 — never in the wire dtype, where integer
    division would truncate toward zero and bias every gradient.
    """
    x = _chunked(qsum, chunk_elems).astype(jnp.float32)
    x = x * (scale[:, None] / float(world))
    return x.reshape(qsum.shape).astype(out_dtype)


def quantize_for_allreduce(
    buf, wire_dt, axes, world: int, chunk_elems: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """The comm half's pre-collective leg: local amax -> ``pmax`` over
    the world (so every rank agrees on the scale) -> quantize.

    Returns ``(q, scale)``; the caller runs its characteristic SUM
    collective on ``q`` and finishes with :func:`dequantize_mean`.
    Must be called inside ``shard_map`` over ``axes`` (the same contract
    as every traced collective).
    """
    amax = lax.pmax(local_amax(buf, chunk_elems), axes)
    scale = scale_for(amax, wire_dt, world)
    return quantize(buf, scale, wire_dt, chunk_elems), scale


def error_bound(comm_dtype: str, amax, world: int):
    """Documented per-dtype worst-case error of the quantized mean vs
    the fp32 mean (see module docstring; gated in tests/test_quant.py).

    ``comm_dtype`` is the canonical name; ``amax`` the global bucket
    amax (scalar or array).  fp8's bound covers the int8 fallback too
    (the int8 bound is strictly tighter at any world size >= 1).
    """
    amax = np.asarray(amax, np.float64)
    if comm_dtype == "int8":
        return amax / (2.0 * max(1.0, np.floor(_INT8_QMAX / world)))
    if comm_dtype == "fp8":
        # Covers the int8 fallback too: the int8 bound is tighter than
        # this for every world size the fallback can see.
        return amax * (world + 1) / 16.0
    raise ValueError(f"no error bound for comm_dtype {comm_dtype!r}")


# ----------------------------------------------------------------------
# Serving KV half: per-token-per-head scales over d_head
# ----------------------------------------------------------------------
def quantize_kv(x) -> Tuple[jax.Array, jax.Array]:
    """Quantize freshly-projected K or V for int8 page storage.

    ``x``: (B, T, Hkv, D).  Returns ``(q, scales)`` with ``q`` int8 of
    the same shape and ``scales`` f32 of shape (B, T, Hkv) — one amax
    scale per written token per KV head, the granularity that survives
    paging: token (page, slot) moves atomically with its scale through
    the same block table.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0, amax / _INT8_QMAX, jnp.ones_like(amax))
    q = jnp.clip(
        jnp.round(xf / scale[..., None]), -_INT8_QMAX, _INT8_QMAX
    ).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scales, dtype):
    """Int8 pages (or a gathered context) back to the compute dtype.

    ``q``: (..., Hkv, D) int8; ``scales``: (..., Hkv) f32 broadcast over
    the trailing head dim.  Invalid/untouched slots hold zero payload
    AND zero scale, so they dequantize to exact zeros — the same value
    the unquantized cache's zero-init gives masked positions.
    """
    return (q.astype(jnp.float32) * scales[..., None]).astype(dtype)


# ----------------------------------------------------------------------
# Host-side measurement (Reporter gauges are host-plane: in-jit
# publishing is impossible, so error is measured eagerly on demand)
# ----------------------------------------------------------------------
def measure_comm_quant_error(comm, tree, publish: bool = True) -> float:
    """Max-abs error of ``comm``'s quantized allreduce vs its own
    full-precision path on ``tree`` (rank-stacked by replication, so the
    true mean is the tree itself).

    Publishes the ``comm/quant_abs_err`` gauge when telemetry is active
    and ``publish`` is set.  Returns the error as a Python float — the
    number bench's A/B column and the verify-skill probe print.
    """
    cd = comm.resolve_comm_dtype(tree)
    if cd is None:
        raise ValueError(
            "measure_comm_quant_error needs a communicator with a "
            "resolved comm_dtype (ctor or CHAINERMN_TPU_COMM_DTYPE)"
        )
    stacked = jax.tree.map(
        lambda l: jnp.broadcast_to(
            l[None], (comm.device_size,) + tuple(l.shape)
        ),
        tree,
    )
    out_q = comm.eager_allreduce_grad(stacked)
    saved = comm.comm_dtype
    try:
        comm.comm_dtype = "none"
        out_ref = comm.eager_allreduce_grad(stacked)
    finally:
        comm.comm_dtype = saved
    err = 0.0
    for a, b in zip(jax.tree.leaves(out_q), jax.tree.leaves(out_ref)):
        d = jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))
        err = max(err, float(d))
    if publish:
        from chainermn_tpu.observability import reporter as _reporter
        from chainermn_tpu.observability import spans as _spans

        if _spans.telemetry_active():
            rep = _reporter.get_reporter()
            if rep is not None:
                rep.gauge("comm/quant_abs_err", err)
    return err
