"""Communicator base class — TPU-native contract matching the reference's
``CommunicatorBase`` (REF:chainermn/communicators/communicator_base.py).

Design stance (SURVEY §7): the reference is N identical MPI processes each
holding one GPU, with an eager communicator object whose methods *are* the
network operations.  The TPU-native rebuild keeps the same API surface but
runs on one global JAX view: a :class:`jax.sharding.Mesh` whose
``(inter, intra)`` axes encode the reference's inter-/intra-node split, with
XLA collectives (``psum``/``all_gather``/``all_to_all``/``ppermute``) as the
data plane.

Two planes, mirroring the reference's MPI-control/NCCL-data split (SURVEY
§2.6):

* **device plane** — collectives *traced into* a jitted program.  Methods in
  this plane (``allreduce_grad``, ``broadcast_data``, ``bcast``,
  ``allgather``, ``alltoall``, ``reduce_scatter``, ``send``/``recv``, …) must
  be called inside a ``shard_map`` over this communicator's mesh axes, where
  every device runs the same SPMD program — exactly the per-rank viewpoint a
  ChainerMN process had.  Eager convenience wrappers (``eager_*``) wrap the
  same implementations in ``jit(shard_map(...))`` for use on "rank-stacked"
  global arrays (leading axis = ``device_size``).
* **host/object plane** — pickled-object transport between *processes*
  (``bcast_obj``, ``gather_obj``, ``allreduce_obj``), the analogue of the
  reference's pickle-over-MPI ``*_obj`` methods
  (REF:chainermn/communicators/mpi_communicator_base.py).  Implemented over
  ``jax.experimental.multihost_utils`` when ``process_count > 1`` and as
  local no-ops on a single host.

Rank semantics: the reference has one process per GPU, so ``rank`` is both a
host and a device concept.  Under JAX one process drives many chips, so the
two split: ``rank``/``size`` here are *host*-plane (process) values — the
ones used for logging gates, dataset scattering, and object transport —
while ``device_size``/``intra_size``/``inter_size`` describe the chip mesh
and ``axis_index()`` is the traced per-chip rank inside ``shard_map``.
``intra_rank`` keeps its reference role of "which local accelerator should I
use" in the degenerate sense: JAX processes own all their local devices, so
it is always 0 and ``local_devices`` is the real answer.
"""

from __future__ import annotations

import os
import pickle
import warnings
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import kvtransport, mesh_utils, overlap as overlap_mod, packing, quant

try:  # jax >= 0.4.35
    from jax import shard_map as _shard_map_impl
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl

# The replication-check kwarg was renamed check_rep -> check_vma across
# jax releases; probe once which spelling this jax takes.
import inspect as _inspect

_SHARD_MAP_REP_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map_impl).parameters
    else "check_rep"
)


def shard_map_compat(fn, mesh, in_specs, out_specs, check_vma: bool = False):
    """``shard_map`` across jax versions: forwards ``check_vma`` under
    whichever replication-check spelling this jax accepts."""
    return _shard_map_impl(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_SHARD_MAP_REP_KW: check_vma},
    )


_shard_map = shard_map_compat


_PPERMUTE_FALLBACK_WARNED = False


def _warn_ppermute_fallback(world: int) -> None:
    """One-time warning when ``ppermute`` hits its general fallback.

    The fallback is ``all_gather`` + slice: correct for arbitrary
    permutations, but it moves ``world × message`` bytes instead of the
    O(message) the factored paths move.  No in-tree caller reaches it, so
    user code arriving here is almost always an unintended routing pattern
    worth restructuring (e.g. into per-axis maps or a uniform ring shift).
    """
    global _PPERMUTE_FALLBACK_WARNED
    if _PPERMUTE_FALLBACK_WARNED:
        return
    _PPERMUTE_FALLBACK_WARNED = True
    warnings.warn(
        "ppermute: permutation does not factor per-axis and is not a "
        f"uniform flat shift; falling back to all_gather over all "
        f"{world} devices + slice.  This moves world-volume "
        f"({world}x message) bytes per call.  Restructure the "
        "permutation (per-axis injective maps, or a constant "
        "(dst-src) % world shift) to get the O(message) paths.  "
        "This warning is emitted once per process.",
        RuntimeWarning,
        stacklevel=3,
    )


def _tree_cast(tree, dtype):
    if dtype is None:
        return tree
    # Skip leaves already at the target dtype: a no-op astype still emits
    # a convert_element_type into the jaxpr, inflating the hlo_audit
    # census (and the compiler's work) for nothing.
    return jax.tree.map(
        lambda x: x if x.dtype == dtype else x.astype(dtype), tree
    )


class CommunicatorBase:
    """Abstract communicator. Subclasses specialise ``allreduce_grad``.

    Reference contract: REF:chainermn/communicators/communicator_base.py
    (properties ``rank/size/intra_rank/intra_size/inter_rank/inter_size``;
    collectives ``send/recv/bcast/gather/allgather/alltoall``; model-level
    ``broadcast_data``/``allreduce_grad``; object-level ``bcast_obj``/
    ``gather_obj``/``allreduce_obj``; ``split``).
    """

    name = "base"
    _plane_count = 0  # class-level: SPMD construction order, see __init__

    def __init__(
        self,
        mesh: Mesh | None = None,
        axes: Sequence[str] | None = None,
        allreduce_grad_dtype: Any | None = None,
        host_members: Sequence[int] | None = None,
        bucket_bytes: int | None = None,
        overlap: bool | None = None,
        overlap_granularity: int | None = None,
        comm_dtype: Any | None = None,
    ):
        # Subgroup membership (``split(color, key)``): the ordered GLOBAL
        # process indices participating in this communicator's host plane.
        # None = the full world.  The calling process must be a member.
        self._hp_members = (
            list(host_members) if host_members is not None else None
        )
        if (
            self._hp_members is not None
            and jax.process_index() not in self._hp_members
        ):
            raise ValueError(
                f"process {jax.process_index()} is not in host_members "
                f"{self._hp_members}"
            )
        if mesh is None:
            mesh = mesh_utils.build_mesh()
        self.mesh = mesh
        self.axes = tuple(axes if axes is not None else mesh.axis_names)
        for a in self.axes:
            if a not in mesh.axis_names:
                raise ValueError(f"axis {a!r} not in mesh axes {mesh.axis_names}")
        # The analogue of pure_nccl's fp16 allreduce option
        # (REF:chainermn/communicators/pure_nccl_communicator.py,
        # `allreduce_grad_dtype`): cast grads before the collective, cast
        # back after.  bfloat16 is the TPU-native choice.
        self.allreduce_grad_dtype = (
            jnp.dtype(allreduce_grad_dtype) if allreduce_grad_dtype else None
        )
        # Gradient bucketing cap (chainermn_tpu.communicators.packing):
        # None = resolve at call time (env override -> tuned -> default),
        # 0 = bucketing off (the legacy per-leaf/one-buffer lowering),
        # >0 = explicit per-bucket payload cap in bytes.
        if bucket_bytes is not None:
            bucket_bytes = int(bucket_bytes)
            if bucket_bytes < 0:
                raise ValueError(
                    f"bucket_bytes must be >= 0, got {bucket_bytes}"
                )
        self.bucket_bytes = bucket_bytes
        # Backward-overlapped bucket emission
        # (chainermn_tpu.communicators.overlap): None = resolve at call
        # time (CHAINERMN_TPU_OVERLAP env, default ON), True/False pins
        # the schedule regardless of environment.
        self.overlap = None if overlap is None else bool(overlap)
        if overlap_granularity is not None:
            overlap_granularity = int(overlap_granularity)
            if overlap_granularity < 1:
                raise ValueError(
                    "overlap_granularity must be >= 1, got "
                    f"{overlap_granularity}"
                )
        self.overlap_granularity = overlap_granularity
        # Low-precision gradient exchange (chainermn_tpu.communicators.
        # quant): None = resolve at call time (CHAINERMN_TPU_COMM_DTYPE
        # env -> tuned -> off), "none" pins it off, "int8"/"fp8" scale
        # packed buckets onto that wire dtype around the sum collective.
        self.comm_dtype = quant.canonical_comm_dtype(comm_dtype)
        # Seed the latency-hiding-scheduler / async-collective XLA flags
        # while they can still take effect (no-op off-TPU, after backend
        # init, or when overlap is off — see overlap.ensure_overlap_flags).
        if self.overlap is not False:
            overlap_mod.ensure_overlap_flags()
        # Host-plane transport context.  Communicator construction is SPMD
        # (every process builds the same communicators in the same order —
        # the same contract MPI_Comm_create relies on), so a class-level
        # creation counter yields matching key namespaces on all processes,
        # playing the role of an MPI communicator context id.  The contract
        # is VERIFIED, not trusted: each plane publishes its construction
        # site (the first user frame below) at creation and checks it
        # against rank 0's at first use, so a rank-conditional
        # create_communicator fails fast with a diagnostic instead of
        # silently delivering another stream's payloads or hanging.
        import traceback

        site = "<unknown>"
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for frame in reversed(traceback.extract_stack()[:-1]):
            if not frame.filename.startswith(pkg):
                site = f"{frame.filename}:{frame.lineno}"
                break
        CommunicatorBase._plane_count += 1
        self._obj_plane = kvtransport.ObjectPlane(
            f"comm{CommunicatorBase._plane_count}",
            jax.process_index(), self.size,
            site=site, members=self._hp_members,
        )

    # ------------------------------------------------------------------
    # Host-plane topology (process granularity — reference ``rank``/``size``)
    # ------------------------------------------------------------------
    @property
    def rank(self) -> int:
        if self._hp_members is not None:
            return self._hp_members.index(jax.process_index())
        return jax.process_index()

    @property
    def size(self) -> int:
        if self._hp_members is not None:
            return len(self._hp_members)
        return jax.process_count()

    @property
    def intra_rank(self) -> int:
        # Reference: GPU index within the node, used as `device = comm.intra_rank`.
        # A JAX process owns all its local devices; see module docstring.
        return 0

    @property
    def local_devices(self):
        # Compare against the GLOBAL process index: on a split() subgroup
        # self.rank is subgroup-relative while d.process_index is global.
        me = jax.process_index()
        return [d for d in self.mesh.devices.flat if d.process_index == me]

    # ------------------------------------------------------------------
    # Device-plane topology (chip granularity)
    # ------------------------------------------------------------------
    @property
    def device_size(self) -> int:
        """Total chips in this communicator's world (reference ``size``)."""
        return mesh_utils.axes_size(self.mesh, self.axes)

    @property
    def inter_size(self) -> int:
        return self.mesh.shape.get(mesh_utils.AXIS_INTER, 1) if mesh_utils.AXIS_INTER in self.axes else 1

    @property
    def intra_size(self) -> int:
        return self.mesh.shape.get(mesh_utils.AXIS_INTRA, 1) if mesh_utils.AXIS_INTRA in self.axes else 1

    @property
    def inter_rank(self) -> int:
        return self.rank  # one mesh row per host; host rank == inter row.

    # ------------------------------------------------------------------
    # Traced device-plane collectives (call inside shard_map over self.axes)
    # ------------------------------------------------------------------
    def axis_index(self):
        """Traced flattened device rank (0..device_size-1)."""
        return mesh_utils.flat_rank(self.axes)

    def allreduce(self, x, op: str = "sum"):
        """Generic traced allreduce (reference ``allreduce``/``multi_node_mean``)."""
        if op == "sum":
            return lax.psum(x, self.axes)
        if op == "mean":
            return lax.pmean(x, self.axes)
        if op == "max":
            return lax.pmax(x, self.axes)
        if op == "min":
            return lax.pmin(x, self.axes)
        raise ValueError(f"unknown op {op!r}")

    def bcast(self, x, root: int = 0):
        """Traced broadcast from flattened device rank ``root``.

        Reference: ``MpiCommunicatorBase.bcast``.  SPMD formulation: zero out
        every shard but the root's and psum — on TPU this lowers to a single
        all-reduce (or is pattern-matched to a collective-broadcast), riding
        ICI for the ``intra`` leg.
        """
        mask = (self.axis_index() == root).astype(x.dtype)
        return lax.psum(x * mask, self.axes)

    def allgather(self, x, axis: int = 0, tiled: bool = False):
        """Traced allgather (reference ``allgather``). Leading world axis."""
        return lax.all_gather(x, self.axes, axis=axis, tiled=tiled)

    def gather(self, x, root: int = 0, axis: int = 0):
        """Traced point-to-root gather (reference ``MPI_Gather``): ``root``
        receives every device's ``x`` stacked along ``axis``; other devices
        return zeros (the reference returns ``None`` off-root).

        Binomial-tree lowering, ``ceil(log2 n)`` collective rounds: in
        round ``k`` every device at relative rank ``2^k (mod 2^{k+1})``
        ships its accumulated block of ``2^k`` messages one tree level
        rootward, all in ONE ppermute.  Latency is log-depth (the previous
        one-ppermute-per-source schedule was world-linear — n−1 rounds and
        O(world²) HLO growth); aggregate wire stays O(world·message) (each
        message crosses each tree level once): leaves send one round-k
        block of 2^k rows (exactly O(message) for power-of-two worlds,
        where every block row is live; on non-power-of-two worlds trailing
        senders' blocks carry padding rows), internal nodes forward their
        subtree.
        For gather-then-use-everywhere patterns prefer :meth:`allgather`,
        which is a single collective.  For an output that exists ONLY on
        the root device (no O(world·message) zeros elsewhere), use
        :meth:`eager_gather`.
        """
        n = self.device_size
        if n == 1:
            return jnp.expand_dims(x, axis)
        idx = self.axis_index()
        buf = x[None]  # block of messages for relative ranks [me, me+width)
        for k in range((n - 1).bit_length()):
            width = 1 << k
            pairs = [
                ((s + root) % n, (s - width + root) % n)
                for s in range(width, n, 2 * width)
            ]
            # Senders' current buf holds rel ranks [s, s+width); after the
            # concat, receivers hold [r, r+2*width).  Non-participants
            # accumulate junk rows that the final root mask discards.
            buf = jnp.concatenate([buf, self.ppermute(buf, pairs)], axis=0)
        buf = buf[:n]  # non-power-of-two worlds: trailing rows are padding
        # buf rows are in RELATIVE order (row j = flat rank (root+j) % n);
        # roll restores flat-rank order, then mask to root.
        buf = jnp.roll(buf, root, axis=0)
        buf = jnp.where(idx == root, buf, jnp.zeros_like(buf))
        return jnp.moveaxis(buf, 0, axis) if axis else buf

    def alltoall(self, x, split_axis: int = 0, concat_axis: int = 0):
        """Traced all-to-all (reference ``alltoall``), the primitive under
        Ulysses-style sequence parallelism (SURVEY §5.7)."""
        return lax.all_to_all(
            x, self.axes, split_axis=split_axis, concat_axis=concat_axis,
            tiled=True,
        )

    def reduce_scatter(self, x, scatter_dimension: int = 0):
        """Traced reduce-scatter — the first leg of the two-dimensional
        algorithm (REF:chainermn/communicators/two_dimensional_communicator.py)."""
        return lax.psum_scatter(
            x, self.axes, scatter_dimension=scatter_dimension, tiled=True
        )

    def scatter(self, x, root: int = 0):
        """Traced point-to-root scatter (reference ``MPI_Scatter``): device
        ``d`` receives chunk ``d`` of ``root``'s ``x`` along axis 0.

        Binomial-tree lowering, ``ceil(log2 n)`` collective rounds (the
        mirror of :meth:`gather`): the root's buffer halves each round,
        with the upper half of every current holder's range shipped one
        tree level leafward in ONE ppermute.  Each receiver's ingress is
        its power-of-two-padded subtree (= exactly its subtree on
        power-of-two worlds) and the aggregate wire is O(world·chunk)
        (each chunk crosses each tree level once) — no broadcast of the
        whole buffer, and log-depth latency versus the previous
        one-ppermute-per-destination schedule's world-linear rounds.
        """
        n = self.device_size
        if x.shape[0] % n:
            raise ValueError(
                f"scatter axis 0 ({x.shape[0]}) must be divisible by the "
                f"device count ({n}); pad the input first"
            )
        chunk = x.shape[0] // n
        if n == 1:
            return x
        idx = self.axis_index()
        rel = (idx - root) % n
        K = (n - 1).bit_length()
        # Message-major layout in RELATIVE rank order (row j = the chunk
        # for flat rank (root+j) % n), padded to the next power of two so
        # every round's send block has a static shape.
        buf = jnp.roll(x.reshape(n, chunk, *x.shape[1:]), -root, axis=0)
        if (1 << K) != n:
            pad = jnp.zeros(((1 << K) - n,) + buf.shape[1:], buf.dtype)
            buf = jnp.concatenate([buf, pad], axis=0)
        for t in range(K):
            width = 1 << (K - t - 1)
            pairs = [
                ((r + root) % n, (r + width + root) % n)
                for r in range(0, n, 2 * width)
                if r + width < n
            ]
            got = self.ppermute(buf[width : 2 * width], pairs)
            # Receivers this round (rel ≡ width mod 2·width) adopt the
            # shipped block; holders keep their lower half; devices not yet
            # reached carry junk that a later round overwrites.
            buf = jnp.where(rel % (2 * width) == width, got, buf[:width])
        return buf.reshape((chunk,) + x.shape[1:])

    def ppermute(self, x, perm):
        """``lax.ppermute`` semantics over this communicator's (flattened)
        world: destinations named in ``perm`` (a list of (src, dst) flat
        ranks) receive their source's value, everyone else receives zeros.
        The building block of differentiable send/recv
        (chainermn_tpu.functions.point_to_point, mirroring
        REF:chainermn/functions/point_to_point_communication.py).

        Multi-axis lowering moves O(message) bytes, not O(world):

        1. *Per-axis product* — when the perm factors into one well-defined
           injective map per mesh axis (single-pair p2p, neighbor exchange,
           grid translations without flat wrap-around), it lowers to one
           ppermute hop per non-identity axis.
        2. *Uniform flat shift* — a constant ``(dst - src) % world`` shift
           (the ring case: ``ring_exchange``, pipelines over 2-axis meshes)
           wraps between rows, so the row hop is issued at both ``q`` and
           ``q+1`` and wrapped columns select the latter: 3 hops total.
        3. General perms that factor neither way fall back to
           ``all_gather`` + slice — correct for arbitrary routing, at
           world-volume cost (no in-tree caller hits this; the fallback
           exists for API completeness).

        All paths are natively differentiable (ppermute transposes to the
        reversed perm; the wrap select is elementwise).
        """
        if len(self.axes) == 1:
            return lax.ppermute(x, self.axes[0], perm)
        sizes = [self.mesh.shape[a] for a in self.axes]
        n = self.device_size

        def coords(r):
            c = []
            for s in reversed(sizes):
                c.append(r % s)
                r //= s
            return tuple(reversed(c))  # row-major; axes[0] slowest

        # (1) per-axis product decomposition.
        axis_maps: list[dict[int, int]] = [{} for _ in sizes]
        factors = True
        for s, d in perm:
            cs, cd = coords(s), coords(d)
            for k in range(len(sizes)):
                if axis_maps[k].setdefault(cs[k], cd[k]) != cd[k]:
                    factors = False
                    break
            if not factors:
                break
        if factors:
            factors = all(
                len(set(m.values())) == len(m) for m in axis_maps
            )
        if factors:
            out = x
            for k, axis in enumerate(self.axes):
                pairs = sorted(axis_maps[k].items())
                if all(a == b for a, b in pairs):
                    continue  # identity along this axis: no hop needed
                out = lax.ppermute(out, axis, pairs)
            return self._mask_non_dsts(out, perm)

        # (2) uniform flat shift over a 2-axis world.
        shifts = {(d - s) % n for s, d in perm}
        if len(shifts) == 1 and len(sizes) == 2:
            shift = shifts.pop()
            n_inter, n_intra = sizes
            q, r = divmod(shift, n_intra)
            if r:
                xj = lax.ppermute(
                    x, self.axes[1],
                    [(j, (j + r) % n_intra) for j in range(n_intra)],
                )
            else:
                xj = x  # row-multiple shift: no intra hop, no wrap
            row = lambda k: lax.ppermute(  # noqa: E731
                xj, self.axes[0],
                [(i, (i + k) % n_inter) for i in range(n_inter)],
            )
            xq = row(q) if q % n_inter else xj
            if r:
                # Columns j < r received a value that wrapped past the end
                # of its row and must advance one extra inter row.
                xq = jnp.where(
                    lax.axis_index(self.axes[1]) < r, row(q + 1), xq
                )
            return self._mask_non_dsts(xq, perm)

        # (3) general fallback: collapse via all_gather + slice.
        _warn_ppermute_fallback(n)
        src_for_dst = {d: s for s, d in perm}
        gathered = lax.all_gather(x, self.axes, axis=0)
        idx = self.axis_index()
        table = jnp.array(
            [src_for_dst.get(d, -1) for d in range(self.device_size)]
        )
        my_src = table[idx]
        picked = jnp.where(
            my_src >= 0,
            jnp.take(gathered, jnp.maximum(my_src, 0), axis=0),
            jnp.zeros_like(x),
        )
        return picked

    def _mask_non_dsts(self, out, perm):
        """Zero devices that are not a destination in ``perm`` — hop
        decompositions deliver junk to bystander devices that a true
        flattened ppermute would zero-fill."""
        dsts = {d for _, d in perm}
        if len(dsts) == self.device_size:
            return out
        table = jnp.asarray([d in dsts for d in range(self.device_size)])
        return jnp.where(table[self.axis_index()], out, jnp.zeros_like(out))

    # ------------------------------------------------------------------
    # Model plane (traced): the two methods every training step uses
    # ------------------------------------------------------------------
    def broadcast_data(self, tree, root: int = 0):
        """Replicate a parameter pytree from ``root`` to all devices.

        Reference: ``CommunicatorBase.broadcast_data(model)`` — the bcast of
        every parameter the multi-node optimizer issues on its first
        ``update`` (REF:chainermn/optimizers.py).
        """
        return jax.tree.map(lambda x: self.bcast(x, root), tree)

    def allreduce_grad(self, tree, overlap: bool | None = None):
        """Average a gradient pytree across the communicator's world.

        Reference: ``CommunicatorBase.allreduce_grad(model)`` — divides by
        ``size`` (mean), which every subclass here preserves.  Subclasses
        implement `_allreduce_impl` with their characteristic collective
        pattern; this wrapper handles the optional low-precision cast
        (``allreduce_grad_dtype``) and, for multi-leaf trees, the bucketed
        flat-buffer packing (:mod:`chainermn_tpu.communicators.packing`)
        that turns O(n_leaves) collectives into O(n_buckets) — the
        reference ``pure_nccl`` fusion generalized to every variant.
        Single-leaf trees take the direct path unchanged, and
        ``bucket_bytes=0`` (or ``CHAINERMN_TPU_BUCKET_BYTES=0``) restores
        the legacy unbucketed lowering.

        ``overlap`` pins the emission schedule for THIS call (the staged
        train-step pipeline threads it); ``None`` resolves ctor ->
        ``CHAINERMN_TPU_OVERLAP`` -> ON.  Overlapped emission is
        bit-exact vs eager: same per-bucket collectives, same operands —
        only the trace order changes so the buckets whose gradients the
        backward pass produces FIRST reduce while the rest still compute
        (see :mod:`chainermn_tpu.communicators.overlap`).

        When a ``comm_dtype`` resolves (ctor -> ``CHAINERMN_TPU_COMM_DTYPE``
        -> tuned), each float bucket is amax-scaled onto the narrow wire
        dtype around its sum collective and dequantized in f32
        (:mod:`chainermn_tpu.communicators.quant`) — bounded-error, not
        bit-exact; the bound per dtype is documented in
        docs/performance.md.  Quantization applies to the BUCKETED path
        only: single-leaf trees and ``bucket_bytes=0`` keep the exact
        full-precision lowering (no bucket boundary means no amax scope).
        """
        leaves = jax.tree.leaves(tree)
        if not leaves:
            return tree
        dtypes = jax.tree.map(lambda x: x.dtype, tree)
        tree = _tree_cast(tree, self.allreduce_grad_dtype)
        bb = self.resolve_bucket_bytes(tree) if len(leaves) > 1 else 0
        if bb > 0:
            out = self._allreduce_bucketed(tree, bb, overlap=overlap)
        else:
            out = self._allreduce_impl(tree)
        return jax.tree.map(
            lambda x, d: x if x.dtype == d else x.astype(d), out, dtypes
        )

    def _allreduce_impl(self, tree):
        raise NotImplementedError

    def _allreduce_sum_impl(self, buf):
        """Pure SUM over the world for one bucket buffer — the collective
        leg the quantized path runs on the narrow wire dtype.  Separate
        from ``_allreduce_impl`` because every variant's mean divides by
        ``device_size`` inline, and integer division on an int8 buffer
        would truncate toward zero and bias every gradient; the quantized
        path applies the mean in f32 at dequant time instead.  Subclasses
        with a multi-leg pattern (hierarchical, two_dimensional) override
        with their characteristic sum chain.
        """
        return lax.psum(buf, self.axes)

    def _allreduce_quantized(self, buf, wire_dt):
        """One bucket through the blessed scale->cast->sum->cast->unscale
        pattern (see :mod:`chainermn_tpu.communicators.quant`): global
        amax via ``pmax``, world-headroom scale, narrow-dtype sum via
        :meth:`_allreduce_sum_impl`, f32 dequant carrying the mean."""
        world = self.device_size
        q, scale = quant.quantize_for_allreduce(buf, wire_dt, self.axes, world)
        qsum = self._allreduce_sum_impl(q)
        return quant.dequantize_mean(qsum, scale, world, buf.dtype)

    def resolve_comm_dtype(self, tree=None) -> str | None:
        """Effective gradient wire dtype for one ``allreduce_grad`` call.

        Resolution order mirrors :meth:`resolve_bucket_bytes`: the
        constructor's ``comm_dtype`` if set ("none" pins off); else the
        ``CHAINERMN_TPU_COMM_DTYPE`` environment override; else a tuned
        value from the persistent tune cache (TPU runtime only — inert
        under pytest and off-TPU); else off.  Returns a canonical name
        from :data:`quant.COMM_DTYPE_CHOICES`, or ``None`` for off.
        """
        cd = self.comm_dtype
        if cd is None:
            env = os.environ.get(quant.ENV_COMM_DTYPE, "").strip()
            if env:
                try:
                    cd = quant.canonical_comm_dtype(env)
                except ValueError:
                    cd = None
        if cd is None and tree is not None:
            cd = self._tuned_comm_dtype(tree)
        return None if cd in (None, "none") else cd

    def _tuned_comm_dtype(self, tree):
        try:
            from chainermn_tpu.tuning.autotune import lookup_comm_dtype
        except Exception:  # pragma: no cover - tuning subsystem absent
            return None
        leaves = jax.tree.leaves(tree)
        per_dtype: dict = {}
        for l in leaves:
            dt = np.dtype(l.dtype)
            per_dtype[dt] = per_dtype.get(dt, 0) + int(l.size) * dt.itemsize
        dominant = max(per_dtype, key=per_dtype.get)
        return lookup_comm_dtype(
            total_bytes=sum(per_dtype.values()),
            n_leaves=len(leaves),
            dtype=dominant,
            communicator=self.name,
        )

    def resolve_bucket_bytes(self, tree=None) -> int:
        """Effective bucket cap for one ``allreduce_grad`` call.

        Resolution order: the constructor's ``bucket_bytes`` if set; else
        the ``CHAINERMN_TPU_BUCKET_BYTES`` environment override; else a
        tuned value from the persistent tune cache (TPU runtime only —
        inert under pytest and off-TPU, like every tuning lookup); else
        :data:`packing.DEFAULT_BUCKET_BYTES`.  Returns 0 when bucketing
        is disabled.
        """
        bb = self.bucket_bytes
        if bb is None:
            env = os.environ.get(packing.ENV_BUCKET_BYTES, "").strip()
            if env:
                try:
                    bb = int(env)
                except ValueError:
                    bb = None
        if bb is None and tree is not None:
            bb = self._tuned_bucket_bytes(tree)
        if bb is None:
            bb = packing.DEFAULT_BUCKET_BYTES
        return max(int(bb), 0)

    def _tuned_bucket_bytes(self, tree):
        try:
            from chainermn_tpu.tuning.autotune import lookup_bucket_bytes
        except Exception:  # pragma: no cover - tuning subsystem absent
            return None
        leaves = jax.tree.leaves(tree)
        per_dtype: dict = {}
        for l in leaves:
            dt = np.dtype(l.dtype)
            per_dtype[dt] = per_dtype.get(dt, 0) + int(l.size) * dt.itemsize
        dominant = max(per_dtype, key=per_dtype.get)
        return lookup_bucket_bytes(
            total_bytes=sum(per_dtype.values()),
            n_leaves=len(leaves),
            dtype=dominant,
            communicator=self.name,
        )

    def resolve_overlap(self, overlap: bool | None = None) -> bool:
        """Effective overlap switch for one ``allreduce_grad`` call:
        the call-site pin if given, else the constructor's ``overlap``,
        else the ``CHAINERMN_TPU_OVERLAP`` environment gate (default
        ON — ``0`` is the escape hatch)."""
        if overlap is not None:
            return bool(overlap)
        if self.overlap is not None:
            return self.overlap
        return overlap_mod.overlap_enabled()

    def resolve_overlap_granularity(self, tree=None) -> int:
        """Effective schedule granularity (buckets emitted per stage).

        Resolution order mirrors :meth:`resolve_bucket_bytes`: ctor ->
        ``CHAINERMN_TPU_OVERLAP_GRANULARITY`` env -> tuned value (TPU
        runtime only) -> 1 (finest overlap: one collective per stage).
        """
        if self.overlap_granularity is not None:
            return self.overlap_granularity
        raw = os.environ.get(overlap_mod.ENV_OVERLAP_GRANULARITY, "").strip()
        if raw:
            try:
                return max(1, int(raw))
            except ValueError:
                pass
        if tree is not None:
            tuned = self._tuned_overlap_granularity(tree)
            if tuned is not None:
                return max(1, int(tuned))
        return overlap_mod.DEFAULT_GRANULARITY

    def _tuned_overlap_granularity(self, tree):
        try:
            from chainermn_tpu.tuning.autotune import lookup_overlap_schedule
        except Exception:  # pragma: no cover - tuning subsystem absent
            return None
        leaves = jax.tree.leaves(tree)
        per_dtype: dict = {}
        for l in leaves:
            dt = np.dtype(l.dtype)
            per_dtype[dt] = per_dtype.get(dt, 0) + int(l.size) * dt.itemsize
        dominant = max(per_dtype, key=per_dtype.get)
        cfg = lookup_overlap_schedule(
            total_bytes=sum(per_dtype.values()),
            n_leaves=len(leaves),
            dtype=dominant,
            communicator=self.name,
        )
        return None if cfg is None else cfg.get("granularity")

    def _allreduce_bucketed(self, tree, bucket_bytes: int,
                            overlap: bool | None = None):
        """One characteristic ``_allreduce_impl`` per contiguous per-dtype
        bucket.  Pack/unpack are pure layout moves (ravel/concat/slice),
        so they commute exactly with the elementwise-linear collectives
        every subclass lowers to — bucketed and unbucketed results are
        identical up to the collective's own dtype arithmetic.

        Two emission schedules, numerically identical:

        * **overlapped** (default): per-bucket pack + collective in
          reverse leaf-production order (`overlap.build_overlap_schedule`)
          so each collective's operands are exactly its member leaves and
          the first-ready buckets reduce under the rest of the backward
          pass (async start/done pairs straddle compute in the HLO once
          the latency-hiding scheduler runs).
        * **eager** (``CHAINERMN_TPU_OVERLAP=0``): pack every bucket,
          then reduce every bucket — the pre-overlap lowering, kept as
          the escape hatch and the parity oracle.
        """
        packer = packing.GradPacker.for_tree(tree, bucket_bytes=bucket_bytes)
        self._report_packing(packer)
        from chainermn_tpu.observability.spans import named_scope

        # Low-precision wire: quantize each float bucket around its sum
        # collective (quant.py's blessed pattern).  Integer buckets pass
        # through at full precision, and the schedule below is untouched
        # — scaled buckets still stage in reverse leaf-production order.
        wire_dt = quant.wire_dtype(self.resolve_comm_dtype(tree))
        self._report_quant(packer, wire_dt)

        def reduce_bucket(buf):
            if wire_dt is not None and quant.quantizable(buf.dtype):
                return self._allreduce_quantized(buf, wire_dt)
            return self._allreduce_impl(buf)

        if not self.resolve_overlap(overlap):
            with named_scope("grad-pack"):
                bufs = packer.pack(tree)
            outs = [reduce_bucket(b) for b in bufs]
            with named_scope("grad-unpack"):
                return packer.unpack(outs)

        schedule = overlap_mod.build_overlap_schedule(
            packer, self.resolve_overlap_granularity(tree)
        )
        leaves = packer._check_tree(tree)
        outs: list = [None] * packer.n_buckets
        for s, stage in enumerate(schedule.stages):
            with named_scope(f"grad-stage{s}"):
                bufs = [packer.pack_bucket(leaves, i) for i in stage]
                for i, buf in zip(stage, bufs):
                    outs[i] = reduce_bucket(buf)
        with named_scope("grad-unpack"):
            return packer.unpack(outs)

    def _report_packing(self, packer) -> None:
        """Publish the packing plan to the Reporter — at TRACE time (the
        plan is static; a jitted step re-publishes only when retraced)."""
        from chainermn_tpu.observability import reporter as _reporter
        from chainermn_tpu.observability import spans as _spans

        if not _spans.telemetry_active():
            return
        rep = _reporter.get_reporter()
        if rep is None:  # pragma: no cover - raced deactivation
            return
        rep.count("grad_pack/traces")
        rep.count("grad_pack/leaves", packer.n_leaves)
        rep.count("grad_pack/buckets", packer.n_buckets)
        rep.count("grad_pack/payload_bytes", packer.payload_bytes)
        rep.count(
            "grad_pack/pad_bytes", packer.padded_bytes - packer.payload_bytes
        )
        rep.histogram_observe("grad_pack/bucket_bytes", packer.bucket_bytes)

    def _report_quant(self, packer, wire_dt) -> None:
        """Publish the quantization plan (trace-time, like
        :meth:`_report_packing`): how many buckets ride the narrow wire
        and the bytes they move vs their full-precision payload."""
        if wire_dt is None:
            return
        from chainermn_tpu.observability import reporter as _reporter
        from chainermn_tpu.observability import spans as _spans

        if not _spans.telemetry_active():
            return
        rep = _reporter.get_reporter()
        if rep is None:  # pragma: no cover - raced deactivation
            return
        wire_size = jnp.dtype(wire_dt).itemsize
        n_q = sum(
            1 for b in packer.buckets if quant.quantizable(b.dtype)
        )
        rep.count("grad_pack/quant_buckets", n_q)
        rep.count("grad_pack/quant_wire_bytes", sum(
            b.padded_elems * wire_size
            for b in packer.buckets if quant.quantizable(b.dtype)
        ))

    def multi_node_mean(self, tree):
        """Alias matching later reference spellings of allreduce_grad."""
        return self.allreduce_grad(tree)

    # ------------------------------------------------------------------
    # Eager wrappers: jit(shard_map(traced impl)) over rank-stacked arrays
    # ------------------------------------------------------------------
    def _eager(self, fn: Callable, in_specs, out_specs):
        return jax.jit(
            _shard_map(
                fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            )
        )

    @property
    def world_axes(self):
        """This communicator's mesh axes in the form collectives take: the
        tuple for multi-axis worlds, the bare name for single-axis ones."""
        return self.axes if len(self.axes) > 1 else self.axes[0]

    @property
    def _world_spec(self):
        """PartitionSpec sharding a leading "rank" axis over the world."""
        return P(self.world_axes)

    def _eager_cached(self, key, stacked_tree, make_body):
        """Build-or-reuse a jitted shard_map for an eager collective.

        Keyed by (op, treedef, leaf shapes/dtypes) so repeated calls — the
        reference's per-step eager ``comm.allreduce_grad(model)`` pattern —
        hit the compile cache instead of re-tracing a fresh closure.
        """
        leaves, treedef = jax.tree.flatten(stacked_tree)
        cache_key = (key, treedef, tuple((l.shape, jnp.asarray(l).dtype) for l in leaves))
        cache = getattr(self, "_eager_cache", None)
        if cache is None:
            cache = self._eager_cache = {}
        fn = cache.get(cache_key)
        if fn is None:
            spec = self._world_spec
            body = make_body()
            specs = jax.tree.map(lambda _: spec, stacked_tree)
            fn = cache[cache_key] = self._eager(body, (specs,), specs)
        return fn(stacked_tree)

    def eager_allreduce_grad(self, stacked_tree):
        """Eager allreduce over a pytree whose leaves have a leading
        ``device_size`` axis ("each rank's grads", the reference's eager
        ``comm.allreduce_grad(model)`` call shape). Returns the same shape
        with every slice equal to the mean."""

        def make_body():
            def body(tree):
                tree = jax.tree.map(lambda x: jnp.squeeze(x, 0), tree)
                out = self.allreduce_grad(tree)
                return jax.tree.map(lambda x: x[None], out)

            return body

        # The resolved wire dtype joins the cache key: toggling
        # comm_dtype (attribute or env) between calls must retrace, not
        # reuse the other precision's compiled collective.
        return self._eager_cached(
            ("allreduce_grad", self.resolve_comm_dtype()),
            stacked_tree, make_body,
        )

    def device_for_rank(self, r: int):
        """The device at flattened rank ``r`` (row-major over ``self.axes``,
        matching :meth:`axis_index`)."""
        sizes = [self.mesh.shape[a] for a in self.axes]
        coords = dict.fromkeys(self.mesh.axis_names, 0)
        for a, s in zip(reversed(self.axes), reversed(sizes)):
            coords[a] = r % s
            r //= s
        pos = tuple(coords[a] for a in self.mesh.axis_names)
        return np.asarray(self.mesh.devices)[pos]

    def eager_gather(self, stacked_x, root: int = 0):
        """Gather a rank-stacked array to the ROOT DEVICE ONLY — the
        off-root-cheap output form of :meth:`gather`.

        ``stacked_x``: global array with leading ``device_size`` axis (each
        device's message at its rank slot).  Returns the same array resident
        solely on ``root``'s device (``SingleDeviceSharding``) — off-root
        devices hold nothing, versus the traced :meth:`gather`'s uniform
        SPMD output shape (zeros off-root, unavoidable inside shard_map).
        This is the TPU-native spelling of MPI_Gather's "only root gets the
        buffer": a resharding, which XLA lowers to its own point-to-root
        tree over ICI.  Single-host form (the root device must be
        addressable from this process; cross-process object gathers go
        through :meth:`gather_obj`)."""
        dev = self.device_for_rank(root)
        if dev.process_index != jax.process_index():
            raise ValueError(
                f"eager_gather root {root} lives on process "
                f"{dev.process_index}; only its owner can address it — use "
                "gather_obj for cross-process host-plane gathers"
            )
        return jax.device_put(
            stacked_x, jax.sharding.SingleDeviceSharding(dev)
        )

    def eager_broadcast_data(self, stacked_tree, root: int = 0):
        def make_body():
            def body(tree):
                tree = jax.tree.map(lambda x: jnp.squeeze(x, 0), tree)
                out = self.broadcast_data(tree, root)
                return jax.tree.map(lambda x: x[None], out)

            return body

        return self._eager_cached(
            ("broadcast_data", root), stacked_tree, make_body
        )

    def shard_map(self, fn, in_specs, out_specs, check_vma: bool = False):
        """Run ``fn`` in the per-device SPMD view over this communicator's
        mesh — the TPU spelling of "the body of a ChainerMN process"."""
        return _shard_map(
            fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )

    def global_batch(self, batch):
        """Assemble the global batch from per-host batches.

        Under JAX one jitted step spans every process, so train steps take
        the *global* batch — there is no per-rank-batch analogue of the
        reference's model.  Each host passes the slice its
        ``scatter_dataset`` shard produced; leaves come back as global
        ``jax.Array``s sharded along axis 0 over the world
        (``shape[0] = per_host_batch * process_count``).  Per-host leading
        axes must be divisible by the host's local device count.
        Single-process: returns ``batch`` unchanged.
        """
        if self.size == 1:
            return batch
        from jax.experimental import multihost_utils

        spec = self._world_spec
        specs = jax.tree.map(lambda _: spec, batch)
        return multihost_utils.host_local_array_to_global_array(
            batch, self.mesh, specs
        )

    # ------------------------------------------------------------------
    # Host/object plane (reference pickle-over-MPI *_obj methods)
    # ------------------------------------------------------------------
    def send_obj(self, obj, dest: int, tag: int = 0) -> None:
        """True host-plane point-to-point send to process ``dest`` — the
        reference's ``MpiCommunicatorBase.send``.  No collective is
        involved: only the two endpoints participate.  ndarrays travel
        TYPED (raw buffer + dtype/shape header, no pickle — the
        reference's first-class ndarray path); other objects are pickled.
        The payload rides a direct TCP connection between the two
        processes (measured ~1 GB/s for 64 MiB arrays on localhost),
        rendezvoused — and, where sockets are unavailable
        (``CHAINERMN_TPU_SOCKET_P2P=0``), carried chunked — through the
        coordination service's KV store (see
        :mod:`chainermn_tpu.communicators.kvtransport`).  Matched
        ``send_obj``/``recv_obj`` pairs on the same (edge, tag) must occur
        in the same order on both sides, exactly MPI's matching rule."""
        if not (0 <= dest < self.size) or dest == self.rank:
            raise ValueError(
                f"send_obj dest must be another process in [0, {self.size}), "
                f"got {dest} (self.rank={self.rank})"
            )
        self._require_kv("send_obj")
        self._obj_plane.send(obj, dest, tag)

    def recv_obj(self, source: int, tag: int = 0,
                 timeout_ms: int | None = None):
        """Blocking host-plane receive from process ``source`` (the
        reference's ``MpiCommunicatorBase.recv``).  Waits indefinitely by
        default (MPI semantics); a finite ``timeout_ms`` raises instead,
        and the sequence stream stays intact so the receive may be
        retried."""
        if not (0 <= source < self.size) or source == self.rank:
            raise ValueError(
                f"recv_obj source must be another process in [0, {self.size}), "
                f"got {source} (self.rank={self.rank})"
            )
        self._require_kv("recv_obj")
        return self._obj_plane.recv(source, tag, timeout_ms=timeout_ms)

    def _require_kv(self, op: str) -> None:
        if not kvtransport.available():
            raise RuntimeError(
                f"{op} needs the jax.distributed coordination service "
                "(call jax.distributed.initialize); single-process runs "
                "have no peer to talk to"
            )

    def bcast_obj(self, obj, root: int = 0):
        if self.size == 1:
            return obj
        if kvtransport.available():
            # Chunked KV-store broadcast: exact payload bytes on the wire,
            # the reference's ``chunked_bcast_obj``
            # (REF:.../_communication_utility.py).
            return self._obj_plane.bcast(obj, root)
        self._require_subgroup_kv("bcast_obj")
        return self._bcast_obj_devices(obj, root)

    def _require_subgroup_kv(self, op: str) -> None:
        """The multihost_utils fallbacks below are WORLD collectives: on a
        split() subgroup they would mix colors' payloads (or deadlock), so
        subgroups insist on the coordination-service object plane."""
        if self._hp_members is not None:
            raise RuntimeError(
                f"{op} on a split() subgroup requires the jax.distributed "
                "coordination service (the world-collective fallback "
                "cannot scope to a subgroup)"
            )

    def _bcast_obj_devices(self, obj, root: int):
        """Fallback broadcast over device collectives for multi-process
        setups without a coordination-service client."""
        from jax.experimental import multihost_utils

        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        n = multihost_utils.broadcast_one_to_all(
            np.int64(payload.size), is_source=self.rank == root
        )
        buf = np.zeros(int(n), np.uint8)
        if self.rank == root:
            buf[:] = payload
        out = multihost_utils.broadcast_one_to_all(buf, is_source=self.rank == root)
        return pickle.loads(np.asarray(out).tobytes())

    def gather_obj(self, obj, root: int | None = None,
                   timeout_ms: int | None = None):
        """Gather every process's object.

        ``root=None`` (default): allgather semantics — the full list on
        every rank, which keeps SPMD callers branch-free (every in-tree
        symmetric caller wants this).

        ``root=r``: the reference's ``MPI_Gather`` wire profile
        (REF:chainermn/communicators/mpi_communicator_base.py ``gather``)
        — every non-root sends ONLY to root (O(n * payload) total wire,
        non-root processes fetch nothing) and the list is returned at
        root, ``None`` elsewhere.  ``timeout_ms`` bounds root's wait on
        EACH member's payload (the same contract ``recv_obj`` has), so a
        member that died before sending raises ``TimeoutError`` at root
        instead of blocking forever.

        Payloads travel at their exact size — no pad-to-max."""
        if self.size == 1:
            return [obj]
        if root is not None:
            if not (0 <= root < self.size):
                raise ValueError(f"gather_obj root {root} out of range")
            self._require_kv("gather_obj(root=...)")
            return self._obj_plane.gather(obj, root, timeout_ms=timeout_ms)
        if kvtransport.available():
            return self._obj_plane.allgather(obj, timeout_ms=timeout_ms)
        if timeout_ms is not None:
            raise ValueError(
                "gather_obj: timeout_ms with root=None needs the KV "
                "object plane; the process_allgather fallback has no "
                "bounded-wait implementation and would silently ignore it"
            )
        self._require_subgroup_kv("gather_obj")
        from jax.experimental import multihost_utils

        payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        sizes = multihost_utils.process_allgather(np.int64(payload.size))
        buf = np.zeros(int(sizes.max()), np.uint8)
        buf[: payload.size] = payload
        all_bufs = multihost_utils.process_allgather(buf)
        return [
            pickle.loads(np.asarray(all_bufs[i][: int(sizes[i])]).tobytes())
            for i in range(self.size)
        ]

    def allgather_obj(self, obj):
        return self.gather_obj(obj)

    def allreduce_obj(self, obj, op=None):
        """Sum (or ``op``-reduce) pickled objects across processes — the
        reference's ``allreduce_obj`` used by the multi-node evaluator."""
        objs = self.gather_obj(obj)
        red = objs[0]
        for o in objs[1:]:
            red = op(red, o) if op is not None else red + o
        return red

    def scatter_obj(self, objs, root: int = 0):
        if self.size == 1:
            return objs[0] if self.rank == root else None
        if kvtransport.available():
            # Point-to-point: each rank receives only its own element
            # (reference ``scatter_obj`` wire profile), not the whole list.
            return self._obj_plane.scatter(objs, root)
        objs = self.bcast_obj(objs, root)
        return objs[self.rank]

    _barrier_seq = 0  # class-level: every process advances it identically

    def barrier(self, timeout_s: float | None = None):
        """``timeout_s`` (or env ``CHAINERMN_TPU_BARRIER_TIMEOUT_S``,
        which the elastic supervisor sets for every rank it spawns)
        bounds the wait: a peer that died mid-job raises
        ``TimeoutError`` here instead of stalling the survivor forever
        — the except hook then turns that into a loud, fast exit the
        supervisor can act on.  The env knob must be set identically on
        every rank (it routes the barrier over the object plane, and
        mixed routes would deadlock)."""
        if self.size <= 1:
            return
        if timeout_s is None:
            t = os.environ.get("CHAINERMN_TPU_BARRIER_TIMEOUT_S")
            timeout_s = float(t) if t else None
        if self._hp_members is not None:
            # Subgroup barrier: must involve ONLY the members (a world
            # barrier would deadlock against other colors).  An obj-plane
            # allgather of a token has exactly MPI_Barrier's completion
            # semantics: no member returns before every member arrived.
            self.gather_obj(
                None,
                timeout_ms=None if timeout_s is None
                else int(timeout_s * 1000),
            )
            return
        if timeout_s is not None and kvtransport.available():
            self._obj_plane.allgather(
                None, timeout_ms=int(timeout_s * 1000)
            )
            return
        from jax.experimental import multihost_utils

        # sync_global_devices asserts the name matches across processes;
        # SPMD processes hit barriers in the same order, so a class-level
        # sequence number is stable where id(self) would not be.
        CommunicatorBase._barrier_seq += 1
        multihost_utils.sync_global_devices(
            f"chainermn_tpu_barrier_{CommunicatorBase._barrier_seq}"
        )

    # ------------------------------------------------------------------
    def split(self, color_or_axes, key: int = 0):
        """Sub-communicator: ``MPI_Comm_split`` in both of its shapes.

        ``split(color, key=...)`` — the reference's arbitrary-subgroup
        semantics (REF:chainermn/communicators/mpi_communicator_base.py
        ``split(color, key)``): every member process calls with ITS color
        and key; processes sharing a color form a new communicator whose
        ranks are ordered by ``(key, old_rank)``.  ``color=None`` is
        MPI_UNDEFINED — the process participates in the split but gets
        ``None`` back.  The sub-communicator's mesh holds only the member
        processes' devices (inter = members, intra = local devices), and
        its object plane is namespaced to the subgroup.

        ``split(('intra',))`` — axis shape: a sub-communicator over a
        subset of THIS mesh's axes (a DP+PP run splitting per-axis
        sub-communicators, as the reference's seq2seq+DP examples split
        MPI_COMM_WORLD).  Variants whose collective pattern needs both
        ``inter`` and ``intra`` (hierarchical, two_dimensional) degrade to
        the flat single-collective communicator when split to one axis —
        as the reference's sub-communicators lose the node hierarchy too.
        """
        if color_or_axes is None or isinstance(
            color_or_axes, (int, np.integer)
        ):
            return self._split_color(color_or_axes, key)
        return self._split_axes(tuple(color_or_axes))

    def _split_axes(self, axes: tuple) -> "CommunicatorBase":
        # A failed variant construction may already have advanced the
        # SPMD plane ordinal; restore it so the degrade retry lands on
        # the SAME ordinal on every process.
        count = CommunicatorBase._plane_count
        try:
            return type(self)(
                self.mesh, axes=axes,
                allreduce_grad_dtype=self.allreduce_grad_dtype,
                host_members=self._hp_members,
                bucket_bytes=self.bucket_bytes,
                overlap=self.overlap,
                overlap_granularity=self.overlap_granularity,
                comm_dtype=self.comm_dtype,
            )
        except ValueError:
            CommunicatorBase._plane_count = count
            from .xla_ici import XlaIciCommunicator

            return XlaIciCommunicator(
                self.mesh, axes=axes,
                allreduce_grad_dtype=self.allreduce_grad_dtype,
                host_members=self._hp_members,
                bucket_bytes=self.bucket_bytes,
                overlap=self.overlap,
                overlap_granularity=self.overlap_granularity,
                comm_dtype=self.comm_dtype,
            )

    def split_devices(self, colors, keys=None) -> dict:
        """Device-plane ``MPI_Comm_split``: partition THIS communicator's
        DEVICES into sub-communicators by color.

        Single-controller form of the reference's arbitrary-subgroup
        split: one process speaks for all its devices, so instead of "each
        rank passes its color" the caller passes ``colors`` — a sequence
        of length ``device_size`` indexed by flat device rank (row-major
        over ``self.axes``, i.e. :meth:`device_for_rank` order) — and
        receives ``{color: communicator}`` covering every color at once.
        ``keys`` (same length) orders each subgroup (ties by old rank);
        ``None`` colors are MPI_UNDEFINED (device in no subgroup).  This
        expresses what the axis split cannot: "every 4th device", or a
        data-parallel subgroup inside one pipeline stage.

        Each sub-communicator's mesh is 1-D over its devices (axis
        ``intra`` — one collective leg, ICI-resident when the devices
        share a host).  A color whose devices span processes gets those
        processes as its host plane; a color with no devices on THIS
        process maps to ``None`` (MPI_COMM_NULL).
        """
        n = self.device_size
        colors = list(colors)
        if len(colors) != n:
            raise ValueError(
                f"colors must have length device_size={n}, got {len(colors)}"
            )
        keys = list(keys) if keys is not None else [0] * n
        if len(keys) != n:
            raise ValueError(
                f"keys must have length device_size={n}, got {len(keys)}"
            )
        groups: dict = {}
        for r in range(n):
            if colors[r] is None:
                continue
            groups.setdefault(colors[r], []).append(
                (keys[r], r, self.device_for_rank(r))
            )
        from .xla_ici import XlaIciCommunicator

        out: dict = {}
        # Deterministic construction order (SPMD).  Colors are unrestricted
        # by the API — mixed types must not raise sorted()'s unordered-types
        # TypeError, and the key must be identical on EVERY process (a
        # repr()-based key would embed id() for default-repr objects and
        # desynchronize plane ordinals across ranks).  Each group's lowest
        # member flat-rank is total, collision-free, and process-invariant.
        for c in sorted(
            groups, key=lambda c: min(r for _k, r, _d in groups[c])
        ):
            lst = sorted(groups[c], key=lambda t: (t[0], t[1]))
            devs = [d for _k, _r, d in lst]
            procs = sorted({d.process_index for d in devs})
            if jax.process_index() not in procs:
                # MPI_COMM_NULL for this process — but keep the plane
                # ordinal advancing in lockstep with constructing ranks.
                CommunicatorBase._plane_count += 1
                out[c] = None
                continue
            submesh = Mesh(
                np.array(devs, dtype=object), (mesh_utils.AXIS_INTRA,)
            )
            out[c] = XlaIciCommunicator(
                submesh,
                allreduce_grad_dtype=self.allreduce_grad_dtype,
                host_members=procs,
                bucket_bytes=self.bucket_bytes,
                overlap=self.overlap,
                overlap_granularity=self.overlap_granularity,
                comm_dtype=self.comm_dtype,
            )
        return out

    def _split_color(self, color, key: int):
        """Process-plane MPI_Comm_split.  A collective over THIS
        communicator: every member must call it (SPMD), colors partition
        the members, keys order the subgroup (ties by old rank)."""
        trips = self.allgather_obj(
            (None if color is None else int(color), int(key), self.rank)
        )
        if color is None:
            # MPI_UNDEFINED: no communicator — but the plane ordinal must
            # still advance in lockstep with the processes that DO
            # construct one, or every later communicator's namespace
            # diverges across processes.
            CommunicatorBase._plane_count += 1
            return None
        mine = sorted(
            (k, r) for c, k, r in trips if c == int(color)
        )
        sub_ranks = [r for _k, r in mine]  # ranks WITHIN this comm
        # Translate to global process indices (wire identities).
        to_global = (
            (lambda r: self._hp_members[r])
            if self._hp_members is not None
            else (lambda r: r)
        )
        members = [to_global(r) for r in sub_ranks]
        # Sub-mesh: the member processes' devices from THIS mesh, one
        # inter row per member (ordered by subgroup rank), intra = each
        # process's local devices in mesh order.
        if len(members) == self.size and members == [
            to_global(r) for r in range(self.size)
        ]:
            submesh = self.mesh  # whole group, original order
        else:
            rows = []
            mesh_devs = list(self.mesh.devices.flat)
            for g in members:
                row = [d for d in mesh_devs if d.process_index == g]
                rows.append(row)
            n_local = len(rows[0])
            if any(len(r) != n_local for r in rows):
                raise ValueError(
                    "split(color) needs equal local device counts across "
                    f"members; got {[len(r) for r in rows]}"
                )
            submesh = Mesh(
                np.array(rows, dtype=object),
                (mesh_utils.AXIS_INTER, mesh_utils.AXIS_INTRA),
            )
        from .xla_ici import XlaIciCommunicator

        cls = type(self)
        # Snapshot the plane ordinal: a variant whose constraints the
        # subgroup shape cannot satisfy may raise AFTER incrementing it,
        # which would desynchronize this process's ordinals from the
        # color=None processes that advanced exactly once.
        count = CommunicatorBase._plane_count
        try:
            return cls(
                submesh,
                allreduce_grad_dtype=self.allreduce_grad_dtype,
                host_members=members,
                bucket_bytes=self.bucket_bytes,
                overlap=self.overlap,
                overlap_granularity=self.overlap_granularity,
                comm_dtype=self.comm_dtype,
            )
        except ValueError:
            CommunicatorBase._plane_count = count
            # Variant constraints (e.g. SingleHostCommunicator) that the
            # subgroup shape cannot satisfy degrade to the flat backend.
            return XlaIciCommunicator(
                submesh,
                allreduce_grad_dtype=self.allreduce_grad_dtype,
                host_members=members,
                bucket_bytes=self.bucket_bytes,
                overlap=self.overlap,
                overlap_granularity=self.overlap_granularity,
                comm_dtype=self.comm_dtype,
            )

    def __repr__(self):
        return (
            f"<{type(self).__name__} axes={self.axes} "
            f"devices={self.device_size} hosts={self.size}>"
        )
