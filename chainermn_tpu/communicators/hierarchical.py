"""Hierarchical communicator — intra-node reduce, inter-node allreduce,
intra-node broadcast.

Reference: REF:chainermn/communicators/hierarchical_communicator.py — the
3-phase allreduce: (1) NCCL ``reduce`` to the node-local leader GPU,
(2) ``MPI_Allreduce`` among node leaders via pinned host buffers,
(3) NCCL ``bcast`` back out.  The point was to keep the slow inter-node
(IB) leg to one participant per node.

TPU-native translation: phase structure becomes two chained ``lax.psum``
legs — first over the ``intra`` (ICI) axis, then over the ``inter`` (DCN)
axis.  There is no leader election or host staging: every chip participates
in the ``inter`` collective with an already-intra-reduced value, which is
the same math (reduce→allreduce→bcast ≡ psum∘psum) with strictly more
inter-leg bandwidth available (each chip's DCN share is used, not one
NIC per host) — the respect in which the TPU formulation dominates the
original rather than imitating it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import mesh_utils
from .base import CommunicatorBase


class HierarchicalCommunicator(CommunicatorBase):
    """``scatter_inter=False`` (default) is the faithful 3-phase
    translation: two chained full-size psums, so every chip ships the
    WHOLE buffer across the inter (DCN) axis — intra-reduced, but not
    sharded.  ``scatter_inter=True`` decomposes the intra leg into
    ``psum_scatter → psum(inter) → all_gather``: the same math (a psum is
    definitionally reduce-scatter + all-gather), but the inter hop now
    carries only ``1/intra_size`` of the bytes per chip — closing the
    inter-leg gap BENCH_r05 measured against two_dimensional (4 MiB vs
    512 KiB at intra=8) while keeping the per-leaf phase structure that
    distinguishes this variant from the flat-packed 2-D communicator."""

    name = "hierarchical"

    def __init__(self, mesh=None, axes=None, allreduce_grad_dtype=None,
                 host_members=None, bucket_bytes=None,
                 overlap=None, overlap_granularity=None,
                 comm_dtype=None, scatter_inter: bool = False):
        super().__init__(mesh, axes, allreduce_grad_dtype,
                         host_members=host_members,
                         bucket_bytes=bucket_bytes,
                         overlap=overlap,
                         overlap_granularity=overlap_granularity,
                         comm_dtype=comm_dtype)
        if mesh_utils.AXIS_INTRA not in self.axes or mesh_utils.AXIS_INTER not in self.axes:
            raise ValueError(
                "hierarchical communicator needs both 'inter' and 'intra' "
                f"mesh axes; got {self.axes}"
            )
        self.scatter_inter = bool(scatter_inter)

    def _allreduce_impl(self, tree):
        n = self.device_size
        if self.scatter_inter:
            return jax.tree.map(self._scatter_leg, tree)

        def leg(g):
            g = lax.psum(g, mesh_utils.AXIS_INTRA)   # NCCL reduce+bcast leg
            g = lax.psum(g, mesh_utils.AXIS_INTER)   # inter-node MPI leg
            return g / n

        return jax.tree.map(leg, tree)

    def _allreduce_sum_impl(self, buf):
        """The quantized path's sum-only leg: the same two chained psums
        (intra then inter — both exact on the narrow wire dtype thanks to
        quant.py's world-headroom scale), WITHOUT the inline mean — int8
        division would truncate; dequant applies the mean in f32.  The
        ``scatter_inter`` decomposition runs its reduce-scatter chain on
        the wire dtype directly (zero padding is exact in any dtype)."""
        if self.scatter_inter:
            k = self.intra_size
            n = buf.size
            pad = (-n) % k
            if pad:
                buf = jnp.concatenate(
                    [buf, jnp.zeros((pad,), buf.dtype)]
                )
            shard = lax.psum_scatter(
                buf, mesh_utils.AXIS_INTRA, scatter_dimension=0, tiled=True
            )
            shard = lax.psum(shard, mesh_utils.AXIS_INTER)
            full = lax.all_gather(
                shard, mesh_utils.AXIS_INTRA, axis=0, tiled=True
            )
            return full[:n]
        buf = lax.psum(buf, mesh_utils.AXIS_INTRA)
        return lax.psum(buf, mesh_utils.AXIS_INTER)

    def _scatter_leg(self, g):
        k = self.intra_size
        shape = g.shape
        flat = g.reshape(-1)
        size = flat.size
        pad = (-size) % k
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        shard = lax.psum_scatter(
            flat, mesh_utils.AXIS_INTRA, scatter_dimension=0, tiled=True
        )
        shard = lax.psum(shard, mesh_utils.AXIS_INTER)
        full = lax.all_gather(
            shard, mesh_utils.AXIS_INTRA, axis=0, tiled=True
        )
        return full[:size].reshape(shape) / self.device_size
