"""Hierarchical communicator — intra-node reduce, inter-node allreduce,
intra-node broadcast.

Reference: REF:chainermn/communicators/hierarchical_communicator.py — the
3-phase allreduce: (1) NCCL ``reduce`` to the node-local leader GPU,
(2) ``MPI_Allreduce`` among node leaders via pinned host buffers,
(3) NCCL ``bcast`` back out.  The point was to keep the slow inter-node
(IB) leg to one participant per node.

TPU-native translation: phase structure becomes two chained ``lax.psum``
legs — first over the ``intra`` (ICI) axis, then over the ``inter`` (DCN)
axis.  There is no leader election or host staging: every chip participates
in the ``inter`` collective with an already-intra-reduced value, which is
the same math (reduce→allreduce→bcast ≡ psum∘psum) with strictly more
inter-leg bandwidth available (each chip's DCN share is used, not one
NIC per host) — the respect in which the TPU formulation dominates the
original rather than imitating it.
"""

from __future__ import annotations

import jax
from jax import lax

from . import mesh_utils
from .base import CommunicatorBase


class HierarchicalCommunicator(CommunicatorBase):
    name = "hierarchical"

    def __init__(self, mesh=None, axes=None, allreduce_grad_dtype=None,
                 host_members=None):
        super().__init__(mesh, axes, allreduce_grad_dtype,
                         host_members=host_members)
        if mesh_utils.AXIS_INTRA not in self.axes or mesh_utils.AXIS_INTER not in self.axes:
            raise ValueError(
                "hierarchical communicator needs both 'inter' and 'intra' "
                f"mesh axes; got {self.axes}"
            )

    def _allreduce_impl(self, tree):
        n = self.device_size

        def leg(g):
            g = lax.psum(g, mesh_utils.AXIS_INTRA)   # NCCL reduce+bcast leg
            g = lax.psum(g, mesh_utils.AXIS_INTER)   # inter-node MPI leg
            return g / n

        return jax.tree.map(leg, tree)
