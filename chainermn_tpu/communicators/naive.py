"""Naive communicator — the correctness oracle.

Reference: REF:chainermn/communicators/naive_communicator.py, which issues
one host-memory ``MPI_Allreduce`` per parameter.  The TPU-native analogue
reduces each gradient leaf with its own ``lax.psum`` (no packing, no dtype
tricks) so XLA sees one collective per parameter — the simplest possible
lowering, and the backend every other variant must numerically match
(SURVEY §4: "NaiveCommunicator ... serves as the correctness oracle").

Runs anywhere, including the forced-host-platform CPU mesh the test suite
uses in place of the reference's ``mpiexec -n 2`` CI trick.
"""

from __future__ import annotations

import jax
from jax import lax

from .base import CommunicatorBase


class NaiveCommunicator(CommunicatorBase):
    name = "naive"

    def _allreduce_impl(self, tree):
        n = self.device_size
        return jax.tree.map(lambda g: lax.psum(g, self.axes) / n, tree)
