"""Mesh/topology bookkeeping — the TPU-native analogue of the reference's
communication utilities.

The reference (REF:chainermn/communicators/_communication_utility.py)
discovers topology with an MPI allgather of hostnames (``init_ranks``) and
builds intra-/inter-node sub-communicators with ``MPI_Comm_split``.  On TPU
the equivalent facts come from JAX itself: ``jax.devices()`` enumerates every
chip in the slice, ``jax.process_index()/process_count()`` give the host
topology, and a :class:`jax.sharding.Mesh` with an ``(inter, intra)`` axis
split plays the role of the reference's inter-/intra-node MPI communicators.
ICI collectives ride the ``intra`` axis; DCN-spanning collectives ride
``inter``.

There is no analogue of REF:chainermn/communicators/_memory_utility.py's
pinned-host/GPU pack buffers: XLA owns device memory and fuses the
pack/allreduce/unpack pipeline itself.  The packing *strategy* of the
``flat``/``pure_nccl`` communicators survives as an explicit flatten-concat
in :mod:`chainermn_tpu.communicators.xla_ici`.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_INTER = "inter"  # DCN / host-spanning axis (reference: inter-node MPI comm)
AXIS_INTRA = "intra"  # ICI / within-host axis (reference: intra-node NCCL comm)


def build_mesh(
    inter_size: int | None = None,
    intra_size: int | None = None,
    devices: Sequence[jax.Device] | None = None,
    axis_names: tuple[str, str] = (AXIS_INTER, AXIS_INTRA),
) -> Mesh:
    """Build the 2-D ``(inter, intra)`` device mesh every communicator runs on.

    Mirrors ``init_ranks`` + ``init_intra_mpi_comm`` + ``init_inter_mpi_comm``
    in REF:chainermn/communicators/_communication_utility.py: the ``inter``
    axis corresponds to the node dimension (one entry per host, DCN between
    them) and ``intra`` to the chips within a host (ICI between them).

    On a real multi-host slice the default is ``inter = process_count`` and
    ``intra = local chips per host``.  For single-process testing (the
    analogue of the reference's ``mpiexec -n 2`` on one box, SURVEY §4) any
    factorization of the device count may be forced, e.g.
    ``build_mesh(inter_size=2, intra_size=4)`` on 8 virtual CPU devices to
    exercise both collective legs of the hierarchical/2-D algorithms.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)

    if inter_size is None and intra_size is None:
        inter_size = jax.process_count()
    if inter_size is None:
        assert intra_size is not None
        inter_size = n // intra_size
    if intra_size is None:
        intra_size = n // inter_size
    if inter_size * intra_size != n:
        raise ValueError(
            f"mesh shape ({inter_size}, {intra_size}) does not cover "
            f"{n} devices"
        )

    # Order devices so that each `inter` row holds one host's chips — this is
    # what keeps `intra`-axis collectives on ICI.  jax.devices() is already
    # process-major, matching the reference's hostname-sorted rank layout.
    grid = np.array(devices).reshape(inter_size, intra_size)
    return Mesh(grid, axis_names)


def axis_size_traced(name: str) -> int:
    """Static size of a mesh axis from inside ``shard_map``.

    ``jax.lax.axis_size`` only exists in newer jax releases; the portable
    spelling is ``psum`` of the Python constant 1 over the axis, which
    constant-folds to the axis size (an ``int``) without emitting a
    collective.
    """
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(name)
    return jax.lax.psum(1, name)


def flat_rank(axes: Sequence[str]):
    """Traced flattened rank over ``axes`` — usable inside ``shard_map``.

    The analogue of the reference's ``comm.rank`` in its SPMD per-process
    view (REF:chainermn/communicators/communicator_base.py).  Row-major over
    the given axes, so with ``axes=('inter','intra')`` rank order matches
    the reference's hostname-major global rank order.
    """
    idx = jax.lax.axis_index(axes[0])
    for name in axes[1:]:
        idx = idx * axis_size_traced(name) + jax.lax.axis_index(name)
    return idx


def axes_size(mesh: Mesh, axes: Sequence[str]) -> int:
    return math.prod(mesh.shape[a] for a in axes)
