"""Single-host communicator — ICI-only collectives.

Reference: REF:chainermn/communicators/single_node_communicator.py, which
asserts ``size == intra_size`` and runs NCCL-only allreduce within the node.
The TPU analogue restricts collectives to the ``intra`` (ICI) axis and
refuses to construct over a multi-host mesh, so a user gets a loud error
instead of silent DCN traffic — the same contract as the reference's
assertion.
"""

from __future__ import annotations

import jax
from jax import lax

from . import mesh_utils
from .base import CommunicatorBase


class SingleHostCommunicator(CommunicatorBase):
    name = "single_host"

    def __init__(self, mesh=None, axes=None, allreduce_grad_dtype=None,
                 host_members=None, bucket_bytes=None,
                 overlap=None, overlap_granularity=None, comm_dtype=None):
        super().__init__(mesh, axes, allreduce_grad_dtype,
                         host_members=host_members,
                         bucket_bytes=bucket_bytes,
                         overlap=overlap,
                         overlap_granularity=overlap_granularity,
                         comm_dtype=comm_dtype)
        if self.inter_size != 1 and mesh_utils.AXIS_INTER in self.axes:
            raise ValueError(
                "single_host communicator requires inter_size == 1 "
                f"(got {self.inter_size}); use 'hierarchical'/'xla_ici' "
                "for multi-host meshes"
            )

    def _allreduce_impl(self, tree):
        n = self.device_size
        return jax.tree.map(
            lambda g: lax.psum(g, self.axes) / n, tree
        )


# Reference alias: 'single_node'.
class SingleNodeCommunicator(SingleHostCommunicator):
    name = "single_node"
