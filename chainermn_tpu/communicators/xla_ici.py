"""Flat single-collective communicator — the ``pure_nccl``/``flat`` analogue.

Reference lineage:

* REF:chainermn/communicators/flat_communicator.py — pack every gradient
  into ONE contiguous GPU buffer, one ``MPI_Allreduce`` over it, unpack.
* REF:chainermn/communicators/pure_nccl_communicator.py — same flat buffer
  but a single ``ncclAllReduce`` across all ranks on a dedicated stream,
  with an optional fp16 cast-pack (``allreduce_grad_dtype``).

TPU-native translation: flatten + concatenate the gradient pytree into one
1-D buffer and issue a single ``lax.psum`` over the whole mesh.  XLA lowers
this to one fused all-reduce riding ICI (and DCN for the ``inter`` axis hops
on multi-host meshes) — the same "one big collective amortizes latency"
strategy that made ``pure_nccl`` the reference's fastest backend, which is
why BASELINE.json maps it to the ``xla_ici`` name.  The optional
low-precision leg uses bfloat16 (TPU's native low-precision format) instead
of the reference's fp16.

There is no explicit stream management: XLA's async collectives already
overlap the allreduce with surrounding compute where data dependence allows
(SURVEY §7.6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .base import CommunicatorBase


def pack(tree):
    """Flatten a pytree into (one 1-D buffer, unpack closure).

    The analogue of ``pack_params`` in
    REF:chainermn/communicators/_memory_utility.py — except XLA owns the
    copies, so this is a trace-time concatenation the compiler fuses with
    the collective rather than a runtime memcpy loop.
    """
    leaves, treedef = jax.tree.flatten(tree)
    flat = jnp.concatenate([jnp.ravel(l) for l in leaves]) if leaves else jnp.zeros((0,))

    shapes = [l.shape for l in leaves]
    sizes = [l.size for l in leaves]

    def unpack(buf):
        out, off = [], 0
        for shape, size in zip(shapes, sizes):
            out.append(jnp.reshape(buf[off : off + size], shape))
            off += size
        return jax.tree.unflatten(treedef, out)

    return flat, unpack


class XlaIciCommunicator(CommunicatorBase):
    name = "xla_ici"

    def _allreduce_impl(self, tree):
        leaves = jax.tree.leaves(tree)
        if not leaves:
            return tree
        # Pack in a common dtype (cast already applied by allreduce_grad
        # when allreduce_grad_dtype is set; otherwise promote to the widest
        # leaf dtype so the single fused collective is well-typed).
        common = jnp.result_type(*[l.dtype for l in leaves])
        casted = jax.tree.map(lambda x: x.astype(common), tree)
        flat, unpack = pack(casted)
        flat = lax.psum(flat, self.axes) / self.device_size
        out = unpack(flat)
        return jax.tree.map(lambda x, ref: x.astype(ref.dtype), out, tree)


# ``flat`` is the CUDA-aware-MPI spelling of the same algorithm in the
# reference; expose it as an alias class so create_communicator('flat')
# resolves (SURVEY §2.1).
class FlatCommunicator(XlaIciCommunicator):
    name = "flat"
