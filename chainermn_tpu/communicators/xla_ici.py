"""Flat single-collective communicator — the ``pure_nccl``/``flat`` analogue.

Reference lineage:

* REF:chainermn/communicators/flat_communicator.py — pack every gradient
  into ONE contiguous GPU buffer, one ``MPI_Allreduce`` over it, unpack.
* REF:chainermn/communicators/pure_nccl_communicator.py — same flat buffer
  but a single ``ncclAllReduce`` across all ranks on a dedicated stream,
  with an optional fp16 cast-pack (``allreduce_grad_dtype``).

TPU-native translation: flatten + concatenate the gradient pytree into one
1-D buffer and issue a single ``lax.psum`` over the whole mesh.  XLA lowers
this to one fused all-reduce riding ICI (and DCN for the ``inter`` axis hops
on multi-host meshes) — the same "one big collective amortizes latency"
strategy that made ``pure_nccl`` the reference's fastest backend, which is
why BASELINE.json maps it to the ``xla_ici`` name.  The optional
low-precision leg uses bfloat16 (TPU's native low-precision format) instead
of the reference's fp16.

There is no explicit stream management: XLA's async collectives already
overlap the allreduce with surrounding compute where data dependence allows
(SURVEY §7.6).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .base import CommunicatorBase

# The flatten/concat core now lives in packing.py (shared with the
# bucketed allreduce_grad path and the ZeRO flat-master buffers in
# chainermn_tpu.optimizers); this name stays as the import surface.
from .packing import pack_tree as pack


class XlaIciCommunicator(CommunicatorBase):
    name = "xla_ici"

    def _allreduce_impl(self, tree):
        leaves = jax.tree.leaves(tree)
        if not leaves:
            return tree
        # Pack in a common dtype (cast already applied by allreduce_grad
        # when allreduce_grad_dtype is set; otherwise promote to the widest
        # leaf dtype so the single fused collective is well-typed).
        common = jnp.result_type(*[l.dtype for l in leaves])
        casted = jax.tree.map(
            lambda x: x if x.dtype == common else x.astype(common), tree
        )
        flat, unpack = pack(casted)
        flat = lax.psum(flat, self.axes) / self.device_size
        out = unpack(flat)
        return jax.tree.map(
            lambda x, ref: x if x.dtype == ref.dtype else x.astype(ref.dtype),
            out, tree,
        )


# ``flat`` is the CUDA-aware-MPI spelling of the same algorithm in the
# reference; expose it as an alias class so create_communicator('flat')
# resolves (SURVEY §2.1).
class FlatCommunicator(XlaIciCommunicator):
    name = "flat"
