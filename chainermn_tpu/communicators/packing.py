"""Gradient packing — the flat-buffer fusion that made ``pure_nccl`` fast.

Reference lineage: REF:chainermn/communicators/_memory_utility.py
(``pack_params``/``unpack_params``) packed every gradient into one
contiguous GPU buffer so the backend issued ONE ``ncclAllReduce`` instead
of one per parameter.  PyTorch DDP generalized the same idea into capped
*buckets* (Li et al., VLDB 2020: "PyTorch Distributed") so the first
buckets can start reducing while later gradients are still materializing.

Two utilities live here:

* :func:`pack_tree` — the single-buffer flatten/concat the ``flat``/
  ``xla_ici`` communicator and the ZeRO flat-master paths in
  :mod:`chainermn_tpu.optimizers` share (one source of truth for the
  flatten order and the unpack arithmetic).
* :class:`GradPacker` — the bucketed form every communicator's
  ``allreduce_grad`` uses by default: the gradient pytree is split into
  contiguous per-dtype buckets capped at ``bucket_bytes``, each padded to
  a power-of-two element count (collective-friendly sizes, stable tune-
  cache buckets), and the communicator's characteristic allreduce runs
  once per bucket — O(n_buckets) collectives instead of O(n_leaves),
  with a lossless unpack (pure slicing, bit-exact).

Padding note: a bucket whose next power of two would overshoot the
``bucket_bytes`` cap (a single oversize leaf, or a near-full bucket) is
padded to a multiple of 128 elements instead — pow2-padding there could
waste up to 2x wire for no latency win.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: Default bucket cap.  4 MiB matches the fused single-buffer regime of
#: BENCH_r05's allreduce table (one collective saturates the link well
#: before this) while keeping the first bucket's launch early enough to
#: overlap with the tail of the backward pass.
DEFAULT_BUCKET_BYTES = 4 * 1024 * 1024

#: Environment escape hatch: overrides an unset ``bucket_bytes`` on every
#: communicator.  ``0`` disables bucketing (the legacy per-leaf path).
ENV_BUCKET_BYTES = "CHAINERMN_TPU_BUCKET_BYTES"

#: Non-pow2 buckets align to the TPU lane width instead.
LANE_ELEMS = 128


def _np_dtype(d) -> np.dtype:
    """``np.dtype`` that also resolves names numpy itself does not know
    (``"bfloat16"`` needs the ml_dtypes scalar type jax registers)."""
    try:
        return np.dtype(d)
    except TypeError:
        return np.dtype(getattr(jnp, str(d)))


def pack_tree(tree, pad_to: int | None = None):
    """Flatten a pytree into (one 1-D buffer, unpack closure).

    The analogue of ``pack_params`` in
    REF:chainermn/communicators/_memory_utility.py — except XLA owns the
    copies, so this is a trace-time concatenation the compiler fuses with
    the collective rather than a runtime memcpy loop.  ``pad_to`` appends
    zeros up to that element count (the ZeRO paths' divisible-by-world
    padding); ``unpack`` slices leaves from the prefix, so padding never
    round-trips into the tree.
    """
    leaves, treedef = jax.tree.flatten(tree)
    flat = (
        jnp.concatenate([jnp.ravel(l) for l in leaves])
        if leaves else jnp.zeros((0,))
    )
    shapes = [l.shape for l in leaves]
    sizes = [l.size for l in leaves]
    if pad_to is not None:
        if pad_to < flat.size:
            raise ValueError(
                f"pad_to={pad_to} smaller than packed size {flat.size}"
            )
        if pad_to > flat.size:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad_to - flat.size,), flat.dtype)]
            )

    def unpack(buf):
        out, off = [], 0
        for shape, size in zip(shapes, sizes):
            out.append(jnp.reshape(buf[off : off + size], shape))
            off += size
        return jax.tree.unflatten(treedef, out)

    return flat, unpack


def _padded_elems(elems: int, cap_elems: int) -> int:
    """Bucket padding rule: next power of two when that stays within the
    cap, else the next multiple of :data:`LANE_ELEMS`."""
    if elems == 0:
        return 0
    p = 1 << (elems - 1).bit_length()
    if p <= cap_elems:
        return p
    return elems + (-elems) % LANE_ELEMS


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One contiguous single-dtype slab of the packed gradient."""

    dtype: Any                       # np.dtype
    leaf_indices: Tuple[int, ...]    # into the tree's flatten order
    elems: int                       # payload elements (sum of leaf sizes)
    padded_elems: int                # buffer length actually reduced

    @property
    def payload_bytes(self) -> int:
        return self.elems * self.dtype.itemsize

    @property
    def padded_bytes(self) -> int:
        return self.padded_elems * self.dtype.itemsize

    @property
    def quantizable(self) -> bool:
        """Whether a ``comm_dtype`` wire applies to this bucket: float
        buckets (including the ml_dtypes extension floats, bf16/fp8)
        quantize; integer buckets ride at full precision."""
        return bool(jnp.issubdtype(self.dtype, jnp.floating))

    def wire_bytes(self, wire_itemsize: int | None = None) -> int:
        """Bytes this bucket actually moves per collective: the padded
        buffer at the wire dtype's width when quantized (plus the f32
        amax scale, one word per bucket), the padded storage bytes
        otherwise."""
        if wire_itemsize is None or not self.quantizable:
            return self.padded_bytes
        return self.padded_elems * wire_itemsize + 4


class GradPacker:
    """Bucketed pack/unpack plan for one gradient pytree structure.

    The plan is computed from leaf metadata only (treedef + shapes +
    dtypes) and is deterministic: leaves are grouped by dtype (groups in
    first-appearance order, leaves within a group in flatten order) and
    greedily filled into buckets capped at ``bucket_bytes`` of payload.
    A bucket always takes at least one leaf, so a single leaf larger than
    the cap becomes its own oversize bucket rather than an error.

    ``pack`` concatenates each bucket's raveled leaves (plus zero
    padding) into one 1-D buffer per bucket; ``unpack`` slices them back
    out — pure layout moves, so ``unpack(pack(tree)) == tree`` bit-for-
    bit, and any elementwise-linear collective applied between the two
    (psum, psum-scatter/all-gather) commutes with the packing exactly.
    """

    def __init__(
        self,
        treedef,
        shapes: Sequence[tuple],
        dtypes: Sequence[Any],
        bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    ):
        if bucket_bytes <= 0:
            raise ValueError(
                f"bucket_bytes must be positive, got {bucket_bytes} "
                "(use the unbucketed path to disable bucketing)"
            )
        self.treedef = treedef
        self.shapes = [tuple(s) for s in shapes]
        self.dtypes = [_np_dtype(d) for d in dtypes]
        self.sizes = [int(np.prod(s, dtype=np.int64)) for s in self.shapes]
        self.bucket_bytes = int(bucket_bytes)

        groups: dict[np.dtype, list[int]] = {}
        for i, dt in enumerate(self.dtypes):
            groups.setdefault(dt, []).append(i)

        buckets: List[Bucket] = []
        for dt, idxs in groups.items():
            cap_elems = max(1, self.bucket_bytes // dt.itemsize)
            cur: list[int] = []
            cur_elems = 0
            for i in idxs:
                if cur and cur_elems + self.sizes[i] > cap_elems:
                    buckets.append(Bucket(
                        dt, tuple(cur), cur_elems,
                        _padded_elems(cur_elems, cap_elems),
                    ))
                    cur, cur_elems = [], 0
                cur.append(i)
                cur_elems += self.sizes[i]
            if cur:
                buckets.append(Bucket(
                    dt, tuple(cur), cur_elems,
                    _padded_elems(cur_elems, cap_elems),
                ))
        self.buckets: Tuple[Bucket, ...] = tuple(buckets)

    @classmethod
    def for_tree(cls, tree, bucket_bytes: int = DEFAULT_BUCKET_BYTES):
        leaves, treedef = jax.tree.flatten(tree)
        return cls(
            treedef,
            [l.shape for l in leaves],
            [l.dtype for l in leaves],
            bucket_bytes,
        )

    # -- plan introspection -------------------------------------------
    @property
    def n_leaves(self) -> int:
        return len(self.shapes)

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    @property
    def payload_bytes(self) -> int:
        return sum(b.payload_bytes for b in self.buckets)

    @property
    def padded_bytes(self) -> int:
        return sum(b.padded_bytes for b in self.buckets)

    def wire_bytes(self, comm_dtype=None) -> int:
        """Total bytes one allreduce moves per rank: padded storage
        bytes at full precision, or each quantizable bucket at the
        resolved wire dtype's width (``comm_dtype``: a canonical name
        from :mod:`chainermn_tpu.communicators.quant`) — the number
        bench's A/B column reports against the bf16 baseline."""
        wire_itemsize = None
        if comm_dtype is not None:
            from . import quant

            wire_dt = quant.wire_dtype(comm_dtype)
            if wire_dt is not None:
                wire_itemsize = jnp.dtype(wire_dt).itemsize
        return sum(b.wire_bytes(wire_itemsize) for b in self.buckets)

    def describe(self, comm_dtype=None) -> dict:
        """JSON-friendly plan summary (what benches and the Reporter
        counters publish).  ``comm_dtype`` (canonical quant name) adds
        the low-precision wire accounting per bucket."""
        wire_itemsize = None
        if comm_dtype is not None:
            from . import quant

            wire_dt = quant.wire_dtype(comm_dtype)
            if wire_dt is not None:
                wire_itemsize = jnp.dtype(wire_dt).itemsize
        out = {
            "bucket_bytes": self.bucket_bytes,
            "n_leaves": self.n_leaves,
            "n_buckets": self.n_buckets,
            "payload_bytes": self.payload_bytes,
            "padded_bytes": self.padded_bytes,
            "buckets": [
                {
                    "dtype": b.dtype.name,
                    "n_leaves": len(b.leaf_indices),
                    "elems": b.elems,
                    "padded_elems": b.padded_elems,
                    "padded_bytes": b.padded_bytes,
                }
                for b in self.buckets
            ],
        }
        if wire_itemsize is not None:
            out["comm_dtype"] = comm_dtype
            out["wire_bytes"] = self.wire_bytes(comm_dtype)
            for spec, b in zip(out["buckets"], self.buckets):
                spec["quantized"] = b.quantizable
                spec["wire_bytes"] = b.wire_bytes(wire_itemsize)
        return out

    # -- pack / unpack ------------------------------------------------
    def _check_tree(self, tree):
        leaves, treedef = jax.tree.flatten(tree)
        if treedef != self.treedef:
            raise ValueError(
                f"tree structure {treedef} does not match the packing "
                f"plan's {self.treedef}"
            )
        for i, l in enumerate(leaves):
            if tuple(l.shape) != self.shapes[i] or _np_dtype(l.dtype) != self.dtypes[i]:
                raise ValueError(
                    f"leaf {i} is {l.shape}/{l.dtype}, plan expects "
                    f"{self.shapes[i]}/{self.dtypes[i]}"
                )
        return leaves

    def pack(self, tree) -> List[jax.Array]:
        """Pytree → one 1-D buffer per bucket (padded with zeros)."""
        leaves = self._check_tree(tree)
        return [self.pack_bucket(leaves, i) for i in range(self.n_buckets)]

    def pack_bucket(self, leaves: Sequence[jax.Array], index: int) -> jax.Array:
        """One bucket's buffer from the tree's flattened leaves.

        The per-bucket form of :meth:`pack` the overlapped emission
        schedule (:mod:`chainermn_tpu.communicators.overlap`) uses:
        packing bucket-by-bucket keeps each collective's dependence
        frontier at exactly its member leaves, so the compiler may start
        it while other leaves' gradients are still being produced.
        ``leaves`` must already be in the plan's flatten order (use
        :meth:`_check_tree` / ``jax.tree.flatten`` on the full tree).
        """
        b = self.buckets[index]
        parts = [jnp.ravel(leaves[i]) for i in b.leaf_indices]
        pad = b.padded_elems - b.elems
        if pad:
            parts.append(jnp.zeros((pad,), dtype=b.dtype))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def unpack(self, bufs: Sequence[jax.Array]):
        """Bucket buffers → pytree (inverse of :meth:`pack`; padding is
        discarded)."""
        if len(bufs) != self.n_buckets:
            raise ValueError(
                f"got {len(bufs)} buffers for {self.n_buckets} buckets"
            )
        out = [None] * self.n_leaves
        for b, buf in zip(self.buckets, bufs):
            if buf.size != b.padded_elems:
                raise ValueError(
                    f"buffer has {buf.size} elems, bucket expects "
                    f"{b.padded_elems}"
                )
            off = 0
            for i in b.leaf_indices:
                out[i] = jnp.reshape(
                    buf[off : off + self.sizes[i]], self.shapes[i]
                )
                off += self.sizes[i]
        return jax.tree.unflatten(self.treedef, out)


def synthetic_grad_tree(
    n_leaves: int,
    total_bytes: int,
    dtypes: Sequence[Any] = ("float32", "bfloat16"),
) -> dict:
    """Deterministic mixed-shape / mixed-dtype gradient pytree.

    The shared shape-maker behind the ``allreduce_tree`` bench, the
    bucket tuner, and the census golden test — one definition so their
    "64-leaf mixed-shape tree" is the same tree.  Leaf 0 is a scalar,
    every 5th leaf is 2-D, dtypes round-robin, and sizes follow a cycling
    weight so buckets straddle leaf boundaries.  Values are exact in
    bfloat16 (multiples of 1/32 below 8) so low-precision round trips
    stay bit-stable.
    """
    dts = [_np_dtype(d) for d in dtypes]
    weights = [(i % 7) + 1 for i in range(n_leaves)]
    wsum = sum(weights) or 1
    tree = {}
    for i in range(n_leaves):
        dt = dts[i % len(dts)]
        if i == 0:
            shape: tuple = ()
        else:
            elems = max(1, int(total_bytes * weights[i] / wsum) // dt.itemsize)
            if i % 5 == 0 and elems % 2 == 0:
                shape = (elems // 2, 2)
            else:
                shape = (elems,)
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        vals = (np.arange(size, dtype=np.float32) % 97) / 32.0 + (i % 13) / 8.0
        tree[f"leaf_{i:03d}"] = vals.reshape(shape).astype(dt)
    return tree
