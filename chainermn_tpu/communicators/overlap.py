"""Backward-overlapped bucket schedule — hide gradient comms in the bwd pass.

PyTorch DDP's headline optimization (Li et al., VLDB 2020) launches each
gradient bucket's allreduce as soon as its last member gradient is
produced, so communication for the early buckets rides under the
remaining backward compute.  The reference stack approximated this with
its ``double_buffering`` optimizer (overlap by one full step of
staleness); here the overlap is *exact* — same-step gradients, zero
staleness — because under XLA the mechanism is dependence structure, not
threads:

1. **Schedule** (:func:`build_overlap_schedule`): emit each bucket's
   pack + allreduce in *reverse leaf-production order*.  Reverse-mode
   autodiff materializes gradients roughly in reverse forward order, so
   the leaves at the END of the flatten order get their grads first —
   emitting the last bucket's collective first hands the compiler a
   collective whose operands are ready while earlier layers' backward
   compute is still pending.  Each bucket's collective depends only on
   its own member leaves (per-bucket pack, not pack-everything-first),
   keeping the dependence frontier minimal.
2. **Async lowering** (:data:`OVERLAP_XLA_FLAGS`): the curated flag set
   that makes the TPU compiler split eligible collectives into
   ``all-reduce-start``/``all-reduce-done`` pairs and run the
   latency-hiding scheduler so independent backward compute lands
   between them.  The flags only matter on real TPU backends; the
   schedule itself is platform-neutral and bit-exact everywhere (the
   per-bucket math is identical to the eager path — only trace order
   changes, and fp addition inside each bucket is untouched).

Escape hatch: ``CHAINERMN_TPU_OVERLAP=0`` restores the eager
pack-all-then-reduce-all emission.  The schedule's granularity (buckets
fused per emission stage) x ``bucket_bytes`` is an autotune dimension —
see ``chainermn_tpu.tuning`` (``tune_overlap_schedule``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Tuple

#: Environment escape hatch: ``0``/``false``/``off`` disables the
#: overlapped emission schedule on every communicator (eager path).
#: Unset or anything truthy keeps it ON — the default.
ENV_OVERLAP = "CHAINERMN_TPU_OVERLAP"

#: Environment override for the schedule granularity (buckets emitted
#: per stage); unset resolves ctor -> tuned -> 1 (finest overlap).
ENV_OVERLAP_GRANULARITY = "CHAINERMN_TPU_OVERLAP_GRANULARITY"

DEFAULT_GRANULARITY = 1

#: Curated XLA flag set for async collectives + latency hiding on TPU.
#: These make the compiler (a) split all-reduce/all-gather/
#: collective-permute into start/done pairs, (b) fuse the async pairs
#: with surrounding loops where legal, and (c) run the latency-hiding
#: scheduler so independent backward compute is placed between start and
#: done.  They are TPU-compiler flags: harmless to *carry* in XLA_FLAGS
#: on CPU runs of the same script, but only applied by
#: :func:`ensure_overlap_flags` when a TPU backend is plausibly in play
#: (or ``force=True``), because mutating XLA_FLAGS after backend init is
#: a silent no-op and unknown flags can abort older jaxlibs.
OVERLAP_XLA_FLAGS: Tuple[str, ...] = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_reduce=true",
    "--xla_enable_async_collective_permute=true",
)


def overlap_enabled(default: bool = True) -> bool:
    """The :data:`ENV_OVERLAP` gate: unset -> ``default`` (ON);
    ``0``/``false``/``off``/``no`` -> False; anything else -> True."""
    raw = os.environ.get(ENV_OVERLAP, "").strip().lower()
    if not raw:
        return default
    return raw not in ("0", "false", "off", "no")


def resolve_granularity(default: int = DEFAULT_GRANULARITY) -> int:
    """The :data:`ENV_OVERLAP_GRANULARITY` override, clamped to >= 1."""
    raw = os.environ.get(ENV_OVERLAP_GRANULARITY, "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return max(1, int(default))


@dataclasses.dataclass(frozen=True)
class OverlapSchedule:
    """Emission plan over a :class:`~.packing.GradPacker`'s buckets.

    ``stages`` lists bucket indices in emission order, grouped into
    stages of ``granularity`` buckets each: within a stage every
    bucket's pack is emitted before any of the stage's collectives
    (coarser stages give the compiler bigger fusion windows; stage size
    1 launches each collective at its earliest ready point).  The stage
    grouping never changes *which* collectives run or their per-bucket
    operands — it is pure trace order, hence bit-exact vs eager.
    """

    stages: Tuple[Tuple[int, ...], ...]
    granularity: int

    @property
    def order(self) -> Tuple[int, ...]:
        """Flat bucket emission order."""
        return tuple(i for stage in self.stages for i in stage)

    @property
    def n_buckets(self) -> int:
        return sum(len(s) for s in self.stages)

    @property
    def n_stages(self) -> int:
        return len(self.stages)

    def describe(self) -> dict:
        return {
            "granularity": self.granularity,
            "n_stages": self.n_stages,
            "n_buckets": self.n_buckets,
            "order": list(self.order),
        }


def build_overlap_schedule(
    packer, granularity: int = DEFAULT_GRANULARITY
) -> OverlapSchedule:
    """Reverse leaf-production emission order for ``packer``'s buckets.

    Buckets are ordered by their *last* member leaf (descending): a
    bucket is ready when its final leaf's gradient exists, and
    reverse-mode AD produces later-flatten-order leaves' grads first.
    Per-dtype grouping can interleave buckets' leaf ranges, so the sort
    key is the readiness leaf, not the bucket's plan position.  Ties
    (identical last-leaf — impossible for a well-formed plan, but cheap
    to pin) break by descending bucket index for determinism.
    """
    g = max(1, int(granularity))
    order: List[int] = sorted(
        range(len(packer.buckets)),
        key=lambda i: (max(packer.buckets[i].leaf_indices), i),
        reverse=True,
    )
    stages = tuple(
        tuple(order[i : i + g]) for i in range(0, len(order), g)
    )
    return OverlapSchedule(stages=stages, granularity=g)


def _tpu_plausible() -> bool:
    """Whether this process could be headed for a TPU backend, WITHOUT
    initializing one (checking ``jax.devices()`` here would freeze the
    backend before the flags land)."""
    plat = os.environ.get("JAX_PLATFORMS", "").lower()
    if plat:
        return "tpu" in plat
    return bool(
        os.environ.get("TPU_NAME")
        or os.environ.get("TPU_WORKER_ID")
        or os.path.exists("/dev/accel0")
        or os.path.exists("/dev/vfio")
    )


def ensure_overlap_flags(force: bool = False) -> List[str]:
    """Idempotently append :data:`OVERLAP_XLA_FLAGS` to ``XLA_FLAGS``.

    Returns the flags newly added (empty when already present, when
    overlap is disabled via :data:`ENV_OVERLAP`, or when no TPU backend
    is plausibly in play and ``force`` is False).  Call this BEFORE the
    first jax backend touch — XLA reads the variable once at init.
    """
    if not overlap_enabled():
        return []
    if not force and not _tpu_plausible():
        return []
    current = os.environ.get("XLA_FLAGS", "")
    have = set(current.split())
    added = [f for f in OVERLAP_XLA_FLAGS if f not in have]
    if added:
        os.environ["XLA_FLAGS"] = " ".join(
            ([current] if current else []) + added
        )
    return added
