"""Two-dimensional communicator — reduce-scatter / inter-allreduce /
all-gather.

Reference: REF:chainermn/communicators/two_dimensional_communicator.py —
(1) intra-node NCCL ``reduceScatter`` so each GPU owns 1/intra_size of the
gradient, (2) inter-node ``MPI_Allreduce`` on each shard (every GPU's NIC
share in play, unlike hierarchical), (3) intra-node NCCL ``allGather``.
This is the "hierarchical 2D allreduce" named in BASELINE.json's
Transformer-WMT config.

TPU-native translation, leaf-fused for one collective group per step: pack
the gradient pytree into one flat buffer (same packing as the flat/xla_ici
backend), pad to a multiple of ``intra_size``, then
``lax.psum_scatter`` over ``intra`` (ICI) → ``lax.psum`` over ``inter``
(DCN) on the 1/intra_size shard → ``lax.all_gather`` over ``intra``.
The DCN leg moves only ``1/intra_size`` of the bytes per chip — exactly the
bandwidth argument the reference's 2-D scheme made for IB, transplanted to
the ICI/DCN hierarchy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import mesh_utils
from .base import CommunicatorBase
from .packing import pack_tree as pack


class TwoDimensionalCommunicator(CommunicatorBase):
    name = "two_dimensional"

    def __init__(self, mesh=None, axes=None, allreduce_grad_dtype=None,
                 host_members=None, bucket_bytes=None,
                 overlap=None, overlap_granularity=None, comm_dtype=None):
        super().__init__(mesh, axes, allreduce_grad_dtype,
                         host_members=host_members,
                         bucket_bytes=bucket_bytes,
                         overlap=overlap,
                         overlap_granularity=overlap_granularity,
                         comm_dtype=comm_dtype)
        if mesh_utils.AXIS_INTRA not in self.axes or mesh_utils.AXIS_INTER not in self.axes:
            raise ValueError(
                "two_dimensional communicator needs both 'inter' and 'intra' "
                f"mesh axes; got {self.axes}"
            )

    def _allreduce_sum_impl(self, buf):
        """Sum-only leg for the quantized path: the same reduce-scatter /
        inter-psum / all-gather chain on the narrow wire dtype (the
        world-headroom scale in quant.py keeps every partial sum in
        range; zero padding is exact in any dtype), WITHOUT the inline
        mean — dequant applies it in f32."""
        k = self.intra_size
        n = buf.size
        pad = (-n) % k
        if pad:
            buf = jnp.concatenate([buf, jnp.zeros((pad,), buf.dtype)])
        shard = lax.psum_scatter(
            buf, mesh_utils.AXIS_INTRA, scatter_dimension=0, tiled=True
        )
        shard = lax.psum(shard, mesh_utils.AXIS_INTER)
        full = lax.all_gather(shard, mesh_utils.AXIS_INTRA, axis=0, tiled=True)
        return full[:n]

    def _allreduce_impl(self, tree):
        leaves = jax.tree.leaves(tree)
        if not leaves:
            return tree
        common = jnp.result_type(*[l.dtype for l in leaves])
        casted = jax.tree.map(
            lambda x: x if x.dtype == common else x.astype(common), tree
        )
        flat, unpack = pack(casted)

        k = self.intra_size
        n = flat.size
        pad = (-n) % k
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])

        shard = lax.psum_scatter(
            flat, mesh_utils.AXIS_INTRA, scatter_dimension=0, tiled=True
        )
        shard = lax.psum(shard, mesh_utils.AXIS_INTER)
        full = lax.all_gather(shard, mesh_utils.AXIS_INTRA, axis=0, tiled=True)

        full = full[:n] / self.device_size
        out = unpack(full)
        return jax.tree.map(
            lambda x, ref: x if x.dtype == ref.dtype else x.astype(ref.dtype),
            out, tree,
        )
