"""Host-plane transport over the jax.distributed coordination-service KV
store — the TPU-native analogue of the reference's pickled-MPI transport.

The reference's ``MpiCommunicatorBase`` gives every *process* an eager,
point-to-point-capable object plane: ``send``/``recv`` of pickled payloads
between two ranks, and chunked collective object transport
(``chunked_bcast_obj``, REF:chainermn/communicators/_communication_utility.py)
that splits large pickles to respect MPI message-count limits.  JAX has no
MPI, but every multi-process JAX job already runs a coordination service
(the ``jax.distributed.initialize`` coordinator) whose distributed KV store
is reachable from all processes over DCN.  This module builds the same
transport primitives on it:

* ``put_payload``/``get_payload`` — a chunked header-written-last
  protocol.  Values are split into ``CHUNK_BYTES`` pieces (the
  coordination service is gRPC-backed; one huge value would trip
  message-size ceilings exactly the way one huge ``MPI_Bcast`` trips
  ``int`` count limits), the chunk RPCs are PIPELINED over a small
  thread pool (the KV round-trip is latency-bound; overlapping
  in-flight chunks converts per-chunk RTTs into a stream), and the
  header key is written *last*, so a reader blocking on the header never
  observes a partial write.
* **Typed ndarray fast path** — the reference's
  ``MpiCommunicatorBase.send/recv`` moved ndarrays as first-class typed
  buffers, not pickles.  Same here: a C-contiguous ``np.ndarray`` payload
  travels as raw buffer bytes with dtype/shape in the header — no pickle
  on either side, and the receiver's chunks land directly in the
  preallocated result array (no join/extra copy).  Everything else goes
  through pickle as before.
* single-reader keys are deleted by their reader; multi-reader keys are
  garbage-collected by the *last* reader, discovered with an atomic
  ``key_value_increment`` ack counter.

Keys are namespaced under ``chainermn_tpu/`` and carry a monotone
per-(edge, tag) sequence number maintained independently on each side.
Matched send/recv pairs advance their counters in lockstep (the same
SPMD-ordering contract MPI tags rely on), so no two in-flight transfers
ever share a key and stale keys cannot be re-read.

**Direct-socket bulk data plane** (:class:`SocketPlane`): the KV store is
a gRPC control plane — measured ~17 MB/s per-byte ceiling on bulk values
regardless of chunking/pipelining — so point-to-point payloads ride a
DIRECT TCP connection between the two processes instead, exactly as MPI's
eager/rendezvous protocol rides its own transport while the runtime's
out-of-band service only bootstraps.  Each process lazily opens one
listener, publishes its ``host:port`` under a KV key, and sends framed
payloads (JSON header + raw buffer bytes; typed ndarrays ``recv_into``
the preallocated result).  p2p send/recv and the per-rank legs of
``scatter`` (the multi-MB dataset path) ride sockets; the KV chunk path
remains as the socket-less fallback and carries bcast/allgather, whose
fan-out the KV server performs once per value.
"""

from __future__ import annotations

import pickle
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any

import numpy as np

import os as _os

# 2 MiB chunks: comfortably under gRPC's default 4 MB message ceiling while
# keeping round-trips low for the multi-MB pickles scatter_dataset ships.
# Env-tunable for transports with different message ceilings/latency.
CHUNK_BYTES = int(
    _os.environ.get("CHAINERMN_TPU_KV_CHUNK_BYTES", str(2 << 20))
)

# In-flight chunk RPCs per transfer.  The KV store is latency-bound per
# call; a handful of overlapped calls saturates it without flooding the
# coordinator.
PIPELINE_DEPTH = int(_os.environ.get("CHAINERMN_TPU_KV_DEPTH", "8"))

# Socket-plane handshake token length (see SocketPlane's trust boundary).
TOKEN_BYTES = 16

# Blocking gets wait indefinitely by default — MPI semantics: a slow peer
# is waited for; a *dead* peer is the global except hook's job to kill.
# The wait is implemented as poll slices so a caller-supplied finite
# timeout (recv_obj's escape hatch) is honored promptly.
POLL_SLICE_MS = 60_000

_PREFIX = "chainermn_tpu"

# Upper bound on a single socket-plane frame payload.  A corrupt header
# must not drive a multi-GB allocation on the receiver, so the reader
# enforces it — and the SENDER enforces the same bound so an oversized
# payload fails loudly on the sending rank instead of poisoning the
# receiver's plane.  Env-tunable (set IDENTICALLY on every process) for
# giant object sends.  Headers are small JSON; their length prefix gets
# its own tight cap.
MAX_FRAME_BYTES = int(
    _os.environ.get("CHAINERMN_TPU_MAX_FRAME_BYTES", str(16 << 30))
)
MAX_HEADER_BYTES = 1 << 20

# Sentinel pushed into every route queue when a reader thread dies on a
# malformed frame, so blocked recvs raise instead of hanging to timeout.
_POISON = object()


class PeerGone(RuntimeError):
    """A host-plane peer died: its connection hit EOF/reset, or a send to
    it failed at the socket layer.  Distinct from :class:`TimeoutError`
    (the peer may merely be slow and the recv is retryable): a
    ``PeerGone`` means the peer's *incarnation* is over — retrying
    against it is pointless until a replacement re-handshakes (a new
    process republishing the same rank's endpoint and reconnecting).
    Router health checks and KV migration catch this to fail over
    instead of hanging."""

    def __init__(self, msg: str, peer: "int | None" = None):
        super().__init__(msg)
        self.peer = peer


class _PeerGoneMarker:
    """Queue sentinel for a dead peer.  Honored only while the plane
    still believes the peer is gone — a replacement incarnation's first
    frame revives the peer, after which stale markers are skipped, so
    messages queued behind one are not lost."""

    __slots__ = ("src", "reason")

    def __init__(self, src: int, reason: str):
        self.src = src
        self.reason = reason


def retry_backoff(fn, *, retries: int = 3, base_s: float = 0.05,
                  exceptions=(PeerGone, TimeoutError)):
    """Call ``fn()`` with exponential backoff on transient host-plane
    failures (the satellite contract: fail fast with ``PeerGone``/
    ``TimeoutError``, then retry with backoff rather than hang).  The
    last failure propagates after ``retries`` re-attempts."""
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions:
            if attempt >= retries:
                raise
            time.sleep(base_s * (2 ** attempt))
            attempt += 1

_pool: ThreadPoolExecutor | None = None


def _get_pool() -> ThreadPoolExecutor:
    global _pool
    if _pool is None:
        _pool = ThreadPoolExecutor(
            max_workers=PIPELINE_DEPTH,
            thread_name_prefix="chainermn_tpu_kv",
        )
    return _pool


def client():
    """The process's coordination-service client, or None outside
    ``jax.distributed`` (single-process runs).

    Reaches through ``jax._src.distributed.global_state`` — a private
    seam (jax exposes no public handle to the coordination-service
    client), so the import is feature-checked: a jax release that moves
    it raises a clear unsupported-version error instead of an opaque
    AttributeError mid-collective."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except (ImportError, AttributeError) as e:
        raise RuntimeError(
            "chainermn_tpu's host-plane transport needs "
            "jax._src.distributed.global_state.client, which this jax "
            f"version does not expose ({e!r}); the KV-store seam must be "
            "re-pointed for this jax release"
        ) from None


def available() -> bool:
    try:
        return client() is not None
    except RuntimeError:
        return False


def _is_deadline(e: Exception) -> bool:
    """Did a blocking KV get time out (vs a real transport error)?

    jaxlib surfaces the gRPC DEADLINE_EXCEEDED status as
    ``XlaRuntimeError`` with the status name in the message; match the
    exception type where jax exports it, plus the status text."""
    try:
        from jax.errors import JaxRuntimeError

        if not isinstance(e, JaxRuntimeError):
            return False
    except ImportError:  # older jax: no common base exported
        pass
    return "DEADLINE" in str(e).upper()


def _put_chunks(c, key: str, view: memoryview) -> int:
    """Write ``view`` as pipelined CHUNK_BYTES-sized chunk values; returns
    the count.  The header is NOT written here — callers write it last."""
    n = max(1, -(-len(view) // CHUNK_BYTES))
    if n == 1:
        c.key_value_set_bytes(f"{key}/c0", bytes(view))
        return n
    futs = [
        _get_pool().submit(
            c.key_value_set_bytes,
            f"{key}/c{i}",
            bytes(view[i * CHUNK_BYTES : (i + 1) * CHUNK_BYTES]),
        )
        for i in range(n)
    ]
    for f in futs:
        f.result()
    return n


def _hdr_prefix(n: int) -> str:
    # The chunk size travels in the header: CHUNK_BYTES is env-tunable,
    # and a sender/receiver mismatch must not scramble chunk offsets.
    return f"{n},{CHUNK_BYTES}"


def _parse_hdr(hdr: str) -> tuple[int, int, str]:
    count, _, meta = hdr.partition("|")
    n, _, chunk = count.partition(",")
    return int(n), int(chunk) if chunk else CHUNK_BYTES, meta


def put_bytes(key: str, data) -> None:
    """Publish ``data`` (bytes-like) under ``key`` — chunked, chunk RPCs
    pipelined, header written last."""
    c = client()
    n = _put_chunks(c, key, memoryview(data).cast("B"))
    c.key_value_set(f"{key}/hdr", f"{_hdr_prefix(n)}|raw")


def _byte_view(a: np.ndarray) -> memoryview:
    """Flat byte view of a C-contiguous array (0-d safe)."""
    return memoryview(a.reshape(-1).view(np.uint8))


def _is_typed_array(obj) -> bool:
    """Payloads eligible for the raw-buffer path: plain ndarrays whose
    dtype holds no Python references anywhere (``hasobject`` also catches
    structured dtypes with object fields, which ``dtype != object``
    would not).  Exactly ``np.ndarray`` — subclasses (``np.matrix``,
    ``np.ma.MaskedArray``) carry state a raw buffer would drop, so they
    take the pickle path, which round-trips them faithfully."""
    return type(obj) is np.ndarray and not obj.dtype.hasobject


def put_payload(key: str, obj) -> None:
    """Publish a Python object under ``key``.

    C-contiguous-able ndarrays travel TYPED: raw buffer chunks plus
    dtype/shape in the header, no pickle byte-string materialized
    (the reference's first-class ndarray ``send`` path,
    REF:chainermn/communicators/mpi_communicator_base.py).  Everything
    else is pickled."""
    c = client()
    if _is_typed_array(obj):
        # asarray(order="C"), not ascontiguousarray: the latter silently
        # promotes 0-d arrays to shape (1,).
        a = np.asarray(obj, order="C")
        n = _put_chunks(c, key, _byte_view(a))
        shape = "x".join(map(str, a.shape))
        # ';' separators: dtype.str itself contains '|' (e.g. '|S1').
        c.key_value_set(
            f"{key}/hdr", f"{_hdr_prefix(n)}|nd;{a.dtype.str};{shape}"
        )
        return
    n = _put_chunks(c, key, memoryview(pickle.dumps(obj)))
    c.key_value_set(f"{key}/hdr", f"{_hdr_prefix(n)}|pkl")


def _blocking_get(fn, key: str, deadline: float | None):
    """Call a blocking KV getter, waiting until ``deadline`` (monotonic
    seconds; None = forever), polling in ``POLL_SLICE_MS`` slices.
    Non-deadline errors propagate immediately; deadline expiry raises
    ``TimeoutError`` so callers see the same exception type on both
    transports (the socket plane's ``recv`` already raises it)."""
    while True:
        if deadline is None:
            slice_ms = POLL_SLICE_MS
        else:
            remaining = int((deadline - time.monotonic()) * 1000)
            if remaining <= 0:
                remaining = 1
            slice_ms = min(POLL_SLICE_MS, remaining)
        try:
            return fn(key, slice_ms)
        except Exception as e:
            if not _is_deadline(e):
                raise
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"KV get of {key!r} expired its caller deadline "
                    f"({type(e).__name__} from the client)"
                ) from e


def _get_chunks_into(c, key: str, n: int, chunk: int, out, deadline) -> None:
    """Fetch ``n`` chunks of ``key`` (written with chunk size ``chunk``)
    into the writable buffer ``out`` — chunk RPCs pipelined, each landing
    at its offset, no join copy."""
    view = memoryview(out).cast("B")

    def fetch(i: int) -> None:
        data = _blocking_get(
            c.blocking_key_value_get_bytes, f"{key}/c{i}", deadline
        )
        view[i * chunk : i * chunk + len(data)] = data

    if n == 1:
        fetch(0)
        return
    futs = [_get_pool().submit(fetch, i) for i in range(n)]
    for f in futs:
        f.result()


def _assemble_raw(c, key: str, n: int, chunk: int, deadline) -> bytes:
    """Fetch an n-chunk variable-length payload: the tail chunk sizes the
    buffer, the rest land at their offsets."""
    tail = _blocking_get(
        c.blocking_key_value_get_bytes, f"{key}/c{n - 1}", deadline
    )
    out = bytearray((n - 1) * chunk + len(tail))
    out[(n - 1) * chunk :] = tail
    if n > 1:
        _get_chunks_into(c, key, n - 1, chunk, out, deadline)
    return bytes(out)


def _deadline_of(timeout_ms: int | None) -> float | None:
    return None if timeout_ms is None else time.monotonic() + timeout_ms / 1e3


def get_bytes(
    key: str, *, timeout_ms: int | None = None
) -> tuple[bytes, int]:
    """Block until ``key`` is published; return (payload, n_chunks).
    ``timeout_ms`` bounds the WHOLE receive (one deadline shared by the
    header and every chunk), not each KV round-trip."""
    c = client()
    deadline = _deadline_of(timeout_ms)
    hdr = _blocking_get(c.blocking_key_value_get, f"{key}/hdr", deadline)
    n, chunk, _meta = _parse_hdr(hdr)
    return _assemble_raw(c, key, n, chunk, deadline), n


def get_payload(key: str, *, timeout_ms: int | None = None):
    """Block until ``key`` is published; return (object, n_chunks).

    Typed ndarray payloads are fetched straight into the preallocated
    result array (chunk RPCs pipelined, each landing at its offset — no
    join, no pickle, no extra copy); pickled payloads are assembled and
    unpickled.  ``timeout_ms`` bounds the WHOLE receive."""
    c = client()
    deadline = _deadline_of(timeout_ms)
    hdr = _blocking_get(c.blocking_key_value_get, f"{key}/hdr", deadline)
    n, chunk, meta = _parse_hdr(hdr)
    if meta.startswith("nd;"):
        _, dts, shp = meta.split(";", 2)
        a = np.empty(tuple(int(s) for s in shp.split("x") if s), np.dtype(dts))
        _get_chunks_into(c, key, n, chunk, _byte_view(a), deadline)
        return a, n
    return pickle.loads(_assemble_raw(c, key, n, chunk, deadline)), n


def delete(key: str, n_chunks: int) -> None:
    c = client()
    for i in range(n_chunks):
        c.key_value_delete(f"{key}/c{i}")
    c.key_value_delete(f"{key}/hdr")


def ack_and_collect(key: str, n_chunks: int, n_readers: int) -> None:
    """Reader-side GC for multi-reader keys: the last of ``n_readers`` to
    ack (atomic increment) deletes the data; earlier readers return
    immediately.  Safe because readers only ack *after* consuming."""
    c = client()
    incr = getattr(c, "key_value_increment", None)
    if incr is None:
        # jaxlib builds without the atomic counter offer no safe
        # last-reader election: leave the payload for the coordinator
        # to reap at job end (keys are sequence-numbered, never
        # reused, so correctness is unaffected — only KV residency).
        return
    if int(incr(f"{key}/ack", 1)) >= n_readers:
        delete(key, n_chunks)
        c.key_value_delete(f"{key}/ack")


class SocketPlane:
    """Per-process direct-TCP data plane for host p2p payloads.

    One listener socket per process (shared by every communicator's
    ObjectPlane), rendezvoused through the KV store: rank r publishes
    ``chainermn_tpu/sockep/r`` = ``host:port`` once.  A background thread
    per accepted connection reads frames —

        ``u32 header_len | header JSON | payload bytes``

    with the header carrying (namespace, src, tag, seq, kind, dtype,
    shape, nbytes) — and routes decoded objects into per-(namespace, src,
    tag) queues, where :meth:`recv` awaits them.  TCP preserves per-edge
    order and senders stamp sequence numbers, so MPI's (communicator,
    source, tag, order) matching rule holds; a timed-out recv leaves the
    queue intact and is retryable.  Typed ndarrays are received straight
    into the preallocated result array (``recv_into`` — no join, no
    pickle, no extra copy).

    Trust boundary: frames can carry pickles, so accepting one from an
    arbitrary connection would be code execution.  The listener binds to
    the coordinator-facing interface only, and every connection must open
    with this process's secret token — a random value published ONLY
    through the KV store, so a peer that presents it has coordinator
    access, the same trust the KV fallback path requires.  Wrong or
    missing token → the connection is dropped before any frame is read."""

    def __init__(self, rank: int):
        import secrets
        import socket as _socket
        import threading

        self.rank = rank
        self._socket = _socket
        self._queues: dict[tuple, Any] = {}
        self._queues_lock = threading.Lock()
        self._broken: str | None = None  # first reader decode failure
        # src rank -> reason, for peers whose connection died (EOF/reset).
        # Cleared when a replacement incarnation's frames arrive.
        self._gone: dict[int, str] = {}
        self._send_socks: dict[int, Any] = {}
        self._send_lock = threading.Lock()
        self._token = secrets.token_bytes(TOKEN_BYTES)
        host = self._my_host()
        srv = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        srv.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        srv.bind((host, 0))
        srv.listen(64)
        self._srv = srv
        port = srv.getsockname()[1]
        # Delete-then-set: a replacement process taking over a dead rank's
        # identity must be able to republish the endpoint (the KV store
        # rejects silent overwrites on some backends; delete is idempotent
        # on others and may raise on a missing key — both are fine).
        try:
            client().key_value_delete(f"{_PREFIX}/sockep/{rank}")
        except Exception:
            pass
        client().key_value_set(
            f"{_PREFIX}/sockep/{rank}",
            f"{host}:{port}:{self._token.hex()}",
        )
        t = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="chainermn_tpu_sock_accept",
        )
        t.start()

    def _my_host(self) -> str:
        """An address peers can reach: the interface that routes toward
        the coordinator (loopback-safe on single-machine runs)."""
        try:
            from jax._src import distributed

            coord = distributed.global_state.coordinator_address
            host = coord.rsplit(":", 1)[0]
            s = self._socket.socket(
                self._socket.AF_INET, self._socket.SOCK_DGRAM
            )
            try:
                s.connect((host, 1))
                return s.getsockname()[0]
            finally:
                s.close()
        except Exception:
            return "127.0.0.1"

    # -- receive side ---------------------------------------------------
    def _queue(self, route: tuple):
        import queue as _q

        with self._queues_lock:
            q = self._queues.get(route)
            if q is None:
                q = self._queues[route] = _q.Queue()
            return q

    def _accept_loop(self):
        import threading

        while True:
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return  # listener closed at process exit
            threading.Thread(
                target=self._reader_loop, args=(conn,), daemon=True,
                name="chainermn_tpu_sock_reader",
            ).start()

    def _read_exact(self, conn, view: memoryview) -> bool:
        got = 0
        while got < len(view):
            n = conn.recv_into(view[got:], len(view) - got)
            if n == 0:
                return False
            got += n
        return True

    def _mark_gone(self, srcs, reason: str) -> None:
        """Record that every src rank seen on a now-dead connection is
        gone, and wake any recv blocked on one of its routes with a
        :class:`_PeerGoneMarker`.  Messages already queued ahead of the
        marker still deliver in order; the marker is only honored while
        ``_gone`` still lists the src (a replacement incarnation's first
        frame revives it, turning queued markers into no-ops)."""
        if not srcs:
            return
        with self._queues_lock:
            for src in srcs:
                self._gone[src] = reason
            routes = [
                (route, q) for route, q in self._queues.items()
                if route[1] in srcs
            ]
        for (_ns, src, _tag), q in routes:
            q.put(_PeerGoneMarker(src, reason))

    def peer_gone(self, src: int) -> "str | None":
        """The recorded death reason for ``src``, or None while it is
        believed alive."""
        with self._queues_lock:
            return self._gone.get(src)

    def _reader_loop(self, conn):
        import hmac
        import json as _json
        import struct

        # src ranks whose frames arrived on THIS connection: the set the
        # connection's death condemns.
        seen_srcs: set = set()
        try:
            conn.setsockopt(
                self._socket.IPPROTO_TCP, self._socket.TCP_NODELAY, 1
            )
            # Handshake: the peer must present our secret token (known
            # only via the KV store) before any frame is processed.
            presented = bytearray(TOKEN_BYTES)
            if not self._read_exact(conn, memoryview(presented)):
                conn.close()
                return
            if not hmac.compare_digest(bytes(presented), self._token):
                conn.close()
                return
            lenbuf = bytearray(4)
            while True:
                if not self._read_exact(conn, memoryview(lenbuf)):
                    self._mark_gone(seen_srcs, "connection EOF")
                    return
                (hlen,) = struct.unpack("<I", lenbuf)
                if hlen > MAX_HEADER_BYTES:
                    raise ValueError(
                        f"frame header length {hlen} exceeds "
                        f"{MAX_HEADER_BYTES} (stream desync/corruption?)"
                    )
                hbuf = bytearray(hlen)
                if not self._read_exact(conn, memoryview(hbuf)):
                    self._mark_gone(seen_srcs, "connection EOF mid-frame")
                    return
                hdr = _json.loads(hbuf.decode())
                nbytes = int(hdr["nbytes"])
                if nbytes < 0 or nbytes > MAX_FRAME_BYTES:
                    raise ValueError(
                        f"frame nbytes {nbytes} outside [0, "
                        f"{MAX_FRAME_BYTES}]"
                    )
                if hdr["kind"] == "nd":
                    dt = np.dtype(hdr["dtype"])
                    shape = tuple(int(s) for s in hdr["shape"])
                    want = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
                    if want != nbytes:
                        raise ValueError(
                            f"frame header inconsistent: dtype {dt} shape "
                            f"{shape} implies {want} bytes, header says "
                            f"{nbytes}"
                        )
                    a = np.empty(shape, dt)
                    if not self._read_exact(conn, _byte_view(a)):
                        self._mark_gone(
                            seen_srcs, "connection EOF mid-frame"
                        )
                        return
                    obj = a
                else:
                    buf = bytearray(nbytes)
                    if not self._read_exact(conn, memoryview(buf)):
                        self._mark_gone(
                            seen_srcs, "connection EOF mid-frame"
                        )
                        return
                    obj = pickle.loads(bytes(buf))
                src = hdr["src"]
                if src not in seen_srcs:
                    seen_srcs.add(src)
                    with self._queues_lock:
                        # A fresh connection carrying this src's frames
                        # is the re-handshake: the replacement is live.
                        self._gone.pop(src, None)
                route = (hdr["ns"], src, hdr["tag"])
                self._queue(route).put((hdr["seq"], obj))
        except OSError as e:
            self._mark_gone(seen_srcs, f"connection error: {e}")
            return
        except Exception as e:
            # A malformed frame must not kill the reader silently: record
            # the failure so every pending/future recv raises a transport
            # error instead of hanging to its timeout (ADVICE r3 #3).
            self._broken = f"{type(e).__name__}: {e}"
            with self._queues_lock:
                queues = list(self._queues.values())
            for q in queues:
                q.put(_POISON)
            try:
                conn.close()
            except Exception:
                pass
            return

    def recv(
        self, ns: str, source: int, tag: int, seq: int,
        timeout_ms: int | None = None,
    ):
        import queue as _q

        q = self._queue((ns, source, tag))
        deadline = _deadline_of(timeout_ms)
        while True:
            if self._broken is not None:
                raise RuntimeError(
                    f"host-plane socket reader on rank {self.rank} died "
                    f"decoding a frame: {self._broken}"
                )
            # Fast-fail on a dead peer with nothing pending: blocking for
            # the full timeout would be waiting on a corpse.  (Benign
            # race with q.put in _mark_gone: the marker also wakes us.)
            reason = self.peer_gone(source)
            if reason is not None and q.empty():
                raise PeerGone(
                    f"host-plane peer {source} is gone ({reason}); recv "
                    f"on {ns!r} tag {tag} cannot complete until a "
                    "replacement re-handshakes",
                    peer=source,
                )
            if deadline is None:
                timeout = None
            else:
                timeout = max(1e-3, deadline - time.monotonic())
            try:
                item = q.get(timeout=timeout)
            except _q.Empty:
                reason = self.peer_gone(source)
                if reason is not None:
                    raise PeerGone(
                        f"host-plane peer {source} is gone ({reason})",
                        peer=source,
                    ) from None
                raise TimeoutError(
                    f"recv_obj from {source} tag {tag}: nothing arrived "
                    f"in {timeout_ms} ms"
                ) from None
            if item is _POISON:
                # keep other waiters on this route failing fast
                q.put(_POISON)
                raise RuntimeError(
                    f"host-plane socket reader on rank {self.rank} died "
                    f"decoding a frame: {self._broken}"
                )
            if isinstance(item, _PeerGoneMarker):
                reason = self.peer_gone(item.src)
                if reason is None:
                    # Stale marker: the peer re-handshook after the marker
                    # was queued.  Drop it and keep draining.
                    continue
                q.put(item)  # keep other waiters on this route failing fast
                raise PeerGone(
                    f"host-plane peer {item.src} died mid-stream "
                    f"({item.reason})",
                    peer=item.src,
                )
            got_seq, obj = item
            if got_seq != seq:
                raise RuntimeError(
                    f"host-plane stream desync on edge "
                    f"{source}->{self.rank} tag {tag}: expected seq "
                    f"{seq}, got {got_seq} (SPMD send/recv order "
                    "diverged across processes)"
                )
            return obj

    # -- send side ------------------------------------------------------
    def _connect(self, dest: int):
        sock = self._send_socks.get(dest)
        if sock is not None:
            return sock
        ep = _blocking_get(
            client().blocking_key_value_get,
            f"{_PREFIX}/sockep/{dest}",
            None,
        )
        host, port, token = ep.rsplit(":", 2)
        try:
            sock = self._socket.create_connection((host, int(port)))
            sock.setsockopt(
                self._socket.IPPROTO_TCP, self._socket.TCP_NODELAY, 1
            )
            sock.sendall(bytes.fromhex(token))  # handshake (see class doc)
        except OSError as e:
            # The published endpoint no longer answers: the peer died
            # between publishing and our connect.  A replacement that
            # republishes the endpoint makes a later attempt succeed.
            raise PeerGone(
                f"cannot reach host-plane peer {dest} at {host}:{port} "
                f"({e})",
                peer=dest,
            ) from e
        self._send_socks[dest] = sock
        return sock

    def send(self, ns: str, dest: int, tag: int, seq: int, obj) -> None:
        import json as _json
        import struct

        if _is_typed_array(obj):
            # asarray(order="C"), not ascontiguousarray: the latter
            # silently promotes 0-d arrays to shape (1,).
            a = np.asarray(obj, order="C")
            payload = _byte_view(a)
            hdr = {
                "kind": "nd", "dtype": a.dtype.str, "shape": list(a.shape),
                "nbytes": a.nbytes,
            }
        else:
            payload = memoryview(pickle.dumps(obj))
            hdr = {"kind": "pkl", "nbytes": len(payload)}
        if hdr["nbytes"] > MAX_FRAME_BYTES:
            raise ValueError(
                f"socket-plane payload of {hdr['nbytes']} bytes exceeds "
                f"MAX_FRAME_BYTES ({MAX_FRAME_BYTES}); raise "
                "CHAINERMN_TPU_MAX_FRAME_BYTES identically on every "
                "process to send objects this large"
            )
        hdr.update(ns=ns, src=self.rank, tag=tag, seq=seq)
        hbytes = _json.dumps(hdr).encode()
        with self._send_lock:
            sock = self._connect(dest)
            try:
                sock.sendall(struct.pack("<I", len(hbytes)))
                sock.sendall(hbytes)
                sock.sendall(payload)
            except OSError as e:
                # Broken pipe / reset: the peer died under us.  Drop the
                # cached socket so a retry after the replacement
                # re-handshakes resolves a fresh endpoint.
                self._send_socks.pop(dest, None)
                try:
                    sock.close()
                except Exception:
                    pass
                raise PeerGone(
                    f"send to host-plane peer {dest} failed mid-frame "
                    f"({e}); the frame was NOT delivered",
                    peer=dest,
                ) from e


_socket_plane: "SocketPlane | None" = None


def socket_plane(rank: int) -> "SocketPlane":
    """The process's shared socket data plane (lazily constructed)."""
    global _socket_plane
    if _socket_plane is None:
        _socket_plane = SocketPlane(rank)
    return _socket_plane


class ObjectPlane:
    """Sequenced pickled-object transport for one communicator.

    Each instance keeps per-(operation, edge) sequence counters; because the
    object plane is SPMD-ordered (every process issues the same collective
    calls in the same order, and matched ``send_obj``/``recv_obj`` pairs are
    ordered per edge+tag), both sides of any transfer derive the same key
    without negotiation — the role MPI's (communicator, tag, order)
    matching plays in the reference.

    Counters commit only after the transfer succeeds, so a p2p call that
    raises (e.g. a finite ``timeout_ms`` expiring) can be retried without
    desynchronizing the stream.  A *collective* that fails midway leaves
    the plane's state undefined across processes — as a failed MPI
    collective does — and the job should abort (the except hook's role).
    """

    def __init__(
        self, namespace: str, rank: int, size: int, site: str = "<unknown>",
        members: "list[int] | None" = None,
    ):
        """``rank`` is this process's GLOBAL process index (its wire
        identity: socket endpoints and KV keys are global-rank-keyed).
        ``members`` — the ordered GLOBAL ranks participating in this plane
        — makes the plane a subgroup (``split(color, key)``); public
        root/dest/source arguments are then SUBGROUP ranks, translated
        through ``members``.  Default: the full world, identity order.
        Disjoint subgroups may share a namespace safely: every key and
        frame route embeds global ranks, so their key spaces are
        disjoint by construction."""
        self.namespace = namespace
        self.rank = rank
        self.size = size
        self.members = list(members) if members is not None else list(
            range(size)
        )
        if len(self.members) != size:
            raise ValueError(
                f"members {self.members} inconsistent with size {size}"
            )
        if rank not in self.members:
            raise ValueError(
                f"global rank {rank} is not a member of {self.members}"
            )
        self.sub_rank = self.members.index(rank)
        self.site = site
        self._seq: dict[Any, int] = {}
        self._validated = size == 1
        # Publish this plane's construction-site fingerprint NOW (one
        # non-blocking put): first use on any rank validates against rank
        # 0's, turning a breached SPMD-construction-order contract into a
        # fast diagnostic instead of a silent stream mixup or hang.
        # Publication at construction (not first use) matters because rank
        # 0 may never use a plane's host ops at all.
        if not self._validated and available():
            try:
                client().key_value_set(
                    f"{_PREFIX}/planecheck/{namespace}/{rank}", site
                )
            except Exception:
                pass  # duplicate keys on re-init: validation degrades soft

    def _ensure_validated(self) -> None:
        """First-use check of the SPMD construction-order contract (see
        base.py's plane-count comment): this plane's construction site
        must match rank 0's for the same namespace ordinal."""
        if self._validated:
            return
        self._validated = True
        timeout_ms = int(
            _os.environ.get("CHAINERMN_TPU_PLANE_CHECK_TIMEOUT_MS", "60000")
        )
        key = f"{_PREFIX}/planecheck/{self.namespace}/{self.members[0]}"
        try:
            root_site = _blocking_get(
                client().blocking_key_value_get, key,
                time.monotonic() + timeout_ms / 1e3,
            )
        except Exception:
            raise RuntimeError(
                f"host-plane {self.namespace} (constructed at {self.site} "
                f"on rank {self.rank}): rank 0 never constructed a plane "
                f"with this ordinal within {timeout_ms} ms — communicator "
                "construction order diverged across processes "
                "(rank-conditional create_communicator?)"
            ) from None
        # The TRUE contract is ordinal matching — rank 0 constructed a
        # plane with this namespace ordinal at all (checked fatally
        # above).  Site equality is only a heuristic fingerprint:
        # heterogeneous checkout paths or a legal rank-conditional
        # wrapper calling create_communicator satisfy the ordinal
        # contract with different filename:lineno, so a mismatch warns
        # rather than aborts (ADVICE r3 #2).  Basenames are compared to
        # tolerate differing install prefixes across hosts.
        def _basename_site(s: str) -> str:
            path, _, line = s.rpartition(":")
            return f"{_os.path.basename(path)}:{line}" if path else s

        if (
            _basename_site(root_site) != _basename_site(self.site)
            and "<unknown>" not in (root_site, self.site)
        ):
            import warnings

            warnings.warn(
                f"host-plane {self.namespace} construction-site mismatch: "
                f"rank {self.rank} built it at {self.site}, rank 0 at "
                f"{root_site}.  If communicator construction ORDER also "
                "diverged across processes, payloads will be delivered "
                "to the wrong streams.",
                RuntimeWarning,
                stacklevel=3,
            )

    def _peek(self, slot) -> int:
        return self._seq.get(slot, 0)

    def _commit(self, slot) -> None:
        self._seq[slot] = self._seq.get(slot, 0) + 1

    def _key(self, *parts) -> str:
        return "/".join([_PREFIX, self.namespace, *map(str, parts)])

    # -- point-to-point ------------------------------------------------
    # p2p rides the direct-socket data plane by default (the KV store's
    # per-byte ceiling is control-plane-grade; see SocketPlane).  Set
    # CHAINERMN_TPU_SOCKET_P2P=0 — identically on EVERY process — to
    # force the KV chunk path (e.g. if direct TCP between hosts is
    # firewalled); the two sides of an edge must use the same plane.
    _use_sockets = _os.environ.get("CHAINERMN_TPU_SOCKET_P2P", "1") != "0"

    def send(self, obj, dest: int, tag: int = 0) -> None:
        self._ensure_validated()
        gdest = self.members[dest]
        slot = ("p2p", self.rank, gdest, tag)
        if self._use_sockets:
            socket_plane(self.rank).send(
                self.namespace, gdest, tag, self._peek(slot), obj
            )
        else:
            put_payload(
                self._key("p2p", self.rank, gdest, tag, self._peek(slot)),
                obj,
            )
        self._commit(slot)

    def recv(
        self, source: int, tag: int = 0, *, timeout_ms: int | None = None
    ):
        self._ensure_validated()
        gsrc = self.members[source]
        slot = ("p2p", gsrc, self.rank, tag)
        if self._use_sockets:
            obj = socket_plane(self.rank).recv(
                self.namespace, gsrc, tag, self._peek(slot),
                timeout_ms=timeout_ms,
            )
        else:
            key = self._key(
                "p2p", gsrc, self.rank, tag, self._peek(slot)
            )
            obj, n = get_payload(key, timeout_ms=timeout_ms)
            delete(key, n)  # sole reader
        self._commit(slot)
        return obj

    # -- collectives ---------------------------------------------------
    def bcast(self, obj, root: int):
        self._ensure_validated()
        groot = self.members[root]
        slot = ("bcast", groot)
        key = self._key("bcast", groot, self._peek(slot))
        if self.rank == groot:
            put_payload(key, obj)
            self._commit(slot)
            return obj
        obj, n = get_payload(key)
        ack_and_collect(key, n, self.size - 1)
        self._commit(slot)
        return obj

    def allgather(self, obj, *, timeout_ms: int | None = None) -> list:
        """``timeout_ms`` bounds the wait on EACH member's payload so a
        dead peer surfaces as ``TimeoutError`` instead of a hang (the
        elastic supervisor's bounded-teardown contract rides this: a
        timed-out collective leaves the slot uncommitted, so the caller
        must treat it as fatal and die loudly, not retry)."""
        self._ensure_validated()
        slot = ("gather",)
        base = self._key("gather", self._peek(slot))
        put_payload(f"{base}/{self.rank}", obj)
        out = []
        for g in self.members:
            if g == self.rank:
                out.append(obj)
                continue
            got, n = get_payload(f"{base}/{g}", timeout_ms=timeout_ms)
            out.append(got)
            ack_and_collect(f"{base}/{g}", n, self.size - 1)
        self._commit(slot)
        return out

    def gather(self, obj, root: int, *,
               timeout_ms: int | None = None) -> "list | None":
        """Point-to-root gather (the reference ``MPI_Gather`` wire
        profile): every non-root sends its payload ONLY to root — O(n *
        payload) total wire, and non-root processes fetch NOTHING — where
        :meth:`allgather` costs O(n^2) total.  Returns the subgroup-
        ordered list at root, None elsewhere.  p2p-shaped, so payloads
        ride the socket data plane in a dedicated route namespace.
        ``timeout_ms`` bounds root's wait per member (``recv_obj``'s
        contract) so a dead sender surfaces as ``TimeoutError``, not a
        hang."""
        self._ensure_validated()
        groot = self.members[root]
        slot = ("pgather", groot)
        seq = self._peek(slot)
        ns = f"{self.namespace}#gather{groot}"
        if self.rank == groot:
            out = []
            for g in self.members:
                if g == groot:
                    out.append(obj)
                elif self._use_sockets:
                    out.append(socket_plane(self.rank).recv(
                        ns, g, 0, seq, timeout_ms=timeout_ms))
                else:
                    key = self._key("pgather", groot, g, seq)
                    got, n = get_payload(key, timeout_ms=timeout_ms)
                    delete(key, n)  # sole reader
                    out.append(got)
            self._commit(slot)
            return out
        if self._use_sockets:
            socket_plane(self.rank).send(ns, groot, 0, seq, obj)
        else:
            put_payload(self._key("pgather", groot, self.rank, seq), obj)
        self._commit(slot)
        return None

    def scatter(self, objs, root: int):
        """Point-to-point scatter: root sends each rank exactly its element
        (the reference's ``scatter_obj``), not a broadcast of the whole list
        — O(total) root-side wire, O(own) per receiver.  The per-rank
        payloads are p2p-shaped, so they ride the socket data plane (this
        is the multi-MB ``scatter_dataset`` path the chunking exists for),
        in a dedicated ``#scatter`` route namespace so user p2p traffic on
        any tag can never interleave with internal collective matching
        (the role of MPI's per-context internal tags); KV keys are the
        socket-less fallback."""
        self._ensure_validated()
        groot = self.members[root]
        slot = ("scatter", groot)
        seq = self._peek(slot)
        ns = f"{self.namespace}#scatter{groot}"
        if self.rank == groot:
            if objs is None or len(objs) != self.size:
                raise ValueError(
                    f"scatter_obj needs a length-{self.size} list at root"
                )
            for i, g in enumerate(self.members):
                if g == groot:
                    continue
                if self._use_sockets:
                    socket_plane(self.rank).send(ns, g, 0, seq, objs[i])
                else:
                    put_payload(
                        self._key("scatter", groot, g, seq), objs[i]
                    )
            self._commit(slot)
            return objs[self.sub_rank]
        if self._use_sockets:
            obj = socket_plane(self.rank).recv(ns, groot, 0, seq)
        else:
            key = self._key("scatter", groot, self.rank, seq)
            obj, n = get_payload(key)
            delete(key, n)  # sole reader
        self._commit(slot)
        return obj
