"""Host-plane transport over the jax.distributed coordination-service KV
store — the TPU-native analogue of the reference's pickled-MPI transport.

The reference's ``MpiCommunicatorBase`` gives every *process* an eager,
point-to-point-capable object plane: ``send``/``recv`` of pickled payloads
between two ranks, and chunked collective object transport
(``chunked_bcast_obj``, REF:chainermn/communicators/_communication_utility.py)
that splits large pickles to respect MPI message-count limits.  JAX has no
MPI, but every multi-process JAX job already runs a coordination service
(the ``jax.distributed.initialize`` coordinator) whose distributed KV store
is reachable from all processes over DCN.  This module builds the same
transport primitives on it:

* ``put_bytes``/``get_bytes`` — a chunked length-then-payload protocol.
  Values are split into ``CHUNK_BYTES`` pieces (the coordination service is
  gRPC-backed; one huge value would trip message-size ceilings exactly the
  way one huge ``MPI_Bcast`` trips ``int`` count limits) and a header key is
  written *last*, so a reader blocking on the header never observes a
  partial write.
* single-reader keys are deleted by their reader; multi-reader keys are
  garbage-collected by the *last* reader, discovered with an atomic
  ``key_value_increment`` ack counter.

Keys are namespaced under ``chainermn_tpu/`` and carry a monotone
per-(edge, tag) sequence number maintained independently on each side.
Matched send/recv pairs advance their counters in lockstep (the same
SPMD-ordering contract MPI tags rely on), so no two in-flight transfers
ever share a key and stale keys cannot be re-read.
"""

from __future__ import annotations

import pickle
import time
from typing import Any

# 1 MiB chunks: comfortably under gRPC's default 4 MB message ceiling while
# keeping round-trips low for the multi-MB pickles scatter_dataset ships.
CHUNK_BYTES = 1 << 20

# Blocking gets wait indefinitely by default — MPI semantics: a slow peer
# is waited for; a *dead* peer is the global except hook's job to kill.
# The wait is implemented as poll slices so a caller-supplied finite
# timeout (recv_obj's escape hatch) is honored promptly.
POLL_SLICE_MS = 60_000

_PREFIX = "chainermn_tpu"


def client():
    """The process's coordination-service client, or None outside
    ``jax.distributed`` (single-process runs)."""
    from jax._src import distributed

    return distributed.global_state.client


def available() -> bool:
    return client() is not None


def put_bytes(key: str, data: bytes) -> None:
    """Publish ``data`` under ``key`` (chunked; header written last)."""
    c = client()
    n = max(1, -(-len(data) // CHUNK_BYTES))
    for i in range(n):
        c.key_value_set_bytes(
            f"{key}/c{i}", bytes(data[i * CHUNK_BYTES : (i + 1) * CHUNK_BYTES])
        )
    c.key_value_set(f"{key}/hdr", str(n))


def _blocking_get(fn, key: str, deadline: float | None):
    """Call a blocking KV getter, waiting until ``deadline`` (monotonic
    seconds; None = forever), polling in ``POLL_SLICE_MS`` slices.
    Non-deadline errors propagate immediately."""
    while True:
        if deadline is None:
            slice_ms = POLL_SLICE_MS
        else:
            remaining = int((deadline - time.monotonic()) * 1000)
            if remaining <= 0:
                remaining = 1
            slice_ms = min(POLL_SLICE_MS, remaining)
        try:
            return fn(key, slice_ms)
        except Exception as e:  # jaxlib surfaces DEADLINE_EXCEEDED as XlaRuntimeError
            if "DEADLINE" not in str(e).upper():
                raise
            if deadline is not None and time.monotonic() >= deadline:
                raise


def get_bytes(
    key: str, *, timeout_ms: int | None = None
) -> tuple[bytes, int]:
    """Block until ``key`` is published; return (payload, n_chunks).
    ``timeout_ms`` bounds the WHOLE receive (one deadline shared by the
    header and every chunk), not each KV round-trip."""
    c = client()
    deadline = (
        None if timeout_ms is None else time.monotonic() + timeout_ms / 1e3
    )
    n = int(_blocking_get(c.blocking_key_value_get, f"{key}/hdr", deadline))
    parts = [
        _blocking_get(c.blocking_key_value_get_bytes, f"{key}/c{i}", deadline)
        for i in range(n)
    ]
    return b"".join(parts), n


def delete(key: str, n_chunks: int) -> None:
    c = client()
    for i in range(n_chunks):
        c.key_value_delete(f"{key}/c{i}")
    c.key_value_delete(f"{key}/hdr")


def ack_and_collect(key: str, n_chunks: int, n_readers: int) -> None:
    """Reader-side GC for multi-reader keys: the last of ``n_readers`` to
    ack (atomic increment) deletes the data; earlier readers return
    immediately.  Safe because readers only ack *after* consuming."""
    c = client()
    if int(c.key_value_increment(f"{key}/ack", 1)) >= n_readers:
        delete(key, n_chunks)
        c.key_value_delete(f"{key}/ack")


class ObjectPlane:
    """Sequenced pickled-object transport for one communicator.

    Each instance keeps per-(operation, edge) sequence counters; because the
    object plane is SPMD-ordered (every process issues the same collective
    calls in the same order, and matched ``send_obj``/``recv_obj`` pairs are
    ordered per edge+tag), both sides of any transfer derive the same key
    without negotiation — the role MPI's (communicator, tag, order)
    matching plays in the reference.

    Counters commit only after the transfer succeeds, so a p2p call that
    raises (e.g. a finite ``timeout_ms`` expiring) can be retried without
    desynchronizing the stream.  A *collective* that fails midway leaves
    the plane's state undefined across processes — as a failed MPI
    collective does — and the job should abort (the except hook's role).
    """

    def __init__(self, namespace: str, rank: int, size: int):
        self.namespace = namespace
        self.rank = rank
        self.size = size
        self._seq: dict[Any, int] = {}

    def _peek(self, slot) -> int:
        return self._seq.get(slot, 0)

    def _commit(self, slot) -> None:
        self._seq[slot] = self._seq.get(slot, 0) + 1

    def _key(self, *parts) -> str:
        return "/".join([_PREFIX, self.namespace, *map(str, parts)])

    # -- point-to-point ------------------------------------------------
    def send(self, obj, dest: int, tag: int = 0) -> None:
        slot = ("p2p", self.rank, dest, tag)
        put_bytes(
            self._key("p2p", self.rank, dest, tag, self._peek(slot)),
            pickle.dumps(obj),
        )
        self._commit(slot)

    def recv(
        self, source: int, tag: int = 0, *, timeout_ms: int | None = None
    ):
        slot = ("p2p", source, self.rank, tag)
        key = self._key("p2p", source, self.rank, tag, self._peek(slot))
        data, n = get_bytes(key, timeout_ms=timeout_ms)
        delete(key, n)  # sole reader
        self._commit(slot)
        return pickle.loads(data)

    # -- collectives ---------------------------------------------------
    def bcast(self, obj, root: int):
        slot = ("bcast", root)
        key = self._key("bcast", root, self._peek(slot))
        if self.rank == root:
            put_bytes(key, pickle.dumps(obj))
            self._commit(slot)
            return obj
        data, n = get_bytes(key)
        ack_and_collect(key, n, self.size - 1)
        self._commit(slot)
        return pickle.loads(data)

    def allgather(self, obj) -> list:
        slot = ("gather",)
        base = self._key("gather", self._peek(slot))
        put_bytes(f"{base}/{self.rank}", pickle.dumps(obj))
        out = []
        for r in range(self.size):
            if r == self.rank:
                out.append(obj)
                continue
            data, n = get_bytes(f"{base}/{r}")
            out.append(pickle.loads(data))
            ack_and_collect(f"{base}/{r}", n, self.size - 1)
        self._commit(slot)
        return out

    def scatter(self, objs, root: int):
        """Point-to-point scatter: root sends each rank exactly its element
        (the reference's ``scatter_obj``), not a broadcast of the whole list
        — O(total) root-side wire, O(own) per receiver.  Keys live in their
        own ``scatter`` namespace so user p2p traffic on any tag can never
        interleave with internal collective matching (the role of MPI's
        per-context internal tags)."""
        slot = ("scatter", root)
        seq = self._peek(slot)
        if self.rank == root:
            if objs is None or len(objs) != self.size:
                raise ValueError(
                    f"scatter_obj needs a length-{self.size} list at root"
                )
            for r in range(self.size):
                if r != root:
                    put_bytes(
                        self._key("scatter", root, r, seq),
                        pickle.dumps(objs[r]),
                    )
            self._commit(slot)
            return objs[root]
        key = self._key("scatter", root, self.rank, seq)
        data, n = get_bytes(key)
        delete(key, n)  # sole reader
        self._commit(slot)
        return pickle.loads(data)
