"""Multi-node checkpointer — coordinated snapshot / auto-resume.

Reference: REF:chainermn/extensions/checkpoint.py —
``create_multi_node_checkpointer(name, comm)``: each rank snapshots its
state, the checkpointer tracks the newest *consistent* generation (present
on every rank), deletes stale snapshots, and ``maybe_load`` on startup
restores the latest consistent set before resuming training (SURVEY §5.4).

TPU-native shape: one snapshot file per *process* (host), holding that
host's addressable shards of the state pytree — the sharded-checkpoint
layout orbax standardized, implemented in-repo to keep the framework
self-contained.  Consistency is a two-phase commit in miniature: write to a
temp name, atomic rename, then a marker file per generation; ``maybe_load``
only accepts generations whose marker count equals the world size.  On a
single host this degrades to plain snapshot/rotate.
"""

from __future__ import annotations

import os
import pickle
import re
from typing import Any, Optional, Tuple

import jax
import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase


def _to_host(tree):
    """Device arrays → numpy (addressable shards only)."""

    def conv(x):
        if isinstance(x, jax.Array):
            return np.asarray(x)
        return x

    return jax.tree.map(conv, tree)


class MultiNodeCheckpointer:
    def __init__(
        self,
        name: str,
        comm: CommunicatorBase,
        path: str = ".",
        keep: int = 2,
    ):
        self.name = name
        self.comm = comm
        self.dir = os.path.join(path, name)
        self.keep = keep
        os.makedirs(self.dir, exist_ok=True)

    # -- file layout -----------------------------------------------------
    def _snap(self, iteration: int, rank: int) -> str:
        return os.path.join(self.dir, f"snapshot_iter_{iteration}.rank{rank}")

    def _marker(self, iteration: int, rank: int) -> str:
        return os.path.join(self.dir, f"done_iter_{iteration}.rank{rank}")

    # -- API (reference: checkpointer.save / maybe_load) ------------------
    def save(self, state: Any, iteration: int) -> None:
        rank = self.comm.rank
        tmp = self._snap(iteration, rank) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(_to_host(state), f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, self._snap(iteration, rank))
        with open(self._marker(iteration, rank), "w") as f:
            f.write("ok")
        self.comm.barrier()
        self._cleanup()

    def _generations(self):
        pat = re.compile(r"done_iter_(\d+)\.rank(\d+)$")
        gens: dict[int, int] = {}
        for fn in os.listdir(self.dir):
            m = pat.match(fn)
            if m:
                gens[int(m.group(1))] = gens.get(int(m.group(1)), 0) + 1
        return gens

    def _consistent_generations(self):
        return sorted(
            it for it, cnt in self._generations().items() if cnt >= self.comm.size
        )

    def _cleanup(self):
        done = self._consistent_generations()
        for it in done[: -self.keep] if len(done) > self.keep else []:
            for rank in range(self.comm.size):
                for p in (self._snap(it, rank), self._marker(it, rank)):
                    try:
                        os.remove(p)
                    except OSError:
                        pass

    def maybe_load(self, state: Any = None) -> Tuple[Any, Optional[int]]:
        """Restore the newest consistent generation, or return ``state``
        untouched when none exists (reference ``maybe_load`` contract)."""
        done = self._consistent_generations()
        if not done:
            return state, None
        it = done[-1]
        with open(self._snap(it, self.comm.rank), "rb") as f:
            loaded = pickle.load(f)
        if state is not None:
            # Preserve the template's structure/dtypes: restore leaf-wise.
            loaded = jax.tree.map(
                lambda tpl, new: np.asarray(new).astype(
                    getattr(tpl, "dtype", np.asarray(new).dtype)
                ),
                state,
                loaded,
            )
        return loaded, it


def create_multi_node_checkpointer(
    name: str, comm: CommunicatorBase, path: str = ".", keep: int = 2
) -> MultiNodeCheckpointer:
    """Reference-parity factory (REF:chainermn/extensions/checkpoint.py)."""
    return MultiNodeCheckpointer(name, comm, path=path, keep=keep)
