"""Multi-node checkpointer — coordinated snapshot / auto-resume.

Reference: REF:chainermn/extensions/checkpoint.py —
``create_multi_node_checkpointer(name, comm)``: each rank snapshots its
state, the checkpointer tracks the newest *consistent* generation (present
on every rank), deletes stale snapshots, and ``maybe_load`` on startup
restores the latest consistent set before resuming training (SURVEY §5.4).

TPU-native shape: one snapshot file per *process* (host), holding that
host's addressable shards of the state pytree — the sharded-checkpoint
layout orbax standardized, implemented in-repo to keep the framework
self-contained.  Leaves that span non-addressable devices (multi-host
GSPMD arrays, ZeRO-3 flat buffers) are saved as their local shard list and
re-assembled on load against the template's sharding.  Consistency is a
two-phase commit in miniature: write to a temp name, atomic rename, then a
marker file per generation; ``maybe_load`` only accepts generations whose
marker count equals the world size.  On a single host this degrades to
plain snapshot/rotate.

``save(..., block=False)`` runs serialization and file I/O on a background
thread (the device→host transfer stays synchronous, so the training loop
may immediately mutate/donate the live state): checkpoint cost overlaps
the next training steps, the reference-era pattern of pausing the trainer
to snapshot is gone.  ``wait()`` joins the in-flight write; ``save`` and
``maybe_load`` join it implicitly.

Snapshots use a framed native format (see the v2 section below): array
payloads are packed with the native ``gatherv``, streamed through the
native ring queue to the file writer, and crc32c-checksummed; ``maybe_load``
verifies integrity and falls back — rank-coordinated — to an older
generation when a snapshot is corrupt.
"""

from __future__ import annotations

import os
import pickle
import re
import struct
import threading
import warnings
from typing import Any, Optional, Tuple

import jax
import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase
from chainermn_tpu.utils import native


class CheckpointCorruptionError(RuntimeError):
    """A snapshot file failed integrity verification (crc32c mismatch,
    truncation, or unparseable contents)."""


class _ArrayRef:
    """Header placeholder for an ndarray whose bytes live in the payload
    section (plain class, not NamedTuple: must be a pytree *leaf*)."""

    def __init__(self, idx: int):
        self.idx = idx


class _ShardList:
    """Pickled stand-in for a leaf that spans non-addressable devices:
    this process's addressable shards plus each shard's global index
    (``Shard.index``), in ``addressable_shards`` order."""

    def __init__(self, shards, indices):
        self.shards = shards
        self.indices = indices


def _to_host(tree):
    """Device arrays → numpy (this process's addressable data only).

    Non-jax ndarray leaves are copied: the returned tree may be pickled on
    a background thread (async save) while the caller keeps mutating the
    live state, so nothing in it may alias caller-owned buffers."""

    def conv(x):
        if isinstance(x, jax.Array):
            if x.is_fully_addressable:
                return np.asarray(x)
            if x.sharding.is_fully_replicated:
                # Replicated over a multi-process mesh: every device holds
                # the whole value, so save ONE local replica as a plain
                # array (restorable against any template), not a
                # redundant per-device shard list.
                return np.asarray(x.addressable_shards[0].data)
            return _ShardList(
                [np.asarray(s.data) for s in x.addressable_shards],
                [s.index for s in x.addressable_shards],
            )
        if isinstance(x, np.ndarray):
            return np.array(x, copy=True)
        return x

    return jax.tree.map(conv, tree)


def _restore_leaf(tpl, saved):
    """Rebuild one leaf from its saved host form against the template leaf
    (structure, dtype, and — for jax Arrays — sharding/placement)."""
    if isinstance(saved, _ShardList):
        if not isinstance(tpl, jax.Array):
            raise ValueError(
                "checkpoint leaf was saved as device shards but the "
                "template leaf is not a jax.Array"
            )
        tpl_shards = list(tpl.addressable_shards)
        if len(tpl_shards) != len(saved.shards):
            raise ValueError(
                f"checkpoint shard count ({len(saved.shards)}) does not "
                f"match template ({len(tpl_shards)}) — was the mesh resized?"
            )
        tpl_indices = [s.index for s in tpl_shards]
        if saved.indices != tpl_indices:
            raise ValueError(
                "checkpoint shard layout does not match the template's "
                f"sharding (saved indices {saved.indices} vs template "
                f"{tpl_indices}) — restoring would place data at wrong "
                "global offsets; load with the save-time sharding instead"
            )
        arrs = [
            jax.device_put(np.asarray(d).astype(tpl.dtype), s.device)
            for d, s in zip(saved.shards, tpl_shards)
        ]
        return jax.make_array_from_single_device_arrays(
            tpl.shape, tpl.sharding, arrs
        )
    arr = np.asarray(saved)
    if isinstance(tpl, jax.Array):
        # Placement fidelity: device_put COMMITS the result to the
        # template's sharding.  That is wanted for explicitly-placed
        # templates (and required for non-addressable ones), but a fresh
        # model.init produces UNCOMMITTED arrays that jit is free to
        # re-place — restoring those as committed single-device arrays
        # would poison a later shard_map step with a device mismatch.
        # Uncommitted fully-addressable templates therefore restore as
        # host arrays, preserving jit's placement freedom.
        committed = getattr(tpl, "_committed", True)
        if committed or not tpl.is_fully_addressable:
            return jax.device_put(arr.astype(tpl.dtype), tpl.sharding)
        return arr.astype(tpl.dtype)
    return arr.astype(getattr(tpl, "dtype", arr.dtype))


# ---------------------------------------------------------------------------
# Framed snapshot format (v2) — the native-component seam.
#
# Layout:  MAGIC | u64 header_len | u32 header_crc32c | header pickle
#          | payload | u32 payload_crc32c
#
# The header pickles the state tree with every ndarray replaced by an
# _ArrayRef into the payload section (shapes/dtypes recorded alongside);
# the payload is the concatenation of the raw array bytes.  Writing
# packs arrays into chunks with the native ``gatherv``
# (csrc/hostbuf.cpp) and streams them through the native ring queue to a
# file-writer thread, overlapping the parallel memcpy + crc32c with disk
# I/O — the pinned-staging double-buffering idea of the reference's
# ``_memory_utility``/``HostPinnedMemory`` applied to checkpointing.
# Reading verifies the crc32c before any bytes are trusted and scatters
# the payload back into preallocated arrays with ``scatterv``.
# ---------------------------------------------------------------------------

_MAGIC = b"CMNTPU02"
_CHUNK_BYTES = 8 << 20


def _split_payload(host_tree):
    """Replace ndarray leaves (incl. inside _ShardList) with _ArrayRef
    placeholders; return (struct_tree, buffers)."""
    buffers: list[np.ndarray] = []

    def add(a: np.ndarray):
        # order="C" (not ascontiguousarray, which promotes 0-d to (1,)).
        buffers.append(np.asarray(a, order="C"))
        return _ArrayRef(len(buffers) - 1)

    def conv(x):
        if isinstance(x, _ShardList):
            return _ShardList(
                [add(s) if _bufferable(s) else s for s in x.shards],
                x.indices,
            )
        if _bufferable(x):
            return add(x)
        return x

    struct_tree = jax.tree.map(
        conv, host_tree, is_leaf=lambda x: isinstance(x, _ShardList)
    )
    return struct_tree, buffers


def _bufferable(x) -> bool:
    return isinstance(x, np.ndarray) and x.dtype != object


def _join_payload(struct_tree, arrays):
    def conv(x):
        if isinstance(x, _ArrayRef):
            return arrays[x.idx]
        if isinstance(x, _ShardList):
            return _ShardList(
                [arrays[s.idx] if isinstance(s, _ArrayRef) else s
                 for s in x.shards],
                x.indices,
            )
        return x

    return jax.tree.map(
        conv, struct_tree,
        is_leaf=lambda x: isinstance(x, (_ArrayRef, _ShardList)),
    )


def _chunk_groups(buffers):
    """Group buffer indices into ~_CHUNK_BYTES packing units (one oversized
    buffer forms its own unit).  Zero-size buffers are skipped: they add no
    payload bytes, and an empty push would mimic the queue-close sentinel
    in the writer."""
    group, group_bytes = [], 0
    for i, a in enumerate(buffers):
        if a.nbytes == 0:
            continue
        if group and group_bytes + a.nbytes > _CHUNK_BYTES:
            yield group
            group, group_bytes = [], 0
        group.append(i)
        group_bytes += a.nbytes
    if group:
        yield group


def _write_snapshot(path: str, host_tree) -> None:
    struct_tree, buffers = _split_payload(host_tree)
    header = pickle.dumps(
        {
            "struct": struct_tree,
            "buffers": [(a.shape, a.dtype.str) for a in buffers],
            "payload_len": int(sum(a.nbytes for a in buffers)),
        },
        protocol=pickle.HIGHEST_PROTOCOL,
    )
    q = native.NativeQueue(capacity=4)
    max_chunk = max(
        [_CHUNK_BYTES] + [a.nbytes for a in buffers]
    )
    result: dict = {}

    def writer():
        try:
            with open(path, "wb") as f:
                f.write(_MAGIC)
                f.write(struct.pack("<QI", len(header),
                                    native.crc32c(header)))
                f.write(header)
                crc = 0
                while True:
                    chunk = q.pop(max_chunk)
                    if not chunk:
                        break
                    crc = native.crc32c(chunk, crc)
                    f.write(chunk)
                f.write(struct.pack("<I", crc))
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            result["error"] = e
            q.close()  # unblock a producer waiting on a full queue

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        for group in _chunk_groups(buffers):
            packed = native.pack_buffers([buffers[i] for i in group])
            if not q.push(packed.tobytes()):
                break  # writer died and closed the queue
    finally:
        q.close()
        t.join()
    if "error" in result:
        raise result["error"]


def _read_snapshot(path: str):
    """Parse one snapshot file; raises CheckpointCorruptionError on any
    integrity failure.  Legacy (pre-v2, plain pickle) files load too.

    The payload is read directly into one preallocated buffer (no whole-
    file bytes object alongside it), keeping peak load memory at payload +
    destination arrays — the read-side counterpart of the chunked writer.
    """
    try:
        f = open(path, "rb")
    except OSError as e:
        raise CheckpointCorruptionError(f"{path}: unreadable: {e}") from e
    try:
        with f:
            return _read_snapshot_body(path, f)
    except CheckpointCorruptionError:
        raise
    except Exception as e:
        # ANY failure parsing a snapshot file — mid-read I/O errors, schema
        # skew that passes the crc (unknown dtype strings, missing header
        # keys), unpack failures — must surface as the typed corruption
        # error: maybe_load's cross-rank vote only catches that type, and
        # an untyped escape would strand peers in the vote collective.
        raise CheckpointCorruptionError(f"{path}: unreadable: {e}") from e


def _read_snapshot_body(path: str, f):
    prefix = f.read(len(_MAGIC))
    if prefix != _MAGIC:
        try:
            return pickle.loads(prefix + f.read())  # legacy pickle
        except Exception as e:
            raise CheckpointCorruptionError(
                f"{path}: not a v2 snapshot and not a legacy pickle"
            ) from e
    try:
        hlen, hcrc_stored = struct.unpack("<QI", f.read(12))
        header_bytes = f.read(hlen)
        if (
            len(header_bytes) != hlen
            or native.crc32c(header_bytes) != hcrc_stored
        ):
            raise CheckpointCorruptionError(
                f"{path}: header crc32c mismatch — snapshot is corrupt"
            )
        header = pickle.loads(header_bytes)
        plen = header["payload_len"]
        payload = np.empty(plen, np.uint8)
        if f.readinto(memoryview(payload)) != plen:
            raise CheckpointCorruptionError(f"{path}: payload truncated")
        (crc_stored,) = struct.unpack("<I", f.read(4))
    except CheckpointCorruptionError:
        raise
    except Exception as e:
        raise CheckpointCorruptionError(
            f"{path}: truncated or garbled"
        ) from e
    if native.crc32c(payload) != crc_stored:
        raise CheckpointCorruptionError(
            f"{path}: payload crc32c mismatch — snapshot is corrupt"
        )
    arrays = [
        np.empty(shape, np.dtype(dt)) for shape, dt in header["buffers"]
    ]
    if arrays:
        native.unpack_buffers(payload, arrays)
    return _join_payload(header["struct"], arrays)


class MultiNodeCheckpointer:
    def __init__(
        self,
        name: str,
        comm: CommunicatorBase,
        path: str = ".",
        keep: int = 2,
        keep_last_n: Optional[int] = None,
    ):
        self.name = name
        self.comm = comm
        self.dir = os.path.join(path, name)
        # ``keep_last_n`` is the retention knob long soaks tune: it
        # bounds BOTH live consistent generations (same rotation as
        # ``keep``, which it overrides when given) and retained
        # quarantined generations.
        self.keep = keep if keep_last_n is None else int(keep_last_n)
        os.makedirs(self.dir, exist_ok=True)
        self._pending: Optional[threading.Thread] = None
        self._pending_error: Optional[BaseException] = None
        # Catch up on rotations a previous run decided but didn't finish
        # (e.g. a rank that never ran another cleanup): drop our own files
        # of tombstoned generations so stale tombstones get released
        # instead of lingering to shadow future saves.
        self._cleanup(ranks=(comm.rank,))

    # -- file layout -----------------------------------------------------
    def _snap(self, iteration: int, rank: int) -> str:
        return os.path.join(self.dir, f"snapshot_iter_{iteration}.rank{rank}")

    def _marker(self, iteration: int, rank: int) -> str:
        return os.path.join(self.dir, f"done_iter_{iteration}.rank{rank}")

    def _tomb(self, iteration: int) -> str:
        return os.path.join(self.dir, f"rotated_iter_{iteration}")

    # -- API (reference: checkpointer.save / maybe_load) ------------------
    def save(self, state: Any, iteration: int, block: bool = True) -> None:
        """Snapshot ``state`` as generation ``iteration``.

        ``block=False``: the device→host transfer happens now (safe to
        donate/mutate the live state immediately), but pickling and file
        I/O run on a background thread — call :meth:`wait` (or let the
        next ``save``/``maybe_load`` do it) to join.
        """
        self.wait()
        rank = self.comm.rank
        # A fresh save of this iteration supersedes any earlier rotation
        # of the same number (dir reuse across runs): clear the tombstone
        # so cleanup cannot delete the checkpoint we are about to write.
        try:
            os.remove(self._tomb(iteration))
        except OSError:
            pass
        host_state = _to_host(state)

        def write():
            tmp = self._snap(iteration, rank) + ".tmp"
            _write_snapshot(tmp, host_state)
            os.replace(tmp, self._snap(iteration, rank))
            with open(self._marker(iteration, rank), "w") as f:
                # The marker records the world size that wrote this
                # generation: consistency is "every SAVE-TIME rank
                # committed", so a rescaled relaunch (different
                # comm.size) can still recognize and resume it.
                f.write(f"ok {self.comm.size}")

        if block:
            write()
            self.comm.barrier()
            # Cleanup only after every rank has committed this generation:
            # deleting a rotated generation before a straggler finished
            # choosing its newest-consistent set could turn its maybe_load
            # into a FileNotFoundError.
            self._cleanup()
        else:
            def run():
                try:
                    write()
                    # No barrier on the background thread; deleting other
                    # ranks' files here could race a straggler's
                    # maybe_load, so each rank rotates only its own.
                    self._cleanup(ranks=(rank,))
                except BaseException as e:  # noqa: BLE001 — surfaced in wait()
                    self._pending_error = e

            self._pending = threading.Thread(target=run, daemon=True)
            self._pending.start()

    def wait(self) -> None:
        """Join an in-flight async save; re-raise its error, if any."""
        t, self._pending = self._pending, None
        if t is not None:
            t.join()
        err, self._pending_error = self._pending_error, None
        if err is not None:
            raise err

    def _generations(self, names=None):
        if names is None:
            names = os.listdir(self.dir)
        pat = re.compile(r"done_iter_(\d+)\.rank(\d+)$")
        gens: dict[int, int] = {}
        for fn in names:
            m = pat.match(fn)
            if m:
                gens[int(m.group(1))] = gens.get(int(m.group(1)), 0) + 1
        for it in self._tombstoned(names):
            gens.pop(it, None)
        return gens

    def _tombstoned(self, names=None):
        if names is None:
            names = os.listdir(self.dir)
        pat = re.compile(r"rotated_iter_(\d+)$")
        return sorted(
            int(m.group(1)) for m in map(pat.match, names) if m
        )

    def _marker_world(self, it: int, names=None) -> Optional[int]:
        """World size recorded in generation ``it``'s markers, or None
        for legacy markers (pre-world-stamp: plain "ok")."""
        if names is None:
            names = os.listdir(self.dir)
        pat = re.compile(rf"done_iter_{it}\.rank\d+$")
        for fn in sorted(n for n in names if pat.match(n)):
            try:
                with open(os.path.join(self.dir, fn)) as f:
                    parts = f.read().split()
                if len(parts) >= 2:
                    return int(parts[1])
            except (OSError, ValueError):
                continue
        return None

    def _consistent_generations(self, names=None):
        """Generations every save-time rank committed.  The marker's
        recorded world size (not the CURRENT comm.size) is the quorum,
        so an elastic N→M relaunch resumes generations the old world
        wrote; legacy markers fall back to the current-size rule."""
        if names is None:
            names = os.listdir(self.dir)
        out = []
        for it, cnt in self._generations(names).items():
            world = self._marker_world(it, names)
            if cnt >= (world if world is not None else self.comm.size):
                out.append(it)
        return sorted(out)

    def _quarantined_generations(self, names=None):
        if names is None:
            names = os.listdir(self.dir)
        pat = re.compile(
            r"(?:snapshot|done)_iter_(\d+)\.rank\d+\.quarantined$"
        )
        return sorted({
            int(m.group(1)) for m in map(pat.match, names) if m
        })

    def _quarantine(self, it: int) -> None:
        """Rename generation ``it``'s files to ``*.quarantined`` so it
        drops out of ``_generations`` permanently — rejected snapshots
        are kept for forensics but never re-verified on later loads.
        Every rank runs this after the failed vote; file ownership is
        split by ``saved_rank % comm.size`` so concurrent renames never
        collide and orphan ranks of a shrunken world are covered."""
        pat = re.compile(
            rf"(?:snapshot|done)_iter_{it}\.rank(\d+)(?:\.tmp)?$"
        )
        for fn in os.listdir(self.dir):
            m = pat.match(fn)
            if not m or int(m.group(1)) % self.comm.size != self.comm.rank:
                continue
            src = os.path.join(self.dir, fn)
            try:
                os.replace(src, src + ".quarantined")
            except OSError:
                pass

    def _cleanup(self, ranks=None):
        """Rotate old generations.

        Rotation is decided ONCE, while the generation is still fully
        consistent, by writing a tombstone (``rotated_iter_N``); every
        rank's later cleanup sees the tombstone and removes its share, so
        nothing leaks even when each rank deletes only its own files.
        ``ranks``: which ranks' files to delete — all (blocking mode,
        after the barrier) or just our own (async mode, where deleting a
        straggler's files could race its ``maybe_load``; each rank reads
        only its own snapshot, so own-file deletion can never break a
        concurrent load on another rank).  File ownership is
        ``saved_rank % comm.size``, NOT identity: after a rescale the
        dead ranks' leftovers must still have an owner, or a shrunken
        world would leak them forever.

        Quarantined generations rotate on the same ``keep`` budget but
        without tombstones (nothing ever loads them, so deleting them
        can't race anything).
        """
        # One directory snapshot serves every check below (shared/network
        # storage: listings are not free), updated locally as we write
        # tombstones and delete files.
        names = set(os.listdir(self.dir))
        done = self._consistent_generations(names)
        for it in done[: -self.keep] if len(done) > self.keep else []:
            with open(self._tomb(it), "w") as f:
                f.write("rotated")
            names.add(os.path.basename(self._tomb(it)))

        def mine(saved_rank: int) -> bool:
            return ranks is None or \
                saved_rank % self.comm.size in ranks

        pat = re.compile(
            r"(?:snapshot|done)_iter_(\d+)\.rank(\d+)"
            r"(?:\.tmp)?(\.quarantined)?$"
        )
        tombstoned = set(self._tombstoned(names))
        quarantined = self._quarantined_generations(names)
        stale_q = set(
            quarantined[: -self.keep] if len(quarantined) > self.keep
            else []
        )
        for fn in sorted(names):
            m = pat.match(fn)
            if not m:
                continue
            it, saved_rank = int(m.group(1)), int(m.group(2))
            if m.group(3):
                if it not in stale_q:
                    continue
            elif it not in tombstoned:
                continue
            if not mine(saved_rank):
                continue
            try:
                os.remove(os.path.join(self.dir, fn))
                names.discard(fn)
            except OSError:
                pass
        # Drop a tombstone once every live (non-quarantined) file of its
        # generation — including any crash-orphaned .tmp — is gone (any
        # rank may observe this; double-removal is swallowed).
        for it in tombstoned:
            gone = not any(
                (m := pat.match(fn)) is not None
                and int(m.group(1)) == it and not m.group(3)
                for fn in names
            )
            if gone:
                try:
                    os.remove(self._tomb(it))
                except OSError:
                    pass

    def maybe_load(self, state: Any = None) -> Tuple[Any, Optional[int]]:
        """Restore the newest consistent generation, or return ``state``
        untouched when none exists (reference ``maybe_load`` contract).

        With a ``state`` template, every leaf is restored at the
        template's dtype AND placement: replicated/sharded jax Arrays come
        back with the template's sharding (shard-list leaves are
        re-assembled onto the template's addressable devices).

        Integrity: every snapshot verifies its crc32c before any byte is
        trusted.  A corrupt newest generation falls back (with a warning)
        to the next older consistent one — *agreed across ranks*, so a
        generation corrupt on any single rank is skipped by all — and
        *quarantined* (files renamed ``*.quarantined``), so no later
        load re-verifies it.  If every consistent generation is corrupt
        this raises rather than silently restarting from scratch."""
        self.wait()
        done = self._consistent_generations()
        # The per-generation integrity votes below are collectives, so all
        # ranks must iterate the SAME generation list: one rank listing a
        # marker before another (async saves, NFS attribute caching) would
        # otherwise desynchronize the votes.  Agree on the intersection.
        if self.comm.size > 1:
            lists = self.comm.allgather_obj(set(done))
            done = sorted(set.intersection(*map(set, lists)))
        if not done:
            return state, None
        last_err: Optional[BaseException] = None
        for it in reversed(done):
            # Elastic rescale: a generation written by a DIFFERENT world
            # size maps ranks onto save-time snapshots by modulo.  Valid
            # because replicated multi-process state is saved as one
            # full array per rank (any snapshot restores on any rank);
            # per-device shard lists still demand a matching mesh and
            # fail loudly in _restore_leaf.
            world = self._marker_world(it) or self.comm.size
            src = self.comm.rank % max(1, world)
            try:
                loaded = _read_snapshot(self._snap(it, src))
                ok = 1
            except CheckpointCorruptionError as e:
                loaded, ok, last_err = None, 0, e
            # All ranks must restore the same generation: one rank's
            # corruption vetoes the generation everywhere.
            ok_everywhere = (
                bool(ok) if self.comm.size == 1
                else self.comm.allreduce_obj(ok) == self.comm.size
            )
            if not ok_everywhere:
                warnings.warn(
                    f"checkpoint generation {it} is corrupt on at least one "
                    f"rank ({last_err}); quarantining it and falling back "
                    f"to an older generation"
                )
                # Rename, don't re-verify: the rejected generation drops
                # out of _generations for good, so every later load skips
                # straight past it.
                self._quarantine(it)
                continue
            if state is not None:
                loaded = jax.tree.map(
                    _restore_leaf, state, loaded,
                    is_leaf=lambda x: isinstance(x, _ShardList),
                )
            return loaded, it
        raise CheckpointCorruptionError(
            f"all consistent checkpoint generations {done} failed "
            f"integrity verification; refusing to silently restart "
            f"from scratch"
        ) from last_err


def create_multi_node_checkpointer(
    name: str, comm: CommunicatorBase, path: str = ".", keep: int = 2,
    keep_last_n: Optional[int] = None,
) -> MultiNodeCheckpointer:
    """Reference-parity factory (REF:chainermn/extensions/checkpoint.py).
    ``keep_last_n`` overrides ``keep`` and also bounds retained
    quarantined generations (docs/fault_tolerance.md)."""
    return MultiNodeCheckpointer(
        name, comm, path=path, keep=keep, keep_last_n=keep_last_n
    )
