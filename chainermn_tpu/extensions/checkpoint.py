"""Multi-node checkpointer — coordinated snapshot / auto-resume.

Reference: REF:chainermn/extensions/checkpoint.py —
``create_multi_node_checkpointer(name, comm)``: each rank snapshots its
state, the checkpointer tracks the newest *consistent* generation (present
on every rank), deletes stale snapshots, and ``maybe_load`` on startup
restores the latest consistent set before resuming training (SURVEY §5.4).

TPU-native shape: one snapshot file per *process* (host), holding that
host's addressable shards of the state pytree — the sharded-checkpoint
layout orbax standardized, implemented in-repo to keep the framework
self-contained.  Leaves that span non-addressable devices (multi-host
GSPMD arrays, ZeRO-3 flat buffers) are saved as their local shard list and
re-assembled on load against the template's sharding.  Consistency is a
two-phase commit in miniature: write to a temp name, atomic rename, then a
marker file per generation; ``maybe_load`` only accepts generations whose
marker count equals the world size.  On a single host this degrades to
plain snapshot/rotate.

``save(..., block=False)`` runs serialization and file I/O on a background
thread (the device→host transfer stays synchronous, so the training loop
may immediately mutate/donate the live state): checkpoint cost overlaps
the next training steps, the reference-era pattern of pausing the trainer
to snapshot is gone.  ``wait()`` joins the in-flight write; ``save`` and
``maybe_load`` join it implicitly.
"""

from __future__ import annotations

import os
import pickle
import re
import threading
from typing import Any, Optional, Tuple

import jax
import numpy as np

from chainermn_tpu.communicators.base import CommunicatorBase


class _ShardList:
    """Pickled stand-in for a leaf that spans non-addressable devices:
    this process's addressable shards plus each shard's global index
    (``Shard.index``), in ``addressable_shards`` order."""

    def __init__(self, shards, indices):
        self.shards = shards
        self.indices = indices


def _to_host(tree):
    """Device arrays → numpy (this process's addressable data only).

    Non-jax ndarray leaves are copied: the returned tree may be pickled on
    a background thread (async save) while the caller keeps mutating the
    live state, so nothing in it may alias caller-owned buffers."""

    def conv(x):
        if isinstance(x, jax.Array):
            if x.is_fully_addressable:
                return np.asarray(x)
            return _ShardList(
                [np.asarray(s.data) for s in x.addressable_shards],
                [s.index for s in x.addressable_shards],
            )
        if isinstance(x, np.ndarray):
            return np.array(x, copy=True)
        return x

    return jax.tree.map(conv, tree)


def _restore_leaf(tpl, saved):
    """Rebuild one leaf from its saved host form against the template leaf
    (structure, dtype, and — for jax Arrays — sharding/placement)."""
    if isinstance(saved, _ShardList):
        if not isinstance(tpl, jax.Array):
            raise ValueError(
                "checkpoint leaf was saved as device shards but the "
                "template leaf is not a jax.Array"
            )
        tpl_shards = list(tpl.addressable_shards)
        if len(tpl_shards) != len(saved.shards):
            raise ValueError(
                f"checkpoint shard count ({len(saved.shards)}) does not "
                f"match template ({len(tpl_shards)}) — was the mesh resized?"
            )
        tpl_indices = [s.index for s in tpl_shards]
        if saved.indices != tpl_indices:
            raise ValueError(
                "checkpoint shard layout does not match the template's "
                f"sharding (saved indices {saved.indices} vs template "
                f"{tpl_indices}) — restoring would place data at wrong "
                "global offsets; load with the save-time sharding instead"
            )
        arrs = [
            jax.device_put(np.asarray(d).astype(tpl.dtype), s.device)
            for d, s in zip(saved.shards, tpl_shards)
        ]
        return jax.make_array_from_single_device_arrays(
            tpl.shape, tpl.sharding, arrs
        )
    arr = np.asarray(saved)
    if isinstance(tpl, jax.Array):
        return jax.device_put(arr.astype(tpl.dtype), tpl.sharding)
    return arr.astype(getattr(tpl, "dtype", arr.dtype))


class MultiNodeCheckpointer:
    def __init__(
        self,
        name: str,
        comm: CommunicatorBase,
        path: str = ".",
        keep: int = 2,
    ):
        self.name = name
        self.comm = comm
        self.dir = os.path.join(path, name)
        self.keep = keep
        os.makedirs(self.dir, exist_ok=True)
        self._pending: Optional[threading.Thread] = None
        self._pending_error: Optional[BaseException] = None
        # Catch up on rotations a previous run decided but didn't finish
        # (e.g. a rank that never ran another cleanup): drop our own files
        # of tombstoned generations so stale tombstones get released
        # instead of lingering to shadow future saves.
        self._cleanup(ranks=(comm.rank,))

    # -- file layout -----------------------------------------------------
    def _snap(self, iteration: int, rank: int) -> str:
        return os.path.join(self.dir, f"snapshot_iter_{iteration}.rank{rank}")

    def _marker(self, iteration: int, rank: int) -> str:
        return os.path.join(self.dir, f"done_iter_{iteration}.rank{rank}")

    def _tomb(self, iteration: int) -> str:
        return os.path.join(self.dir, f"rotated_iter_{iteration}")

    # -- API (reference: checkpointer.save / maybe_load) ------------------
    def save(self, state: Any, iteration: int, block: bool = True) -> None:
        """Snapshot ``state`` as generation ``iteration``.

        ``block=False``: the device→host transfer happens now (safe to
        donate/mutate the live state immediately), but pickling and file
        I/O run on a background thread — call :meth:`wait` (or let the
        next ``save``/``maybe_load`` do it) to join.
        """
        self.wait()
        rank = self.comm.rank
        # A fresh save of this iteration supersedes any earlier rotation
        # of the same number (dir reuse across runs): clear the tombstone
        # so cleanup cannot delete the checkpoint we are about to write.
        try:
            os.remove(self._tomb(iteration))
        except OSError:
            pass
        host_state = _to_host(state)

        def write():
            tmp = self._snap(iteration, rank) + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self._snap(iteration, rank))
            with open(self._marker(iteration, rank), "w") as f:
                f.write("ok")

        if block:
            write()
            self.comm.barrier()
            # Cleanup only after every rank has committed this generation:
            # deleting a rotated generation before a straggler finished
            # choosing its newest-consistent set could turn its maybe_load
            # into a FileNotFoundError.
            self._cleanup()
        else:
            def run():
                try:
                    write()
                    # No barrier on the background thread; deleting other
                    # ranks' files here could race a straggler's
                    # maybe_load, so each rank rotates only its own.
                    self._cleanup(ranks=(rank,))
                except BaseException as e:  # noqa: BLE001 — surfaced in wait()
                    self._pending_error = e

            self._pending = threading.Thread(target=run, daemon=True)
            self._pending.start()

    def wait(self) -> None:
        """Join an in-flight async save; re-raise its error, if any."""
        t, self._pending = self._pending, None
        if t is not None:
            t.join()
        err, self._pending_error = self._pending_error, None
        if err is not None:
            raise err

    def _generations(self, names=None):
        if names is None:
            names = os.listdir(self.dir)
        pat = re.compile(r"done_iter_(\d+)\.rank(\d+)$")
        gens: dict[int, int] = {}
        for fn in names:
            m = pat.match(fn)
            if m:
                gens[int(m.group(1))] = gens.get(int(m.group(1)), 0) + 1
        for it in self._tombstoned(names):
            gens.pop(it, None)
        return gens

    def _tombstoned(self, names=None):
        if names is None:
            names = os.listdir(self.dir)
        pat = re.compile(r"rotated_iter_(\d+)$")
        return sorted(
            int(m.group(1)) for m in map(pat.match, names) if m
        )

    def _consistent_generations(self, names=None):
        return sorted(
            it
            for it, cnt in self._generations(names).items()
            if cnt >= self.comm.size
        )

    def _cleanup(self, ranks=None):
        """Rotate old generations.

        Rotation is decided ONCE, while the generation is still fully
        consistent, by writing a tombstone (``rotated_iter_N``); every
        rank's later cleanup sees the tombstone and removes its share, so
        nothing leaks even when each rank deletes only its own files.
        ``ranks``: which ranks' files to delete — all (blocking mode,
        after the barrier) or just our own (async mode, where deleting a
        straggler's files could race its ``maybe_load``; each rank reads
        only its own snapshot, so own-file deletion can never break a
        concurrent load on another rank).
        """
        # One directory snapshot serves every check below (shared/network
        # storage: listings are not free), updated locally as we write
        # tombstones and delete files.
        names = set(os.listdir(self.dir))
        done = self._consistent_generations(names)
        if ranks is None:
            ranks = range(self.comm.size)
        for it in done[: -self.keep] if len(done) > self.keep else []:
            with open(self._tomb(it), "w") as f:
                f.write("rotated")
            names.add(os.path.basename(self._tomb(it)))
        for it in self._tombstoned(names):
            for rank in ranks:
                snap = self._snap(it, rank)
                for p in (snap, snap + ".tmp", self._marker(it, rank)):
                    try:
                        os.remove(p)
                        names.discard(os.path.basename(p))
                    except OSError:
                        pass
            # Drop the tombstone once every rank's files — including any
            # crash-orphaned .tmp — are gone (any rank may observe this;
            # double-removal is swallowed).
            gone = not any(
                os.path.basename(p) in names
                for rank in range(self.comm.size)
                for p in (
                    self._snap(it, rank),
                    self._snap(it, rank) + ".tmp",
                    self._marker(it, rank),
                )
            )
            if gone:
                try:
                    os.remove(self._tomb(it))
                except OSError:
                    pass

    def maybe_load(self, state: Any = None) -> Tuple[Any, Optional[int]]:
        """Restore the newest consistent generation, or return ``state``
        untouched when none exists (reference ``maybe_load`` contract).

        With a ``state`` template, every leaf is restored at the
        template's dtype AND placement: replicated/sharded jax Arrays come
        back with the template's sharding (shard-list leaves are
        re-assembled onto the template's addressable devices)."""
        self.wait()
        done = self._consistent_generations()
        if not done:
            return state, None
        it = done[-1]
        with open(self._snap(it, self.comm.rank), "rb") as f:
            loaded = pickle.load(f)
        if state is not None:
            loaded = jax.tree.map(
                _restore_leaf, state, loaded,
                is_leaf=lambda x: isinstance(x, _ShardList),
            )
        return loaded, it


def create_multi_node_checkpointer(
    name: str, comm: CommunicatorBase, path: str = ".", keep: int = 2
) -> MultiNodeCheckpointer:
    """Reference-parity factory (REF:chainermn/extensions/checkpoint.py)."""
    return MultiNodeCheckpointer(name, comm, path=path, keep=keep)
