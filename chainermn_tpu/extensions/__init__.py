from chainermn_tpu.extensions.multi_node_evaluator import (  # noqa: F401
    create_multi_node_evaluator,
    Evaluator,
)
from chainermn_tpu.extensions.checkpoint import (  # noqa: F401
    create_multi_node_checkpointer,
    MultiNodeCheckpointer,
)
