"""Multi-node evaluator — distributed validation metric averaging.

Reference: REF:chainermn/extensions/multi_node_evaluator.py —
``create_multi_node_evaluator(actual_evaluator, communicator)`` replaces the
evaluator's ``evaluate()`` with local-evaluate → ``allreduce_obj`` mean of
the result dict, so each rank evaluates its shard of the validation set and
rank 0's report covers the full set (SURVEY §3.5).

TPU-native shape: metric aggregation happens on two planes —

* across the *devices* of one step's eval batch, inside the jitted eval
  step (a ``pmean``, handled by ``Evaluator.make_eval_step``), and
* across *hosts'* dataset shards, via the communicator's object plane
  (``allreduce_obj``), exactly the reference's mechanism.

``create_multi_node_evaluator`` keeps the reference's duck-typed contract:
give it anything with an ``evaluate() -> dict`` method and it returns the
same object with ``evaluate`` wrapped.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from chainermn_tpu.communicators.base import CommunicatorBase


def _publish_eval_metrics(metrics: Dict[str, float]) -> None:
    """Report the host-aggregated metric dict into whatever telemetry is
    installed: ``eval/<name>`` scalars on the current Reporter and one
    ``{"event": "eval", ...}`` row on the current StepRecorder.  Free
    when telemetry is off."""
    from chainermn_tpu.observability import spans as _spans

    if not _spans.telemetry_active():
        return
    from chainermn_tpu.observability import reporter as _rep
    from chainermn_tpu.observability import step_log as _step_log

    _rep.report({f"eval/{k}": v for k, v in metrics.items()})
    rec = _step_log.current_recorder()
    if rec is not None:
        rec.record("eval", metrics=metrics)


def create_multi_node_evaluator(actual_evaluator, communicator: CommunicatorBase):
    """Wrap ``actual_evaluator.evaluate`` with cross-host metric averaging
    (reference-parity API)."""
    actual_evaluate = actual_evaluator.evaluate
    comm = communicator

    def evaluate(*args, **kwargs):
        local = actual_evaluate(*args, **kwargs)
        n = comm.size
        summed = comm.allreduce_obj(
            {k: float(v) for k, v in local.items()},
            op=lambda a, b: {k: a[k] + b[k] for k in a},
        )
        result = {k: v / n for k, v in summed.items()}
        _publish_eval_metrics(result)
        return result

    actual_evaluator.evaluate = evaluate
    return actual_evaluator


class Evaluator:
    """A minimal evaluator with the shape the reference's examples expect:
    iterate a (host-sharded) dataset, run a jitted metric step over the
    device mesh, average across devices and hosts."""

    def __init__(
        self,
        metric_fn: Callable,
        communicator: CommunicatorBase,
        batch_spec=None,
    ):
        """``metric_fn(params, batch) -> dict[str, scalar]`` on one device's
        shard of the eval batch."""
        self.comm = communicator
        axes = communicator.axes
        if batch_spec is None:
            batch_spec = P(axes if len(axes) > 1 else axes[0])

        def body(params, batch):
            metrics = metric_fn(params, batch)
            return {k: lax.pmean(v, axes) for k, v in metrics.items()}

        self._step = jax.jit(
            communicator.shard_map(
                body, in_specs=(P(), batch_spec), out_specs=P()
            )
        )

    def evaluate(self, params, batches) -> Dict[str, float]:
        from chainermn_tpu.observability.spans import span

        totals: Dict[str, float] = {}
        count = 0
        with span("evaluate"):
            for batch in batches:
                if self.comm.size > 1:
                    # Multi-process: each rank yields its LOCAL slice; the
                    # jitted step wants the device-global batch.  (Every
                    # rank must yield the same number of batches —
                    # guaranteed by scatter_dataset's force_equal_length
                    # default.)
                    batch = self.comm.global_batch(batch)
                out = self._step(params, batch)
                for k, v in out.items():
                    totals[k] = totals.get(k, 0.0) + float(v)
                count += 1
            local = {k: v / max(count, 1) for k, v in totals.items()}
            if self.comm.size > 1:
                summed = self.comm.allreduce_obj(
                    local, op=lambda a, b: {k: a[k] + b[k] for k in a}
                )
                local = {k: v / self.comm.size for k, v in summed.items()}
        _publish_eval_metrics(local)
        return local
