"""Draft proposal sources for speculative decoding.

Two drafters share one contract — a draft is a **pure deterministic
function of the request's own context**, so the proposal never depends
on batch composition, scheduling order, or preemption history, and the
accepted stream can't either:

* :func:`propose_draft` — n-gram prompt-lookup (arXiv:2304.04487 /
  vLLM's ngram speculator): the most recent earlier occurrence of the
  context's trailing n-gram predicts the next few tokens.  Free (no
  parameters, no forward passes) but acceptance length depends on the
  context repeating itself.
* :class:`DraftModel` — a layer-truncated self-draft (LayerSkip /
  Draft&Verify style): a standalone small ``TransformerLM`` whose
  parameters are a strict subset of the target's (embedding, the first
  ``k`` layers, the final norm, and the tied ``embed.attend`` head), run
  greedily under its own jit.  No training, no extra weights to ship —
  the shallow stack is a cheap approximation of the full model that
  proposes useful tokens even on never-repeating contexts.

Acceptance is exact-match (DeepMind-style greedy speculative sampling
specialised to our counter-based sampler): the scheduler samples token
``i`` from the verify logits exactly as sequential decode would have,
accepts while the draft agrees, and always emits the first disagreeing
*sampled* token as a bonus — so every step emits between 1 and
``len(draft) + 1`` tokens and the stream is byte-identical to the
sequential oracle under ANY sampling params.  A bad draft costs wasted
chunk compute, never correctness.  The draft's own greediness is
irrelevant to that contract: under temperature/top-k sampling a greedy
draft just gets accepted less often.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def propose_draft(context: Sequence[int], n_draft: int, *,
                  max_ngram: int = 3, min_ngram: int = 1) -> List[int]:
    """Up to ``n_draft`` draft tokens continuing ``context``.

    Tries the trailing n-gram from ``max_ngram`` down to ``min_ngram``;
    the first n for which the n-gram recurs earlier in the context wins,
    and the tokens following its MOST RECENT earlier occurrence are the
    draft (clipped at the context end, so the draft may be shorter than
    ``n_draft``).  Returns ``[]`` when nothing recurs — the scheduler
    falls back to a plain decode step for that request.
    """
    if n_draft <= 0:
        return []
    ctx = [int(t) for t in context]
    L = len(ctx)
    for n in range(min(max_ngram, L - 1), min_ngram - 1, -1):
        if n <= 0:
            break
        tail = ctx[L - n:]
        for s in range(L - n - 1, -1, -1):
            if ctx[s:s + n] == tail:
                follow = ctx[s + n:s + n + n_draft]
                if follow:
                    return follow
                break  # the match sits flush at the end; shorter n won't
    return []


def longest_accepted(drafts: Sequence[int],
                     sampled: Sequence[int]) -> int:
    """How many leading draft tokens the (sequentially-exact) sampled
    tokens confirm: ``sampled[i]`` is the true token at the position
    ``drafts[i]`` guessed, so acceptance stops at the first mismatch.
    Pure bookkeeping, split out for direct unit testing."""
    n = 0
    for d, s in zip(drafts, sampled):
        if int(d) != int(s):
            break
        n += 1
    return n


def draft_param_names(n_layers: int) -> List[str]:
    """Top-level param-collection keys a ``k``-layer truncated draft
    shares with its target ``TransformerLM``: the embedding (also the
    tied output head via ``embed.attend``), the first ``k`` decoder
    layers, and the final norm."""
    return (["embed"]
            + [f"layer_{i}" for i in range(int(n_layers))]
            + ["final_norm"])


class DraftModel:
    """Layer-truncated self-draft: the target model's first ``n_layers``
    layers run as a standalone small ``TransformerLM`` under a separate
    jit, proposing greedy continuations of a request's context.

    The draft's parameters are a **strict subset** of the target's — no
    training, no second checkpoint, and whatever sharding plan placed
    the target params placed these same arrays (the subset holds
    references, not copies; :meth:`rebind` re-subsets after a
    ``device_put``).  The rollout is ``n_draft`` sequential full-context
    dense forwards, each padded up the engine's prefill bucket ladder so
    the jit cache stays warm; a draft forward touches no paged cache and
    no collectives, so it can never perturb verify state.

    Determinism: greedy argmax over fp32 logits of a fixed function of
    ``context`` — the bit-exactness contract holds regardless of the
    request's own sampling params (see module docstring).
    """

    def __init__(self, lm, params, n_layers: int, buckets):
        import jax
        import jax.numpy as jnp

        from chainermn_tpu.models.transformer import TransformerLM

        if not 1 <= int(n_layers) <= int(lm.n_layers):
            raise ValueError(
                f"draft_layers ({n_layers}) must be in [1, {lm.n_layers}]")
        self.n_layers = int(n_layers)
        self.max_len = int(lm.max_len)
        self.buckets = sorted(int(b) for b in buckets)
        self.model = TransformerLM(
            vocab=lm.vocab, d_model=lm.d_model, n_heads=lm.n_heads,
            d_ff=lm.d_ff, n_layers=self.n_layers, max_len=lm.max_len,
            dtype=lm.dtype, n_kv_heads=lm.n_kv_heads,
        )
        self.params = self._subset(params)
        self._shapes = set()

        def draft_step(params, tokens, length):
            # (1, S) padded tokens; causal masking makes the pad inert
            # for every query at position < length.
            logits = self.model.apply({"params": params}, tokens)
            row = logits[0, jnp.maximum(length - 1, 0)]
            return jnp.argmax(row.astype(jnp.float32)).astype(jnp.int32)

        self._step = jax.jit(draft_step)

    def _subset(self, params):
        missing = [k for k in draft_param_names(self.n_layers)
                   if k not in params]
        if missing:
            raise ValueError(f"target params missing {missing} — not a "
                             "TransformerLM parameter tree?")
        return {k: params[k] for k in draft_param_names(self.n_layers)}

    def rebind(self, params) -> None:
        """Re-subset after the caller re-placed the target params (e.g.
        ``device_put`` under a sharding plan) so the draft shares the
        placed arrays instead of stale host copies."""
        self.params = self._subset(params)

    def _bucket(self, length: int) -> int:
        for b in self.buckets:
            if b >= length:
                return b
        return length

    @property
    def compiles(self) -> int:
        """Distinct (bucketed) shapes the draft step has compiled."""
        return len(self._shapes)

    def propose(self, context: Sequence[int], n_draft: int) -> List[int]:
        """Up to ``n_draft`` greedy draft tokens continuing ``context``
        (clipped so the rollout never runs past ``max_len``)."""
        import jax.numpy as jnp

        if n_draft <= 0:
            return []
        ctx = [int(t) for t in context]
        out: List[int] = []
        for _ in range(int(n_draft)):
            L = len(ctx)
            if L >= self.max_len:
                break
            S = self._bucket(L)
            self._shapes.add(S)
            padded = np.zeros((1, S), np.int32)
            padded[0, :L] = ctx
            tok = int(self._step(self.params, jnp.asarray(padded),
                                 jnp.asarray(L, jnp.int32)))
            out.append(tok)
            ctx.append(tok)
        return out
