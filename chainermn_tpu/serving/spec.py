"""N-gram prompt-lookup drafting for speculative decoding.

The draft model here is the *free* one (prompt-lookup decoding,
arXiv:2304.04487 / vLLM's ngram speculator): natural-language and code
generations repeat their own context heavily, so the most recent earlier
occurrence of the context's trailing n-gram is a cheap, surprisingly
accurate predictor of the next few tokens.  No parameters, no extra
forward passes, and — crucially for this codebase's bit-exactness
contract — a **pure deterministic function of the request's own
context**: the proposal never depends on batch composition, scheduling
order, or preemption history, so the accepted stream can't either.

Acceptance is exact-match (DeepMind-style greedy speculative sampling
specialised to our counter-based sampler): the scheduler samples token
``i`` from the verify logits exactly as sequential decode would have,
accepts while the draft agrees, and always emits the first disagreeing
*sampled* token as a bonus — so every step emits between 1 and
``len(draft) + 1`` tokens and the stream is byte-identical to the
sequential oracle under ANY sampling params.  A bad draft costs wasted
chunk compute, never correctness.
"""

from __future__ import annotations

from typing import List, Sequence


def propose_draft(context: Sequence[int], n_draft: int, *,
                  max_ngram: int = 3, min_ngram: int = 1) -> List[int]:
    """Up to ``n_draft`` draft tokens continuing ``context``.

    Tries the trailing n-gram from ``max_ngram`` down to ``min_ngram``;
    the first n for which the n-gram recurs earlier in the context wins,
    and the tokens following its MOST RECENT earlier occurrence are the
    draft (clipped at the context end, so the draft may be shorter than
    ``n_draft``).  Returns ``[]`` when nothing recurs — the scheduler
    falls back to a plain decode step for that request.
    """
    if n_draft <= 0:
        return []
    ctx = [int(t) for t in context]
    L = len(ctx)
    for n in range(min(max_ngram, L - 1), min_ngram - 1, -1):
        if n <= 0:
            break
        tail = ctx[L - n:]
        for s in range(L - n - 1, -1, -1):
            if ctx[s:s + n] == tail:
                follow = ctx[s + n:s + n + n_draft]
                if follow:
                    return follow
                break  # the match sits flush at the end; shorter n won't
    return []


def longest_accepted(drafts: Sequence[int],
                     sampled: Sequence[int]) -> int:
    """How many leading draft tokens the (sequentially-exact) sampled
    tokens confirm: ``sampled[i]`` is the true token at the position
    ``drafts[i]`` guessed, so acceptance stops at the first mismatch.
    Pure bookkeeping, split out for direct unit testing."""
    n = 0
    for d, s in zip(drafts, sampled):
        if int(d) != int(s):
            break
        n += 1
    return n
