"""Jitted inference engine: bucketed prefill + single-token paged decode.

The engine is the *execution* half of serving (the scheduler is the
*policy* half): it owns the device-side KV pages, the two jitted step
programs, and sampling.  Design constraints, in order:

1. **Bit-stable batching.**  A token stream must not depend on which
   other requests happened to share its decode batch — that is what lets
   the scheduler batch aggressively while `tests/test_serving.py` pins
   batched == sequential.  Everything per-sequence: the paged attention
   reduces only within one sequence's gathered context, padding rows
   write to the dropped invalid page, and sampling is host-side per
   request (greedy argmax on fp32 logits; temperature/top-k from a
   per-request counter-based RNG independent of batch composition).
2. **Bounded recompiles.**  jit re-traces per shape, so every host-side
   shape is padded to a static bucket: prompt length (pow2 ladder),
   decode batch (pow2 up to ``max_batch``), and block-table width (pow2
   pages).  The compile count is the number of *buckets touched*, not
   the number of requests — pinned by the recompile-count test.
3. **CPU-safe.**  The data plane is pure jnp (gather/scatter + einsum
   softmax, :mod:`chainermn_tpu.ops.decode_attention`), so the tier-1
   suite runs the whole engine under ``JAX_PLATFORMS=cpu``; on TPU the
   same program picks up the tuned gather chunk
   (``tuning.lookup_decode_block_ctx``) with identical numerics.

The decode data plane is collective-free by construction — no psum ever
belongs in a per-sequence cache read — and stays that way via the
``serving_decode`` lint fixture and the
``tests/golden/serving_decode_census.json`` golden.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from chainermn_tpu.communicators import quant
from chainermn_tpu.models.transformer import TransformerLM
from chainermn_tpu.serving.kv_cache import PagedKVCache
from chainermn_tpu.serving.spec import DraftModel, propose_draft as _ngram_draft

#: draft proposal sources the engine can dispatch to.
DRAFT_SOURCES = ("ngram", "model")
ENV_DRAFT = "CHAINERMN_TPU_DRAFT"
ENV_PREFILL_CHUNK = "CHAINERMN_TPU_PREFILL_CHUNK"
#: largest default chunk bucket (tokens) — the T ladder for slices and
#: verify windows is capped here and grows lazily beyond (see
#: ``EngineConfig.max_len_growth``), so a 128k ``max_len`` does not
#: pre-declare a 128k-token chunk program.
DEFAULT_CHUNK_CAP = 4096


def _resolve_draft(cfg: "EngineConfig", lm: TransformerLM) -> str:
    """``draft`` source resolution, same order as ``kv_dtype``: explicit
    config -> ``CHAINERMN_TPU_DRAFT`` env -> autotune cache (inert under
    pytest / off-TPU) -> ``"ngram"``."""
    import os

    if cfg.draft is not None:
        if cfg.draft not in DRAFT_SOURCES:
            raise ValueError(
                f"draft must be one of {DRAFT_SOURCES}, got {cfg.draft!r}")
        return cfg.draft
    env = os.environ.get(ENV_DRAFT)
    if env is not None:
        return env if env in DRAFT_SOURCES else "ngram"
    try:
        from chainermn_tpu.tuning import lookup_draft
    except ImportError:  # pragma: no cover - partial installs
        return "ngram"
    return lookup_draft(
        vocab=lm.vocab, d_model=lm.d_model, n_layers=lm.n_layers,
        max_len=cfg.max_len, dtype=lm.dtype,
    ) or "ngram"


def _resolve_prefill_chunk(cfg: "EngineConfig") -> int:
    """``prefill_chunk`` resolution (0 = off): explicit config ->
    ``CHAINERMN_TPU_PREFILL_CHUNK`` env -> autotune cache -> off."""
    import os

    if cfg.prefill_chunk is not None:
        return max(0, int(cfg.prefill_chunk))
    env = os.environ.get(ENV_PREFILL_CHUNK)
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            return 0
    try:
        from chainermn_tpu.tuning import lookup_prefill_chunk
    except ImportError:  # pragma: no cover - partial installs
        return 0
    return lookup_prefill_chunk(
        max_len=cfg.max_len, block_size=cfg.block_size,
    ) or 0


def _resolve_kv_dtype(cfg: "EngineConfig", lm: TransformerLM):
    """``kv_dtype`` resolution, mirroring the comm side's ctor -> env ->
    tuned -> off order: an explicit config value (any spelling,
    including ``"none"``) wins outright; an unset one consults the
    ``CHAINERMN_TPU_KV_DTYPE`` env, then the autotune cache (inert under
    pytest / off-TPU)."""
    import os

    if cfg.kv_dtype is not None:
        return quant.canonical_kv_dtype(cfg.kv_dtype)
    env = os.environ.get(quant.ENV_KV_DTYPE)
    if env is not None:
        try:
            return quant.canonical_kv_dtype(env)
        except ValueError:
            return None
    try:
        from chainermn_tpu.tuning import lookup_kv_dtype
    except ImportError:  # pragma: no cover - partial installs
        return None
    n_kv = lm.n_kv_heads or lm.n_heads
    return lookup_kv_dtype(
        n_pages=cfg.n_blocks, page_size=cfg.block_size, n_kv=n_kv,
        d_head=lm.d_model // lm.n_heads, dtype=lm.dtype,
    )


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling policy.  ``temperature == 0`` is greedy
    (argmax, RNG never consulted); otherwise softmax sampling at the
    given temperature, optionally truncated to the ``top_k`` most likely
    tokens.  ``seed`` plus the token position form a counter-based RNG,
    so a request's stream is reproducible and independent of batching."""

    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static geometry of the serving engine.

    ``n_blocks * block_size`` is the total KV pool in tokens;
    ``max_len`` bounds any single sequence (prompt + generated);
    ``max_batch`` is the widest decode iteration.  Buckets are pow2
    ladders derived from these unless given explicitly."""

    block_size: int = 16
    n_blocks: int = 256
    max_len: int = 2048
    max_batch: int = 8
    #: enable the prefix index / CoW sharing in the page accounting.
    prefix_cache: bool = True
    #: KV page storage dtype: ``"int8"`` stores pages quantized with
    #: per-token-per-head scales (docs/serving.md — ~half the pool bytes
    #: per token, bounded decode error); ``None`` resolves
    #: ``CHAINERMN_TPU_KV_DTYPE`` -> tuned value -> model dtype;
    #: ``"none"`` pins full precision.
    kv_dtype: Optional[str] = None
    prefill_buckets: Optional[Tuple[int, ...]] = None
    batch_buckets: Optional[Tuple[int, ...]] = None
    table_width_buckets: Optional[Tuple[int, ...]] = None
    #: T ladder for the multi-token chunk step (speculative verify and
    #: prefix-hit suffix prefill share one jitted program).
    chunk_buckets: Optional[Tuple[int, ...]] = None
    #: speculative draft source: ``"ngram"`` (prompt lookup, free) or
    #: ``"model"`` (layer-truncated self-draft under its own jit);
    #: ``None`` resolves ``CHAINERMN_TPU_DRAFT`` -> tuned value ->
    #: ``"ngram"``.  Either source is verified by the same chunk step,
    #: so streams stay bit-exact regardless.
    draft: Optional[str] = None
    #: layers in the truncated draft (``draft="model"`` only); ``None``
    #: = ``max(1, n_layers // 2)``.  ``n_layers`` gives an exact (but
    #: pointless in production) draft — useful for acceptance tests.
    draft_layers: Optional[int] = None
    #: chunked prefill: prompts whose un-cached suffix exceeds this many
    #: tokens prefill in slices of this size, interleaved with decode
    #: iterations (bounds decode p99 under long-prompt arrival).
    #: ``None`` resolves ``CHAINERMN_TPU_PREFILL_CHUNK`` -> tuned value
    #: -> 0 (off); 0 pins off.
    prefill_chunk: Optional[int] = None
    #: sequence-parallel prefill: shard the chunk program's token axis
    #: over this many devices (pow2; the ``sp`` registry plan supplies
    #: the replicated placement), so one slice's activations and K/V
    #: transients split across chips.  Decode is untouched — it stays
    #: single-program and collective-free.  0/1 = off.
    sp: int = 0
    #: lazily extend the prompt/chunk/table-width bucket ladders (next
    #: pow2, capped at ``max_len`` worth of tokens/pages) instead of
    #: raising when a value overflows the ladder — each extension costs
    #: exactly one traced recompile on THIS replica only (the fleet
    #: routes long prompts to replicas whose ladders are already warm
    #: via the gossiped ``max_bucket``).  False pins the pre-growth
    #: hard-error behavior.
    max_len_growth: bool = True

    def resolved(self) -> "EngineConfig":
        def pow2_ladder(lo, hi):
            out, v = [], lo
            while v < hi:
                out.append(v)
                v *= 2
            out.append(hi)
            return tuple(sorted(set(out)))

        max_pages = -(-self.max_len // self.block_size)
        return dataclasses.replace(
            self,
            prefill_buckets=self.prefill_buckets
            or pow2_ladder(min(16, self.max_len), self.max_len),
            batch_buckets=self.batch_buckets
            or pow2_ladder(1, self.max_batch),
            table_width_buckets=self.table_width_buckets
            or pow2_ladder(1, max_pages),
            # The default chunk ladder stops at DEFAULT_CHUNK_CAP:
            # chunk rows are prefill slices and verify windows, both
            # small by design, so max_len=131072 must not imply 17
            # compiled chunk programs.  Longer rows (a prefix-cached
            # suffix without chunked prefill) grow the ladder lazily.
            chunk_buckets=self.chunk_buckets
            or pow2_ladder(1, min(self.max_len, DEFAULT_CHUNK_CAP)),
        )


def _bucket(value: int, buckets: Tuple[int, ...], what: str) -> int:
    for b in buckets:
        if value <= b:
            return b
    raise ValueError(f"{what} {value} exceeds the largest bucket "
                     f"{buckets[-1]}")


class InferenceEngine:
    """Cached-KV inference over a trained :class:`TransformerLM`.

    ``lm`` is the model the ``params`` were trained with (any ``decode``
    / ``paged`` setting — prefill and decode twins are constructed here,
    sharing the trained parameter structure).  The engine owns:

    * ``kv`` — the :class:`PagedKVCache` page accounting;
    * the device pages (flax ``cache`` collection of both twins);
    * the two jitted steps and their bucket bookkeeping.
    """

    def __init__(self, lm: TransformerLM, params,
                 config: Optional[EngineConfig] = None, *,
                 plan=None, mesh=None):
        cfg = (config or EngineConfig(max_len=lm.max_len)).resolved()
        if cfg.max_len > lm.max_len:
            raise ValueError(
                f"config.max_len {cfg.max_len} exceeds the model's "
                f"max_len {lm.max_len}"
            )
        self.config = cfg
        self.params = params["params"] if "params" in params else params
        self.lm = lm
        self.kv = PagedKVCache(cfg.n_blocks, cfg.block_size,
                               prefix_cache=cfg.prefix_cache)

        self.kv_dtype = _resolve_kv_dtype(cfg, lm)
        twin = dict(
            vocab=lm.vocab, d_model=lm.d_model, n_heads=lm.n_heads,
            d_ff=lm.d_ff, n_layers=lm.n_layers, max_len=lm.max_len,
            dtype=lm.dtype, n_kv_heads=lm.n_kv_heads,
            page_count=cfg.n_blocks, page_size=cfg.block_size,
            kv_dtype=self.kv_dtype,
        )
        self._prefill_model = TransformerLM(**twin, paged="prefill")
        self._decode_model = TransformerLM(**twin, paged="decode")
        self._chunk_model = TransformerLM(**twin, paged="chunk")

        # Mutable bucket ladders: start from the resolved config and
        # extend lazily (next pow2, capped) when max_len_growth is on —
        # a long prompt costs one extra trace on this replica instead
        # of a hard error, and the growth count is pinned in stats().
        self._prefill_buckets = list(cfg.prefill_buckets)
        self._table_buckets = list(cfg.table_width_buckets)
        self._chunk_buckets = list(cfg.chunk_buckets)
        self._table_cap = max(1, -(-cfg.max_len // cfg.block_size))
        self._bucket_growths = 0
        self._max_prefilled = 0

        # Sequence-parallel prefill (docs/serving.md): a fourth jitted
        # program — the chunk step under shard_map over the 'sp' mesh
        # axis — used for single-row slices whose T bucket the axis
        # divides.  Placement (params/cache replicated) comes from the
        # 'sp' registry plan.
        self.sp = int(cfg.sp) if cfg.sp and int(cfg.sp) > 1 else 0
        self._sp_mesh = None
        self._sp_chunk_model = None
        if self.sp:
            if self.sp & (self.sp - 1):
                raise ValueError(
                    f"sp must be a power of two (it has to divide the "
                    f"pow2 chunk buckets), got {self.sp}"
                )
            if plan is not None:
                raise ValueError(
                    "sp prefill and an explicit tensor-parallel plan "
                    "are mutually exclusive: sp brings its own mesh "
                    "and the 'sp' registry plan"
                )
            devs = jax.devices() if mesh is None else list(
                np.asarray(mesh.devices).reshape(-1)
            )
            if len(devs) < self.sp:
                raise ValueError(
                    f"sp={self.sp} needs {self.sp} devices, have "
                    f"{len(devs)}"
                )
            from jax.sharding import Mesh

            self._sp_mesh = Mesh(np.asarray(devs[: self.sp]), ("sp",))
            self._sp_chunk_model = TransformerLM(
                **twin, paged="chunk", sp_axis="sp"
            )
            plan, mesh = "sp", self._sp_mesh

        # Cache geometry without allocating a throwaway param set; zeros
        # ARE the empty pages (every table slot starts invalid, so stale
        # page contents are unreachable anyway).
        W0 = cfg.table_width_buckets[0]
        cache_shapes = jax.eval_shape(
            lambda: self._prefill_model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
                block_tables=jnp.zeros((1, W0), jnp.int32),
                seq_lens=jnp.zeros((1,), jnp.int32),
            )["cache"]
        )
        self._cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes
        )

        # Quantized engines also pull the "intermediates" collection (the
        # per-layer kv round-trip errors sown by MultiHeadAttention) and
        # return their max, the serve/kv_quant_err gauge's source.  The
        # default path keeps the exact two-output signature it always had.
        kv_q = self.kv_dtype is not None
        muts = ["cache", "intermediates"] if kv_q else ["cache"]

        def _kv_err(upd):
            leaves = jax.tree.leaves(upd.get("intermediates", {}))
            if not leaves:
                return jnp.zeros((), jnp.float32)
            return jnp.max(jnp.stack([l.astype(jnp.float32) for l in leaves]))

        def prefill_step(params, cache, tokens, block_tables, seq_lens):
            logits, upd = self._prefill_model.apply(
                {"params": params, "cache": cache}, tokens,
                block_tables=block_tables, seq_lens=seq_lens,
                mutable=muts,
            )
            # Logits of the LAST PROMPT TOKEN per row — what samples the
            # first generated token.  (Padding rows index position 0 of
            # garbage; callers never read them.)
            idx = jnp.maximum(seq_lens - 1, 0)[:, None, None]
            last = jnp.take_along_axis(
                logits, jnp.broadcast_to(
                    idx, (logits.shape[0], 1, logits.shape[2])
                ), axis=1,
            )[:, 0]
            if kv_q:
                return last.astype(jnp.float32), upd["cache"], _kv_err(upd)
            return last.astype(jnp.float32), upd["cache"]

        def decode_step(params, cache, tokens, block_tables, seq_lens):
            logits, upd = self._decode_model.apply(
                {"params": params, "cache": cache}, tokens[:, None],
                position_offset=jnp.maximum(seq_lens, 0)[:, None],
                block_tables=block_tables, seq_lens=seq_lens,
                mutable=muts,
            )
            if kv_q:
                return (logits[:, 0].astype(jnp.float32), upd["cache"],
                        _kv_err(upd))
            return logits[:, 0].astype(jnp.float32), upd["cache"]

        def chunk_step(params, cache, tokens, block_tables, start_lens):
            # T tokens per row starting at context position start_lens[b]
            # (< 0 = padding row, writes drop, mask hides everything).
            T = tokens.shape[1]
            offs = (jnp.maximum(start_lens, 0)[:, None]
                    + jnp.arange(T, dtype=jnp.int32)[None])
            logits, upd = self._chunk_model.apply(
                {"params": params, "cache": cache}, tokens,
                position_offset=offs,
                block_tables=block_tables, seq_lens=start_lens,
                mutable=muts,
            )
            if kv_q:
                return logits.astype(jnp.float32), upd["cache"], _kv_err(upd)
            return logits.astype(jnp.float32), upd["cache"]

        def cow_step(cache, old, new):
            # Device half of a copy-on-write split: duplicate page `old`
            # into the freshly-allocated page `new` on every cache leaf.
            # old/new are traced scalars, so every split shares ONE
            # compiled program.
            return jax.tree.map(lambda l: l.at[new].set(l[old]), cache)

        # donate the pages: each step consumes the previous step's cache,
        # so the (large) page buffers update in place where the backend
        # supports aliasing.
        self._prefill_jit = jax.jit(prefill_step, donate_argnums=(1,))
        self._decode_jit = jax.jit(decode_step, donate_argnums=(1,))
        self._chunk_jit = jax.jit(chunk_step, donate_argnums=(1,))
        self._cow_jit = jax.jit(cow_step, donate_argnums=(0,))

        self._sp_chunk_jit = None
        if self.sp:
            from jax.sharding import PartitionSpec as P

            from chainermn_tpu.communicators.base import shard_map_compat

            def sp_chunk_step(params, cache, tokens, block_tables,
                              start_lens):
                # Shard body: tokens is this shard's C = T/sp
                # consecutive slice tokens; start_lens carries the
                # GLOBAL slice start (replicated).  The model gathers
                # the full slice's K/V, writes it whole (identical on
                # every shard, so the cache output is validly declared
                # replicated), and attends the local queries — the
                # per-shard attention start offset (r*C) is added
                # inside the layer; positions here are global.
                import jax.lax as _lax

                C = tokens.shape[1]
                r = _lax.axis_index("sp")
                offs = (jnp.maximum(start_lens, 0)[:, None] + r * C
                        + jnp.arange(C, dtype=jnp.int32)[None])
                logits, upd = self._sp_chunk_model.apply(
                    {"params": params, "cache": cache}, tokens,
                    position_offset=offs,
                    block_tables=block_tables, seq_lens=start_lens,
                    mutable=muts,
                )
                if kv_q:
                    return (logits.astype(jnp.float32), upd["cache"],
                            _kv_err(upd))
                return logits.astype(jnp.float32), upd["cache"]

            out_specs = (P(None, "sp"), P()) + ((P(),) if kv_q else ())
            self._sp_chunk_jit = jax.jit(
                shard_map_compat(
                    sp_chunk_step, self._sp_mesh,
                    in_specs=(P(), P(), P(None, "sp"), P(), P()),
                    out_specs=out_specs,
                ),
                donate_argnums=(1,),
            )

        #: shard-group mirror hook: when set (the leader of a TP shard
        #: group), every device-mutating step — prefill/decode/chunk/
        #: CoW/defrag — first emits ``(op, host payload)`` here, and a
        #: follower replays it with :meth:`apply_step`.  The payload is
        #: exactly the host-side arrays the jit call consumes, so the
        #: replayed program is the SAME compiled program: on CPU the
        #: mirrored caches stay bit-identical, on a real TP mesh each
        #: process runs its shard of the one GSPMD program in lockstep.
        self.mirror_sink = None
        #: decode microbatching for the tp×pp serving mode: when > 1,
        #: each decode iteration splits its rows into this many
        #: contiguous microbatches (``parallel/pipeline.py`` supplies
        #: the fill order) and runs one step per microbatch — on a
        #: shard group the stage subgroups overlap those steps.
        #: Bit-exact by construction: attention is per-sequence and
        #: sampling counter-based, so no stream's tokens depend on
        #: batch composition.
        self.pp_stages = 1
        self._prefill_shapes: set = set()
        self._decode_shapes: set = set()
        self._chunk_shapes: set = set()
        self._sp_shapes: set = set()
        self._tokens_decoded = 0
        self._tokens_prefilled = 0
        self._tokens_chunked = 0
        self._tokens_prefix_cached = 0
        self._cow_splits = 0
        self._kv_quant_err = 0.0

        self.plan = None
        self.mesh = None
        if plan is not None:
            self._apply_plan(plan, mesh)

        # Draft source + chunked prefill (resolution: config -> env ->
        # tuned -> default, like kv_dtype above).  The draft model is
        # built AFTER plan placement so its param subset references the
        # placed arrays, not stale host copies.
        self.draft_source = _resolve_draft(cfg, lm)
        self.prefill_chunk = _resolve_prefill_chunk(cfg)
        self.draft_model: Optional[DraftModel] = None
        if self.draft_source == "model":
            k = cfg.draft_layers
            if not k:
                try:
                    from chainermn_tpu.tuning import lookup_draft_layers

                    k = lookup_draft_layers(
                        vocab=lm.vocab, d_model=lm.d_model,
                        n_layers=lm.n_layers, max_len=cfg.max_len,
                        dtype=lm.dtype,
                    )
                except ImportError:  # pragma: no cover
                    k = None
            k = k or max(1, lm.n_layers // 2)
            self.draft_model = DraftModel(
                lm, self.params, k, cfg.prefill_buckets
            )

    def _apply_plan(self, plan, mesh) -> None:
        """Tensor-parallel placement from a sharding plan: device_put
        the params and the KV pages with the plan's resolved
        NamedShardings (the ``tp`` table shards attention heads / FFN
        hidden on the params and the KV-head axis of ``k_pages`` /
        ``v_pages``).  The jitted step programs are untouched — GSPMD
        propagates the input shardings through the same prefill /
        decode / chunk programs, so the single-device path stays
        byte-identical and the TP token stream is pinned bit-exact
        against it by ``tests/test_shardplan.py``."""
        from chainermn_tpu.sharding import ShardingPlan, get_plan

        if isinstance(plan, str):
            plan = get_plan(plan)
        if not isinstance(plan, ShardingPlan):
            raise TypeError(
                f"plan must be a ShardingPlan or registry name, got "
                f"{type(plan).__name__}"
            )
        if mesh is None:
            raise ValueError(
                f"plan {plan.name!r} needs mesh=: the plan only names "
                "axes; the mesh supplies the devices behind them"
            )
        missing = set(plan.axes) - set(mesh.axis_names)
        if missing:
            raise ValueError(
                f"plan {plan.name!r} shards over axes {sorted(missing)} "
                f"the mesh lacks (mesh axes: {tuple(mesh.axis_names)})"
            )
        self.plan = plan
        self.mesh = mesh
        self.params = jax.device_put(
            self.params, plan.shardings(mesh, self.params)
        )
        # Placement, not a replayed step: followers run _apply_plan
        # themselves at attach (the plan is part of engine construction,
        # not the mirrored op stream), so no mirror emit here.
        self._cache = jax.device_put(  # hostlint: disable=H003
            self._cache, plan.shardings(mesh, self._cache)
        )
        if getattr(self, "draft_model", None) is not None:
            self.draft_model.rebind(self.params)

    # -- shard-group mirroring -----------------------------------------
    def _mirror(self, op: str, *payload) -> None:
        if self.mirror_sink is not None:
            self.mirror_sink(op, payload)

    def apply_step(self, op: str, payload) -> None:
        """Replay one mirrored device step — the follower half of a TP
        shard group.  ``(op, payload)`` is what the leader's
        ``mirror_sink`` emitted; the follower drives the same jitted
        program over its own params/cache (same seed-derived values,
        same plan placement) and keeps only the cache update — logits
        are discarded, sampling and all host accounting are
        leader-only."""
        if op == "prefill":
            padded, table, lens = payload
            out = self._prefill_jit(
                self.params, self._cache, jnp.asarray(padded),
                jnp.asarray(table), jnp.asarray(lens),
            )
            self._cache = out[1]
        elif op == "decode":
            tok, tables, lens = payload
            out = self._decode_jit(
                self.params, self._cache, jnp.asarray(tok),
                jnp.asarray(tables), jnp.asarray(lens),
            )
            self._cache = out[1]
        elif op == "chunk":
            tok, tables, start, use_sp = payload
            step = self._sp_chunk_jit if use_sp else self._chunk_jit
            out = step(
                self.params, self._cache, jnp.asarray(tok),
                jnp.asarray(tables), jnp.asarray(start),
            )
            self._cache = out[1]
        elif op == "cow":
            old, new = payload
            self._cache = self._cow_jit(
                self._cache, jnp.asarray(old, jnp.int32),
                jnp.asarray(new, jnp.int32),
            )
        elif op == "defrag":
            (perm,) = payload
            iperm = jnp.asarray(perm)
            self._cache = jax.tree.map(
                lambda leaf: jnp.take(leaf, iperm, axis=0), self._cache
            )
        else:
            raise ValueError(f"unknown mirrored op {op!r}")

    # -- geometry ------------------------------------------------------
    @property
    def max_batch(self) -> int:
        return self.config.max_batch

    @property
    def max_bucket(self) -> int:
        """Longest context (tokens) this replica has actually run a
        prefill or chunk program over — "my ladders, jit caches and
        pages are warm up to here".  Gossiped in ``ReplicaLoad`` so the
        router can steer a long prompt to a replica that will serve it
        without a cold trace (and, mid-prefill, to the replica already
        streaming that document's pages)."""
        return self._max_prefilled

    def _bucket_grow(self, value: int, ladder: List[int], cap: int,
                     what: str) -> int:
        """Bucket ``value`` on a mutable ladder, extending it (next
        pow2, capped at ``cap``) instead of raising when
        ``max_len_growth`` is on.  Every appended bucket is about to be
        traced by the caller, so the growth count IS the extra-compile
        count — pinned via ``stats()['bucket_growths']``."""
        for b in ladder:
            if value <= b:
                return b
        if not self.config.max_len_growth or value > cap:
            raise ValueError(f"{what} {value} exceeds the largest bucket "
                             f"{ladder[-1]}")
        while ladder[-1] < value:
            ladder.append(min(ladder[-1] * 2, cap))
            self._bucket_growths += 1
        return ladder[-1]

    def table_width(self, n_tokens: int) -> int:
        """Bucketed block-table width for a context of ``n_tokens``."""
        return self._bucket_grow(
            max(1, self.kv.blocks_for(n_tokens)),
            self._table_buckets, self._table_cap, "table width",
        )

    # -- steps ---------------------------------------------------------
    def prefill(self, token_ids, seq_id) -> np.ndarray:
        """Run one prompt (host int sequence) through the prefill step,
        writing its K/V into the pages of the already-allocated
        ``seq_id``.  Returns the fp32 (vocab,) logits of the last prompt
        token.  One sequence per call: per-request prefill keeps the
        compiled shapes to one ladder and the token stream independent
        of co-arrivals."""
        toks = np.asarray(token_ids, np.int32).reshape(-1)
        L = len(toks)
        if L == 0:
            raise ValueError("empty prompt")
        if L >= self.config.max_len:
            raise ValueError(
                f"prompt of {L} tokens leaves no room to generate within "
                f"max_len {self.config.max_len}"
            )
        S = self._bucket_grow(L, self._prefill_buckets,
                              self.config.max_len, "prompt length")
        W = self.table_width(L)
        padded = np.zeros((1, S), np.int32)
        padded[0, :L] = toks
        table = self.kv.padded_table(seq_id, W)[None]
        self._prefill_shapes.add((S, W))
        self._mirror("prefill", padded, table, np.asarray([L], np.int32))
        out = self._prefill_jit(
            self.params, self._cache, jnp.asarray(padded),
            jnp.asarray(table), jnp.asarray([L], np.int32),
        )
        last, self._cache = out[0], out[1]
        if self.kv_dtype is not None:
            self._note_kv_err(out[2])
        self._tokens_prefilled += L
        self._max_prefilled = max(self._max_prefilled, L)
        return np.asarray(last[0])

    def decode(self, tokens, seq_ids, seq_lens) -> np.ndarray:
        """One decode iteration: for each running sequence, write the
        given (just-sampled) token at position ``seq_lens[i]`` and
        return the fp32 (B, vocab) logits predicting the next one.

        ``tokens``/``seq_ids``/``seq_lens`` are parallel host lists; the
        batch is padded to its pow2 bucket with inert rows (invalid
        tables, ``seq_len = -1`` → the page write drops, the gather
        masks to nothing).

        With ``pp_stages > 1`` the iteration splits into per-stage
        microbatches dispatched as separate steps (same per-row
        results — batch composition never changes a stream).
        """
        B = len(tokens)
        if self.pp_stages > 1 and B > 1:
            from chainermn_tpu.parallel.pipeline import (
                decode_microbatches,
            )

            return np.concatenate([
                self._decode_step(tokens[a:b], seq_ids[a:b],
                                  seq_lens[a:b])
                for a, b in decode_microbatches(B, self.pp_stages)
            ], axis=0)
        return self._decode_step(tokens, seq_ids, seq_lens)

    def _decode_step(self, tokens, seq_ids, seq_lens) -> np.ndarray:
        B = len(tokens)
        if B == 0:
            raise ValueError("empty decode batch")
        if B > self.config.max_batch:
            raise ValueError(
                f"decode batch {B} exceeds max_batch "
                f"{self.config.max_batch}"
            )
        Bp = _bucket(B, self.config.batch_buckets, "decode batch")
        W = max(
            self.table_width(int(l) + 1) for l in seq_lens
        )
        tok = np.zeros((Bp,), np.int32)
        tok[:B] = np.asarray(tokens, np.int32)
        lens = np.full((Bp,), -1, np.int32)
        lens[:B] = np.asarray(seq_lens, np.int32)
        tables = np.full((Bp, W), self.kv.invalid, np.int32)
        for i, sid in enumerate(seq_ids):
            tables[i] = self.kv.padded_table(sid, W)
        self._decode_shapes.add((Bp, W))
        self._mirror("decode", tok, tables, lens)
        out = self._decode_jit(
            self.params, self._cache, jnp.asarray(tok),
            jnp.asarray(tables), jnp.asarray(lens),
        )
        logits, self._cache = out[0], out[1]
        if self.kv_dtype is not None:
            self._note_kv_err(out[2])
        self._tokens_decoded += B
        return np.asarray(logits[:B])

    def chunk(self, token_rows, seq_ids, start_lens) -> np.ndarray:
        """One multi-token step: for each row, write ``len(token_rows[i])``
        consecutive tokens starting at context position ``start_lens[i]``
        and return fp32 (B, T, vocab) logits — ``logits[i, t]`` predicts
        position ``start_lens[i] + t + 1``, exactly what ``len(row)``
        sequential :meth:`decode` calls would have produced (bit-exact:
        the T=1 lowering is shared, and each query carries its own
        causal bound).

        This one program serves both speculative *verify* (row =
        pending token + draft) and prefix-cache *suffix prefill* (row =
        the un-shared prompt tail).  Rows may over-run a sequence's real
        suffix (draft tokens, T-bucket padding): those writes land
        beyond the masked context and are rewritten by a later step
        before any mask exposes them.
        """
        B = len(token_rows)
        if B == 0:
            raise ValueError("empty chunk batch")
        if B > self.config.max_batch:
            raise ValueError(
                f"chunk batch {B} exceeds max_batch {self.config.max_batch}"
            )
        Tmax = max(len(r) for r in token_rows)
        if Tmax == 0:
            raise ValueError("empty chunk row")
        T = self._bucket_grow(Tmax, self._chunk_buckets,
                              self.config.max_len, "chunk length")
        Bp = _bucket(B, self.config.batch_buckets, "decode batch")
        W = max(self.table_width(self.kv.seq_len(sid)) for sid in seq_ids)
        # Sequence-parallel routing: single-row slices whose T bucket
        # the sp axis divides run under the shard_map program (bit-
        # identical — the gather is pure concatenation); everything
        # else (multi-row verify batches, tiny buckets) stays on the
        # single-device chunk program.
        use_sp = bool(self.sp and B == 1 and T % self.sp == 0)
        tok = np.zeros((Bp, T), np.int32)
        start = np.full((Bp,), -1, np.int32)
        tables = np.full((Bp, W), self.kv.invalid, np.int32)
        for i, (row, sid, s) in enumerate(
            zip(token_rows, seq_ids, start_lens)
        ):
            tok[i, : len(row)] = np.asarray(row, np.int32)
            start[i] = int(s)
            tables[i] = self.kv.padded_table(sid, W)
        if use_sp:
            self._sp_shapes.add((Bp, T, W))
            step = self._sp_chunk_jit
        else:
            self._chunk_shapes.add((Bp, T, W))
            step = self._chunk_jit
        self._mirror("chunk", tok, tables, start, use_sp)
        out = step(
            self.params, self._cache, jnp.asarray(tok),
            jnp.asarray(tables), jnp.asarray(start),
        )
        logits, self._cache = out[0], out[1]
        if self.kv_dtype is not None:
            self._note_kv_err(out[2])
        self._tokens_chunked += sum(len(r) for r in token_rows)
        covered = max(
            (int(s) + len(r)
             for r, s in zip(token_rows, start_lens) if int(s) >= 0),
            default=0,
        )
        self._max_prefilled = max(self._max_prefilled, covered)
        return np.asarray(logits[:B])

    def prefill_cached(self, token_ids, seq_id, n_cached: int) -> np.ndarray:
        """Prefill a prompt whose first ``n_cached`` tokens are already
        covered by shared prefix pages: only the suffix runs through the
        chunk step (attending over the cached pages).  Returns the fp32
        (vocab,) logits of the last prompt token — bit-identical to what
        a full :meth:`prefill` would have produced.  ``n_cached`` must
        leave at least one suffix token (the fully-cached case needs the
        rewind path: CoW the last page, re-decode the final token)."""
        toks = np.asarray(token_ids, np.int32).reshape(-1)
        L = len(toks)
        if n_cached <= 0:
            return self.prefill(toks, seq_id)
        if n_cached >= L:
            raise ValueError(
                f"n_cached {n_cached} leaves no suffix for a prompt of "
                f"{L} tokens (use the CoW rewind path)"
            )
        if L >= self.config.max_len:
            raise ValueError(
                f"prompt of {L} tokens leaves no room to generate within "
                f"max_len {self.config.max_len}"
            )
        suffix = [int(t) for t in toks[n_cached:]]
        logits = self.chunk([suffix], [seq_id], [n_cached])
        self._tokens_prefilled += len(suffix)
        self._tokens_prefix_cached += n_cached
        return logits[0, len(suffix) - 1]

    def make_writable(self, seq_id, position: int) -> bool:
        """Copy-on-write guard before a K/V write at ``position``:
        delegates the accounting to :meth:`PagedKVCache.make_writable`
        and, when a split happened, copies the device page so the
        writer's fresh page starts as an exact replica.  Returns whether
        a split happened.  May raise
        :class:`~chainermn_tpu.serving.kv_cache.OutOfBlocks`."""
        split = self.kv.make_writable(seq_id, position)
        if split is None:
            return False
        old, new = split
        self._mirror("cow", int(old), int(new))
        self._cache = self._cow_jit(
            self._cache, jnp.asarray(old, jnp.int32),
            jnp.asarray(new, jnp.int32),
        )
        self._cow_splits += 1
        return True

    def _note_kv_err(self, err) -> None:
        """Fold one step's KV round-trip quantization error into the
        running max and publish the ``serve/kv_quant_err`` gauge when
        telemetry is active (host-plane: gauges cannot be set in-jit)."""
        self._kv_quant_err = max(self._kv_quant_err, float(err))
        from chainermn_tpu.observability import reporter as _reporter
        from chainermn_tpu.observability import spans as _spans

        if _spans.telemetry_active():
            rep = _reporter.get_reporter()
            if rep is not None:
                rep.gauge("serve/kv_quant_err", self._kv_quant_err)

    # -- speculative drafts --------------------------------------------
    def propose_draft(self, context, n_draft: int) -> List[int]:
        """Up to ``n_draft`` draft tokens continuing ``context`` from the
        resolved draft source — n-gram prompt lookup or the truncated
        draft model.  Either way a pure deterministic function of the
        context alone, so the exact-match acceptance downstream keeps
        streams bit-exact regardless of which source proposed."""
        if n_draft <= 0:
            return []
        if self.draft_model is not None:
            return self.draft_model.propose(context, n_draft)
        return _ngram_draft(context, n_draft)

    # -- sampling ------------------------------------------------------
    @staticmethod
    def sample(logits: np.ndarray, params: SamplingParams,
               position: int) -> int:
        """Sample one token from fp32 (vocab,) logits.  Greedy at
        ``temperature == 0`` (np.argmax — deterministic, first-max on
        ties).  Otherwise counter-based: the RNG is seeded from
        ``(seed, position)`` alone, so the draw does not depend on batch
        composition, scheduling order, or preemption history."""
        if params.temperature == 0.0:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / params.temperature
        if params.top_k:
            k = min(params.top_k, z.shape[-1])
            cutoff = np.partition(z, -k)[-k]
            z = np.where(z >= cutoff, z, -np.inf)
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        rng = np.random.default_rng((int(params.seed), int(position)))
        return int(rng.choice(p.shape[-1], p=p))

    # -- maintenance ---------------------------------------------------
    def defragment(self) -> int:
        """Compact the page pool (see :meth:`PagedKVCache.defragment`)
        and permute the device pages to match.  Returns the number of
        pages moved (0 = already compact, no device copy)."""
        perm = self.kv.defragment()
        if perm is None:
            return 0
        self._mirror("defrag", np.asarray(perm))
        iperm = jnp.asarray(perm)

        def permute(leaf):
            # every cache leaf is a page array: (n_blocks, bs, n_kv, d)
            return jnp.take(leaf, iperm, axis=0)

        self._cache = jax.tree.map(permute, self._cache)
        return int(self.kv._last_defrag_moves)

    def reset(self) -> None:
        """Drop every sequence and the prefix index (device pages are
        left as-is — unreachable without a table entry)."""
        for sid in self.kv.seq_ids():
            self.kv.free(sid)
        self.kv.drop_prefix_cache()

    # -- introspection -------------------------------------------------
    def stats(self) -> dict:
        """Occupancy + compile bookkeeping (the recompile-count test's
        surface, and the scheduler's gauge source)."""
        out = {
            "cache": self.kv.stats().as_dict(),
            "prefill_compiles": len(self._prefill_shapes),
            "decode_compiles": len(self._decode_shapes),
            "chunk_compiles": len(self._chunk_shapes),
            "prefill_shapes": sorted(self._prefill_shapes),
            "decode_shapes": sorted(self._decode_shapes),
            "chunk_shapes": sorted(self._chunk_shapes),
            "tokens_prefilled": self._tokens_prefilled,
            "tokens_decoded": self._tokens_decoded,
            "tokens_chunked": self._tokens_chunked,
            "tokens_prefix_cached": self._tokens_prefix_cached,
            "cow_splits": self._cow_splits,
        }
        # Quantized-KV keys only when the feature is on, so the default
        # stats shape (and everything golden-pinned to it) is unchanged.
        if self.kv_dtype is not None:
            out["kv_dtype"] = self.kv_dtype
            out["kv_quant_err"] = self._kv_quant_err
        # Same shape-stability rule for the new levers: keys appear only
        # when the feature is on.
        if self.draft_model is not None:
            out["draft_source"] = self.draft_source
            out["draft_layers"] = self.draft_model.n_layers
            out["draft_compiles"] = self.draft_model.compiles
        if self.prefill_chunk:
            out["prefill_chunk"] = self.prefill_chunk
        if self.sp:
            out["sp"] = self.sp
            out["sp_chunk_compiles"] = len(self._sp_shapes)
            out["sp_chunk_shapes"] = sorted(self._sp_shapes)
        if self._bucket_growths:
            # Lazily-grown ladder entries (== extra traces accepted on
            # this replica); absent until a growth actually happens so
            # the default stats shape is unchanged.
            out["bucket_growths"] = self._bucket_growths
        out["max_bucket"] = self._max_prefilled
        # Cross-check against jit's own cache where the API exists.
        for name, fn in (("prefill", self._prefill_jit),
                         ("decode", self._decode_jit),
                         ("chunk", self._chunk_jit)):
            try:
                out[f"{name}_jit_cache_size"] = fn._cache_size()
            except Exception:
                pass
        return out

    # -- convenience ---------------------------------------------------
    def generate(self, prompt, max_new_tokens: int,
                 sampling: Optional[SamplingParams] = None,
                 stop_token: Optional[int] = None) -> List[int]:
        """Single-request generation through the SAME prefill/decode
        machinery the scheduler drives — the sequential oracle the
        continuous-batching parity test compares against, and the
        simplest way to smoke-test an engine."""
        sp = sampling or SamplingParams()
        toks = list(np.asarray(prompt, np.int32).reshape(-1))
        L = len(toks)
        total = min(L + max_new_tokens, self.config.max_len)
        sid = object()
        self.kv.allocate(sid, L)
        try:
            logits = self.prefill(toks, sid)
            out: List[int] = []
            cur = L
            while cur < total:
                nxt = self.sample(logits, sp, cur)
                out.append(nxt)
                if stop_token is not None and nxt == stop_token:
                    break
                if cur + 1 >= total:
                    break
                self.kv.extend(sid, cur + 1)
                logits = self.decode([nxt], [sid], [cur])[0]
                cur += 1
            return out
        finally:
            self.kv.free(sid)
